"""Worked example: summarize a transcript three ways.

Mirrors the reference repo's self-demoing pattern (every module there has a
runnable ``__main__`` demo — SURVEY.md §3.4); this single script demos the
public API end to end:

    python examples/summarize_demo.py [transcript.json]

1. offline mock engine (no accelerator — the reference's no-API-key mode),
2. the same run with a custom map prompt + "video editor" reduce prompt
   (the bundled prompt assets),
3. the on-device JAX engine on whatever accelerator JAX finds
   (tiny random-weight model — swap in a preset + checkpoint for real use).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

from lmrs_tpu.config import ChunkConfig, EngineConfig, PipelineConfig
from lmrs_tpu.pipeline import TranscriptSummarizer
from lmrs_tpu.utils.logging import setup_logging

ASSETS = Path(__file__).parent.parent / "lmrs_tpu" / "prompts" / "assets"


def load_transcript() -> dict:
    if len(sys.argv) > 1:
        return json.loads(Path(sys.argv[1]).read_text())
    # tiny synthetic transcript so the demo runs standalone
    segs, t = [], 0.0
    for i in range(40):
        segs.append({"start": t, "end": t + 4.0, "speaker": f"SPEAKER_0{i % 2}",
                     "text": f"Item {i}: the team discussed milestone {i % 5} "
                             f"and agreed on next steps for workstream {i % 3}."})
        t += 4.5
    return {"segments": segs}


def banner(title: str, stats: dict) -> None:
    print(f"\n=== {title} " + "=" * max(0, 56 - len(title)))
    print(stats["summary"][:400])
    print(f"[chunks={stats['num_chunks']} tokens={stats['total_tokens_used']} "
          f"wall={stats['processing_time']:.2f}s]")


def main() -> int:
    setup_logging(quiet=True)
    transcript = load_transcript()

    # 1. offline mock mode
    s = TranscriptSummarizer(PipelineConfig(engine=EngineConfig(backend="mock")))
    banner("mock engine", s.summarize(transcript))

    # 2. custom prompts (map + video-editor reduce from the bundled assets)
    banner("custom prompts", s.summarize(
        transcript,
        prompt_file=str(ASSETS / "analytical_map.txt"),
        aggregator_prompt_file=str(ASSETS / "video_editor_reduce.txt"),
    ))

    # 3. on-device engine (tiny random-weight model; content-free output —
    #    pass model="gemma-2b" + EngineConfig(checkpoint_path=...) for real)
    s2 = TranscriptSummarizer(PipelineConfig(
        engine=EngineConfig(backend="jax", model="tiny", max_tokens=32),
        chunk=ChunkConfig(max_tokens_per_chunk=512, tokenizer="byte"),
    ))
    banner("jax engine (random weights)", s2.summarize(transcript))
    s2.shutdown()
    s.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
