"""SLO-aware routing A/B over a mock fleet (ISSUE 15 acceptance).

Two arms over the SAME traffic against N mock-backend lmrs-serve
instances behind a RouterEngine, with ONE host forced into a degraded
burn-rate state (its engine carries real per-request latency against a
tight TTFT objective, so the SLO engine derives ``warn`` from actual
samples — nothing is hard-coded):

* ``slo_off``: ``slo_route=False`` — today's load/health ordering;
* ``slo_routed``: the router reads each host's published ``/healthz``
  SLO state and demotes degraded hosts as a graded placement penalty
  (serving/router.py ``_targets``).

PASS gate: the degraded host's traffic share DROPS in the routed arm
while the two arms' outputs stay token-identical (placement never
changes text — the mock is deterministic per prompt), and the fleet
``GET /v1/usage`` per-tenant rollups sum to the router-reported totals
exactly (the ledger-conservation acceptance, fleet level).

CPU-only and fast (~seconds); the same flow is tier-1 gated in
tests/test_cost_slo.py.
"""

from __future__ import annotations

import _pathfix  # noqa: F401

import json
import sys
import time

from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.obs.slo import SLOEngine, SLOSpec
from lmrs_tpu.serving.router import RouterEngine
from lmrs_tpu.serving.server import EngineHTTPServer
from lmrs_tpu.utils.env import env_int

N_HOSTS = env_int("LMRS_SLO_AB_HOSTS", 3, lo=2, hi=8)
N_REQS = env_int("LMRS_SLO_AB_REQUESTS", 30, lo=8)
DEGRADED_LATENCY_S = 0.08
TTFT_TARGET_MS = 50.0  # degraded host burns ~1.6x -> warn, healthy ~0x


def mk_fleet() -> list[EngineHTTPServer]:
    """N mock hosts, host 0 degraded: real request latency against a
    tight TTFT p95 objective — its OWN samples put it in warn."""
    servers = []
    for i in range(N_HOSTS):
        eng = MockEngine(seed=0, latency_s=DEGRADED_LATENCY_S if i == 0
                         else 0.0)
        # identical objective on every host (the degraded one differs by
        # BEHAVIOR, not configuration); short windows so the A/B settles
        eng.slo = SLOEngine(
            enabled=True, fast_s=30.0, slow_s=30.0, hold_s=5.0,
            specs=(SLOSpec("ttft_p95_ms", "latency_p95", TTFT_TARGET_MS),))
        servers.append(EngineHTTPServer(eng, port=0))
    for s in servers:
        s.start_background()
    return servers


def mk_requests() -> list[GenerationRequest]:
    return [GenerationRequest(
        prompt=f"Chunk {i}: summarize this deterministic mock content "
               f"item number {i} carefully and completely.",
        request_id=i, temperature=0.0, max_new_tokens=48,
        tenant=f"team{i % 2}") for i in range(N_REQS)]


def run_arm(servers: list[EngineHTTPServer], routed: bool) -> dict:
    hosts = [f"127.0.0.1:{s.port}" for s in servers]
    router = RouterEngine(hosts, timeout_s=30.0, prefix_route=False,
                          slo_route=routed, summary_ttl_s=0.5)
    # warm-up: populate every host's SLO windows past the latency
    # min-sample guard (min_events samples per host) + the router's
    # summary cache (states publish through /healthz on the wave
    # cadence); the measured window starts at the per-host served
    # counts AFTER it
    for k in range(4 * N_HOSTS):
        router.generate_batch([GenerationRequest(
            prompt=f"warmup {k}", request_id=10_000 + k,
            temperature=0.0, max_new_tokens=8)])
        time.sleep(0.05)
    time.sleep(0.6)  # one summary TTL: states land in the cache
    served0 = {h.netloc: h.served for h in router.hosts}
    texts = {}
    for req in mk_requests():
        res = router.generate_batch([req])[0]
        assert res.error is None, res.error
        texts[req.prompt] = res.text
        time.sleep(0.02)
    served = {h.netloc: h.served - served0[h.netloc]
              for h in router.hosts}
    total = sum(served.values())
    em = router.engine_metrics()
    usage = router.usage_report()
    router.shutdown()
    degraded = hosts[0]
    return {
        "arm": "slo_routed" if routed else "slo_off",
        "served": served,
        "degraded_host": degraded,
        "degraded_share": round(served[degraded] / max(total, 1), 3),
        "slo_states": em["slo_route"]["states"],
        "penalized": em["slo_route"]["penalized"],
        "usage_totals": usage["totals"],
        "usage_tenants": {t: r.get("requests", 0)
                          for t, r in usage["tenants"].items()},
        "texts": texts,
        "usage_doc": usage,
    }


def main() -> int:
    servers_a = mk_fleet()
    off = run_arm(servers_a, routed=False)
    for s in servers_a:
        s.shutdown()
    servers_b = mk_fleet()
    routed = run_arm(servers_b, routed=True)
    for s in servers_b:
        s.shutdown()

    identical = off["texts"] == routed["texts"]
    # fleet-conservation acceptance: per-tenant rollups sum to totals
    u = routed["usage_doc"]
    tenant_sum = sum(r.get("device_seconds", 0.0)
                     for r in u["tenants"].values())
    conserved = abs(tenant_sum - u["totals"].get("device_seconds", 0.0)) \
        < 1e-9
    ok = (routed["degraded_share"] < off["degraded_share"]
          and identical and conserved and routed["penalized"] > 0)
    report = {
        "object": "ab_slo_route",
        "hosts": N_HOSTS, "requests": N_REQS,
        "degraded_latency_s": DEGRADED_LATENCY_S,
        "ttft_target_ms": TTFT_TARGET_MS,
        "arms": [{k: v for k, v in arm.items()
                  if k not in ("texts", "usage_doc")}
                 for arm in (off, routed)],
        "outputs_token_identical": identical,
        "usage_conserved": conserved,
        "status": "PASS" if ok else "FAIL",
    }
    print(json.dumps(report, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
