"""Per-row fixed-cost probe for the ragged decode kernel, in isolation.

Times ONE attention layer's kernel (no model around it) at bench-1b's
attention shape across a batch sweep, for the arms:

* walk       — ``paged_decode_pallas`` (page walk only, no RMW)
* fused      — ``paged_decode_pallas_fused`` (walk + RMW + cross-row pipeline)
* rpa        — ``ragged_spans_pallas`` at q_len=1 spans: the unified
               span program the scheduler now routes EVERY phase through
               (ISSUE 16), measured at its decode-shaped corner so the
               us/row fit is directly comparable against the retired
               fused arm it replaced (perf_sentry tracks the
               ``decode_row_us_rpa`` bench-detail column)
* walk_gG / fused_gG — the multi-row kernels at row_group=G (one pair per
               entry in LMRS_ROWCOST_GROUPS, default "2,4,8"): the
               group-size sweep behind EngineConfig.decode_row_group —
               pick the G where the us/row curve flattens (past that,
               groups only add padding waste at partial occupancy).  The
               walk arms isolate the grouped pipeline itself; the fused
               arms are what the serving path actually runs.

Kernel calls are chained inside one jitted ``fori_loop`` (output feeds
the next q, pools ride the carry — the decode-block scan's shape) and
timed by the shared LONG-minus-SHORT chain method
(lmrs_tpu.utils.perf_model.time_chain): the tunnel's ~100 ms fetch RTT
and the dispatch cost cancel exactly instead of polluting the fit (the
naive per-call timing here is ~97% RTT).
Run: python scripts/decode_rowcost.py
Env hooks: LMRS_ROWCOST_GROUPS (comma list, "" disables the group arms),
LMRS_ROWCOST_INTERPRET=1 (Pallas interpret mode — the CPU-only stand-in
harness: us/kernel numbers then measure the emulator and are only
meaningful RELATIVE to each other per arm, never absolutely).
"""

import _pathfix  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from lmrs_tpu.ops.paged_attention import (
    pack_spans,
    paged_decode_pallas,
    paged_decode_pallas_fused,
    ragged_spans_pallas,
)
from lmrs_tpu.utils.env import env_bool, env_list
from lmrs_tpu.utils.perf_model import time_chain

KH, NREP, HD, PS = 8, 2, 128, 512   # bench-1b attention shape
LIVE = 64
LO, HI = 64, 2048
REPS = 5
INTERPRET = env_bool("LMRS_ROWCOST_INTERPRET", False)


def make_chain(arm, iters, kn, vn, pt, kl, row_group=1, spans=None):
    @jax.jit
    def chain(q, kp, vp):
        def body(_, carry):
            q, kp, vp = carry
            if arm == "rpa":
                qs, ql = spans
                out, kp, vp = ragged_spans_pallas(
                    q, kn, vn, kp, vp, pt, kl, qs, ql,
                    interpret=INTERPRET)
            elif arm.startswith("walk"):
                out = paged_decode_pallas(q, kp, vp, pt, kl,
                                          interpret=INTERPRET,
                                          row_group=row_group)
            else:
                out, kp, vp = paged_decode_pallas_fused(
                    q, kn, vn, kp, vp, pt, kl, interpret=INTERPRET,
                    row_group=row_group)
            return (out.astype(q.dtype), kp, vp)

        return jax.lax.fori_loop(0, iters, body, (q, kp, vp))

    return chain


def main():
    rng = np.random.default_rng(0)
    lo, hi, reps = LO, HI, REPS
    if INTERPRET:  # emulator chains are ~1000x slower; keep the harness usable
        lo, hi, reps = 2, 8, 2
    batches = (4, 8) if INTERPRET else (8, 16, 24, 32)
    groups = [int(g) for g in env_list("LMRS_ROWCOST_GROUPS",
                                       ("2", "4", "8"))]
    arms = [("walk", 1), ("fused", 1), ("rpa", 1)]
    for g in groups:
        arms += [(f"walk_g{g}", g), (f"fused_g{g}", g)]
    results = {}
    for B in batches:
        P = B + 1
        q = jnp.asarray(rng.standard_normal((B, KH * NREP, HD)), jnp.bfloat16)
        kn = jnp.asarray(rng.standard_normal((B, KH, HD)), jnp.bfloat16)
        vn = jnp.asarray(rng.standard_normal((B, KH, HD)), jnp.bfloat16)
        kp = jnp.asarray(rng.standard_normal((P, KH, PS, HD)), jnp.bfloat16)
        vp = jnp.asarray(rng.standard_normal((P, KH, PS, HD)), jnp.bfloat16)
        pt = jnp.asarray(
            (1 + np.arange(B))[:, None], jnp.int32)  # one live page per row
        kl = jnp.full((B,), LIVE, jnp.int32)

        # span-shaped inputs for the rpa arm: B decode rows = B q_len=1
        # spans over a SPAN_QT-aligned flat token buffer (kernel reads
        # only each span's first row; the padding rows are walked but
        # never gathered — the cost being measured IS that padding tax)
        ql_np = np.ones((B,), np.int32)
        qs_np, total = pack_spans(ql_np)
        qf = jnp.zeros((total, KH * NREP, HD), jnp.bfloat16)
        qf = qf.at[jnp.asarray(qs_np)].set(q)
        knf = jnp.zeros((total, KH, HD), jnp.bfloat16)
        knf = knf.at[jnp.asarray(qs_np)].set(kn)
        vnf = jnp.zeros((total, KH, HD), jnp.bfloat16)
        vnf = vnf.at[jnp.asarray(qs_np)].set(vn)
        spans = (jnp.asarray(qs_np), jnp.asarray(ql_np))

        for arm, g in arms:
            def chain(iters, arm=arm, g=g):
                if arm == "rpa":
                    fn = make_chain(arm, iters, knf, vnf, pt, kl,
                                    spans=spans)
                    return lambda: fn(qf, kp, vp)[0]
                fn = make_chain(arm, iters, kn, vn, pt, kl, row_group=g)
                return lambda: fn(q, kp, vp)[0]

            us = time_chain(chain, lo, hi, reps) * 1e6
            results.setdefault(arm, []).append((B, us))
            print(f"B={B:3d} {arm:9s} {us:8.2f} us/kernel"
                  f"  ({us/B:6.2f} us/row)", flush=True)

    for arm, rows in results.items():
        bs = np.array([r[0] for r in rows], float)
        us = np.array([r[1] for r in rows], float)
        A = np.vstack([bs, np.ones_like(bs)]).T
        slope, icpt = np.linalg.lstsq(A, us, rcond=None)[0]
        print(f"{arm:9s}: {slope:6.3f} us/row + {icpt:6.1f} us launch")


if __name__ == "__main__":
    main()
