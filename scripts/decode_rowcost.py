"""Per-row fixed-cost probe for the ragged decode kernel, in isolation.

Times ONE attention layer's kernel (no model around it) at bench-1b's
attention shape across a batch sweep, for two arms:

* walk     — ``paged_decode_pallas`` (page walk only, no RMW)
* fused    — ``paged_decode_pallas_fused`` (walk + RMW + cross-row pipeline)

Kernel calls are chained inside one jitted ``fori_loop`` (output feeds
the next q, pools ride the carry — the decode-block scan's shape), and
the per-kernel time is the DIFFERENCE between a long and a short chain
divided by the iteration delta: the tunnel's ~100 ms fetch RTT and the
dispatch cost cancel exactly instead of polluting the fit (the naive
per-call timing here is ~97% RTT).
Run: python scripts/decode_rowcost.py
"""
import time

import _pathfix  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from lmrs_tpu.ops.paged_attention import (
    paged_decode_pallas,
    paged_decode_pallas_fused,
)

KH, NREP, HD, PS = 8, 2, 128, 512   # bench-1b attention shape
LIVE = 64
LO, HI = 64, 2048
REPS = 5


def make_chain(arm, iters, kn, vn, pt, kl):
    @jax.jit
    def chain(q, kp, vp):
        def body(_, carry):
            q, kp, vp = carry
            if arm == "walk":
                out = paged_decode_pallas(q, kp, vp, pt, kl)
            else:
                out, kp, vp = paged_decode_pallas_fused(
                    q, kn, vn, kp, vp, pt, kl)
            return (out.astype(q.dtype), kp, vp)

        return jax.lax.fori_loop(0, iters, body, (q, kp, vp))

    return chain


def main():
    rng = np.random.default_rng(0)
    results = {}
    for B in (8, 16, 24, 32):
        P = B + 1
        q = jnp.asarray(rng.standard_normal((B, KH * NREP, HD)), jnp.bfloat16)
        kn = jnp.asarray(rng.standard_normal((B, KH, HD)), jnp.bfloat16)
        vn = jnp.asarray(rng.standard_normal((B, KH, HD)), jnp.bfloat16)
        kp = jnp.asarray(rng.standard_normal((P, KH, PS, HD)), jnp.bfloat16)
        vp = jnp.asarray(rng.standard_normal((P, KH, PS, HD)), jnp.bfloat16)
        pt = jnp.asarray(
            (1 + np.arange(B))[:, None], jnp.int32)  # one live page per row
        kl = jnp.full((B,), LIVE, jnp.int32)

        for arm in ("walk", "fused"):
            walls = {}
            for iters in (LO, HI):
                fn = make_chain(arm, iters, kn, vn, pt, kl)
                out = fn(q, kp, vp)
                np.asarray(jax.device_get(out[0]))  # compile + settle
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.time()
                    out = fn(q, kp, vp)
                    np.asarray(jax.device_get(out[0]))
                    best = min(best, time.time() - t0)
                walls[iters] = best
            us = (walls[HI] - walls[LO]) / (HI - LO) * 1e6
            results.setdefault(arm, []).append((B, us))
            print(f"B={B:3d} {arm:6s} {us:8.2f} us/kernel", flush=True)

    for arm, rows in results.items():
        bs = np.array([r[0] for r in rows], float)
        us = np.array([r[1] for r in rows], float)
        A = np.vstack([bs, np.ones_like(bs)]).T
        slope, icpt = np.linalg.lstsq(A, us, rcond=None)[0]
        print(f"{arm:6s}: {slope:6.3f} us/row + {icpt:6.1f} us launch")


if __name__ == "__main__":
    main()
