"""Speculation with REAL acceptance (VERDICT r3 item 6, the close-the-file
measurement): random-weight models cannot accept drafts (ab_spec.py measures
pure overhead, 0.5x), so this script TRAINS a ~370M byte-level model on chip
on an extractive agenda-copy task — the canonical prompt-lookup win case
(summaries quoting their source verbatim; ops/speculative.py module doc) —
then runs the k=0 vs k=4 ABBA on held-out prompts through the production
continuous-batching engine with the ragged multi-token verify kernel.

The model is sized so decode is WEIGHT-STREAM-bound (~280 MB bf16/step at
B=24: the (1+k)/(1+a*k) weight-amortization mechanism has something to
amortize), unlike the in-tree tiny quality model (RTT-bound; docs/PERF.md
round 3).  Run on the real chip: python scripts/ab_spec_trained.py
"""
import _pathfix  # noqa: F401  (repo-root import shim)
import json
import tempfile
import time
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp
import optax

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.data.tokenizer import ByteTokenizer
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.models.transformer import init_params
from lmrs_tpu.training.cli import batches, load_examples
from lmrs_tpu.training.train import make_train_step
from lmrs_tpu.utils.logging import setup_logging

WORDS = ["alpha", "beta", "gamma", "delta", "omega", "sigma", "theta",
         "kappa", "lambda", "zeta"]


def copy_example(rng) -> dict:
    """Agenda with unmemorizable content (random ids): the only way to low
    loss is COPYING from the prompt — which is exactly what prompt-lookup
    drafting can draft."""
    n = int(rng.integers(6, 10))
    # word-only content: unmemorizable combinations (10^3 per line) force
    # real copying, but avoid random DIGIT strings — measured: digit spans
    # resist induction far longer than word spans (2200 steps: words copy,
    # digits still garbled), and a wrong digit derails the whole line's
    # draft chain
    lines = [f"[{m:02d}:00] {WORDS[rng.integers(0, 10)]} "
             f"{WORDS[rng.integers(0, 10)]} {WORDS[rng.integers(0, 10)]}"
             for m in range(n)]
    agenda = "\n".join(lines)
    return {"prompt": f"Repeat the agenda.\n{agenda}\nAgenda:",
            "summary": "\n" + agenda}


def main():
    setup_logging(quiet=True)
    # f32 (bf16 training diverged to NaN at this lr on the first attempt);
    # ~370M params = 1.5 GB f32 weights -> the decode step is genuinely
    # weight-stream-bound at B=24 (floor ~1.8 ms vs ~2.5 ms launch cost)
    cfg = ModelConfig(name="spec-370m", vocab_size=512, dim=1280,
                      n_layers=14, n_heads=10, n_kv_heads=5,
                      hidden_dim=5120, max_seq_len=1024, dtype="float32")

    rng = np.random.default_rng(0)
    train = [copy_example(rng) for _ in range(1500)]
    with tempfile.TemporaryDirectory() as td:
        p = Path(td) / "copy.jsonl"
        p.write_text("\n".join(json.dumps(e) for e in train))
        seqs, masks = load_examples(str(p), ByteTokenizer())

    params = init_params(cfg, jax.random.PRNGKey(0))
    steps = 1200
    sched = optax.warmup_cosine_decay_schedule(0.0, 3e-4, 60, steps, 6e-6)
    optimizer = optax.chain(optax.clip_by_global_norm(1.0),
                            optax.adamw(sched))
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, None, masked=True,
                              remat=True)  # 16 GB chip: f32 370M + adam needs it
    it = batches(seqs, masks, 4, 768, 0)
    t0 = time.time()
    for i in range(steps):
        t, m = next(it)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(t), jnp.asarray(m))
        if i % 100 == 0 or i == steps - 1:
            print(f"train step {i}: loss {float(loss):.4f} "
                  f"({time.time() - t0:.0f}s)", flush=True)
    if float(loss) > 0.1:
        print(f"WARNING: copy task not converged (loss {float(loss):.3f}); "
              "acceptance will undershoot")

    held = [copy_example(np.random.default_rng(10_000 + i)) for i in range(24)]

    def make_engine(k):
        return JaxEngine(
            EngineConfig(backend="jax", scheduler="continuous",
                         max_tokens=288, max_batch_slots=24, retry_delay=0.0,
                         seed=0, page_size=512, num_pages=1,
                         decode_block=120, prefill_chunk=4096,
                         speculate_k=k),
            cfg, params=params, tokenizer=ByteTokenizer())

    def wave(eng, tag):
        reqs = [GenerationRequest(prompt=ex["prompt"], request_id=i,
                                  temperature=0.0, max_new_tokens=288)
                for i, ex in enumerate(held)]
        t0 = time.time()
        out = eng.generate_batch(reqs)
        dt = time.time() - t0
        assert all(r.error is None for r in out)
        return dt, out

    engines = {0: make_engine(0), 4: make_engine(4)}
    outs = {}
    for k, e in engines.items():
        _, outs[k] = wave(e, f"warm{k}")  # compile + cache warm

    # copy fidelity: greedy output must actually BE the agenda (otherwise
    # acceptance is meaningless); exact-prefix tokens over the batch
    ok = sum(o.text.startswith(ex["summary"][:80])
             for ex, o in zip(held, outs[0]))
    print(f"copy fidelity: {ok}/24 rows reproduce the agenda prefix "
          f"(k=0 greedy)", flush=True)
    print("sample got :", repr(outs[0][0].text[:90]), flush=True)
    print("sample want:", repr(held[0]["summary"][:90]), flush=True)

    sums = {0: [], 4: []}
    for r in range(3):
        for k in (0, 4, 4, 0):
            dt, _ = wave(engines[k], f"{r}-{k}")
            sums[k].append(dt)
        print(f"round {r}: k=0 {np.mean(sums[0]):.2f}s  "
              f"k=4 {np.mean(sums[4]):.2f}s", flush=True)

    m0, m4 = np.mean(sums[0]), np.mean(sums[4])
    met = engines[4]._scheduler.metrics
    dec, acc = met["decode_tokens"], met["spec_accepted_tokens"]
    disp = met["decode_dispatches"]
    # verify steps = tokens / (1 + accepted-per-step); per-step acceptance
    a_hat = acc / max(dec - acc, 1)  # accepted drafts per verify step
    print(f"k=4 engine: {dec} tokens, {acc} accepted draft tokens, "
          f"{disp} dispatches -> mean accepted/verify-step = {a_hat:.2f}")
    pred = (1 + a_hat) / 1.09  # 1.09x = measured verify-kernel cost ratio
    print(f"speedup: measured {m0 / m4:.2f}x  "
          f"(weight-stream prediction (1+a)/1.09 = {pred:.2f}x)")
    verdict = ("WINS >= 1.2x — flip default ON for extractive workloads"
               if m0 / m4 >= 1.2 else "stays OFF")
    print(f"VERDICT: speculation {verdict}", flush=True)


if __name__ == "__main__":
    main()
