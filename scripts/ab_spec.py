"""Speculation overhead at bench-1b scale (random weights => ~zero draft
acceptance: this measures pure speculation cost; acceptance upside needs a
real checkpoint and is demonstrated separately on the trained tiny model).

Three engines: speculate_k in {0, 4, 8}; interleaved A B C C B A waves.
Run: python scripts/ab_spec.py
"""
import time

import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.utils.logging import setup_logging


def wave(engine, n, max_new, tag):
    rng = np.random.default_rng(hash(tag) % 2**31)
    reqs = [GenerationRequest(
        prompt=f"[{i:02d}:00] " + " ".join(
            f"word{rng.integers(0, 997)}" for _ in range(160)),
        request_id=i, temperature=0.3, max_new_tokens=max_new)
        for i in range(n)]
    t0 = time.time()
    out = engine.generate_batch(reqs)
    dt = time.time() - t0
    assert all(r.error is None for r in out)
    return dt


def main():
    setup_logging(quiet=True)
    model = model_preset("bench-1b")

    def make(k):
        return JaxEngine(EngineConfig(
            backend="jax", max_tokens=128, max_batch_slots=24,
            retry_delay=0.0, seed=0, page_size=512, num_pages=1,
            decode_block=128, prefill_chunk=4096, speculate_k=k), model)

    engines = {0: make(0), 4: make(4), 8: make(8)}
    n, max_new = 48, 128
    for k, e in engines.items():
        wave(e, n, max_new, f"warm{k}")

    sums = {k: [] for k in engines}
    for r in range(3):
        order = [0, 4, 8, 8, 4, 0]
        for k in order:
            dt = wave(engines[k], n, max_new, f"{r}-{k}-{len(sums[k])}")
            sums[k].append(dt)
        line = "  ".join(f"k={k}: {np.mean(v):.2f}s" for k, v in sums.items())
        print(f"round {r}: {line}", flush=True)
    for k, v in sums.items():
        acc = engines[k]._scheduler.metrics.get("spec_accepted_tokens", 0)
        print(f"k={k}: mean {np.mean(v):.2f}s  accepted={acc}")


if __name__ == "__main__":
    main()
