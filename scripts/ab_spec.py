"""Speculation overhead at bench-1b scale (random weights => ~zero draft
acceptance: this measures pure speculation cost; acceptance upside needs a
real checkpoint and is demonstrated separately on the trained tiny model).

Three engines: speculate_k in {0, 4, 8}; interleaved A B C C B A waves.
Run: python scripts/ab_spec.py
The spec arm takes the tree path (ISSUE 19) when LMRS_SPEC_TREE is
unset/1 and reports its accept/dispatch block; LMRS_SPEC_TREE=0 is the
linear-speculation A/B control for the same command line.
"""
import _pathfix  # noqa: F401  (repo-root import shim)
import time

import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.utils.logging import setup_logging

from _bench_common import wave


def main():
    setup_logging(quiet=True)
    model = model_preset("bench-1b")

    def make(k):
        return JaxEngine(EngineConfig(
            backend="jax", max_tokens=128, max_batch_slots=24,
            retry_delay=0.0, seed=0, page_size=512, num_pages=1,
            decode_block=128, prefill_chunk=4096, speculate_k=k), model)

    import sys
    spec_k = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    # pairwise (0 vs spec_k): three 1B engines OOM a 16 GB chip
    engines = {0: make(0), spec_k: make(spec_k)}
    n, max_new = 48, 128
    for k, e in engines.items():
        wave(e, n, max_new, f"warm{k}", words=(160, 161))

    # two workloads per round (VERDICT r3 decision protocol): high-entropy
    # prompts measure speculation's pure overhead; repetitive prompts are
    # the acceptance-rich case where it must show >= 1.2x to ship ON
    for rep, label in ((False, "high-entropy"), (True, "repetitive")):
        sums = {k: [] for k in engines}
        for r in range(3):
            order = [0, spec_k, spec_k, 0]
            for k in order:
                dt = wave(engines[k], n, max_new,
                          f"{label}-{r}-{k}-{len(sums[k])}",
                          words=(160, 161), repetitive=rep)
                sums[k].append(dt)
            line = "  ".join(f"k={k}: {np.mean(v):.2f}s"
                             for k, v in sums.items())
            print(f"[{label}] round {r}: {line}", flush=True)
        speedup = np.mean(sums[0]) / np.mean(sums[spec_k])
        for k, v in sums.items():
            sch = engines[k]._scheduler
            acc = sch.metrics.get("spec_accepted_tokens", 0)
            st = sch._spec_tree_report()
            tree = (f"  tree: accept/step={st['accept_per_step']}"
                    f" mean_depth={st['mean_accept_depth']}"
                    f" dispatches={st['dispatches']}"
                    if st["enabled"] else "")
            print(f"[{label}] k={k}: mean {np.mean(v):.2f}s  "
                  f"accepted={acc}{tree}")
        print(f"[{label}] speculation speedup: {speedup:.2f}x "
              f"({'WIN' if speedup >= 1.2 else 'keep OFF'})", flush=True)


if __name__ == "__main__":
    main()
