"""Prefix-aware routing A/B over a mock fleet (ROADMAP item 3 / ISSUE 12).

Two arms over the SAME traffic — a workload of requests sharing a handful
of system/map preambles, submitted in single-request waves so round-robin
placement genuinely scatters — against >= 2 mock-backend lmrs-serve
instances behind a RouterEngine:

* ``round_robin``: ``prefix_route=False`` — today's load/health ordering;
* ``routed``: prefix-aware placement (summary-predicted + rendezvous,
  docs/SERVING.md § routing policy) with a short summary TTL so the
  predicted path engages within the run.

Reported per arm: fleet-aggregate prefix hit rate and prefill-tokens-saved
(summed over the backends' ``/metrics`` prefix blocks — the mock's
deterministic emulation, same accounting surface as the jax scheduler),
per-host placement spread, router placement counters, and client-side
request latency percentiles (the mock generates instantly, so latency
deltas here measure routing overhead, not cache wins — the token savings
are the win; TTFT impact needs the chip arm, docs/PERF.md).

CPU-only and fast (~seconds); the identity guarantee (placement never
changes outputs) is tier-1 gated in tests/test_router.py.
"""

from __future__ import annotations

import _pathfix  # noqa: F401

import json
import time

import numpy as np

from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.serving.router import RouterEngine
from lmrs_tpu.serving.server import EngineHTTPServer
from lmrs_tpu.utils.env import env_int

N_HOSTS = env_int("LMRS_AB_HOSTS", 2, lo=2, hi=8)
N_REQS = env_int("LMRS_AB_REQUESTS", 24, lo=4)
N_PREAMBLES = env_int("LMRS_AB_PREAMBLES", 3, lo=1)

PREAMBLES = [
    ("You are summarizing one section of a much longer transcript. "
     f"Style {k}: keep every fact, decision, name, and number. ")
    * 3  # long enough that reuse dominates the per-chunk body
    for k in range(N_PREAMBLES)
]


def mk_requests() -> list[GenerationRequest]:
    rng = np.random.default_rng(7)
    out = []
    for i in range(N_REQS):
        pre = PREAMBLES[i % N_PREAMBLES]
        body = " ".join(f"w{rng.integers(0, 999)}" for _ in range(40))
        out.append(GenerationRequest(
            prompt=pre + f"Chunk {i}: {body}", request_id=i,
            system_prompt="Respond with the summary content only.",
            cache_prefix=len(pre), temperature=0.0, max_new_tokens=64))
    return out


def host_prefix_metrics(router: RouterEngine) -> list[dict]:
    per = []
    for row in router.engine_metrics()["per_host"]:
        eng = row.get("metrics", {}).get("engine", {})
        per.append({"host": row["host"], "served": row["served"],
                    **(eng.get("prefix_cache") or {})})
    return per


def run_arm(routed: bool) -> dict:
    servers = [EngineHTTPServer(MockEngine(seed=0), port=0)
               for _ in range(N_HOSTS)]
    for s in servers:
        s.start_background()
    router = RouterEngine([f"127.0.0.1:{s.port}" for s in servers],
                          timeout_s=30.0, prefix_route=routed,
                          summary_ttl_s=1.0)
    lat = []
    try:
        for req in mk_requests():
            t0 = time.time()
            res = router.generate_batch([req])[0]
            lat.append(time.time() - t0)
            assert res.error is None, res.error
            if routed:
                time.sleep(0.03)  # let summary fetches land between waves
        per = host_prefix_metrics(router)
        hits = sum(p.get("hits", 0) for p in per)
        queries = sum(p.get("queries", 0) for p in per)
        saved = sum(p.get("tokens_reused", 0) for p in per)
        lat_ms = sorted(x * 1e3 for x in lat)
        pct = lambda q: round(lat_ms[min(len(lat_ms) - 1,
                                         int(q * len(lat_ms)))], 2)
        return {
            "arm": "routed" if routed else "round_robin",
            "hosts": N_HOSTS,
            "requests": N_REQS,
            "preambles": N_PREAMBLES,
            "fleet_hit_rate": round(hits / queries, 3) if queries else 0.0,
            "fleet_hits": hits,
            "fleet_queries": queries,
            "prefill_tokens_saved": saved,
            "served_spread": sorted(p["served"] for p in per),
            "router_prefix_route":
                router.engine_metrics()["prefix_route"],
            "request_latency_ms": {"p50": pct(0.50), "p90": pct(0.90)},
        }
    finally:
        router.shutdown()
        for s in servers:
            s.shutdown()


def main() -> int:
    rr = run_arm(routed=False)
    ro = run_arm(routed=True)
    out = {
        "round_robin": rr,
        "routed": ro,
        "delta": {
            "fleet_hit_rate": round(
                ro["fleet_hit_rate"] - rr["fleet_hit_rate"], 3),
            "prefill_tokens_saved": (ro["prefill_tokens_saved"]
                                     - rr["prefill_tokens_saved"]),
        },
    }
    print(json.dumps(out, indent=2))
    ok = (ro["fleet_hit_rate"] >= rr["fleet_hit_rate"]
          and ro["prefill_tokens_saved"] >= rr["prefill_tokens_saved"])
    print(f"\nrouted hit rate {ro['fleet_hit_rate']} vs round-robin "
          f"{rr['fleet_hit_rate']}; tokens saved "
          f"{ro['prefill_tokens_saved']} vs {rr['prefill_tokens_saved']} "
          f"-> {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
