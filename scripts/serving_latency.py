"""Serving-config latency percentiles on the real chip (PERF round 5):
bench-1b int8 W+KV at decode_block=16 — the TTFT / per-block-gap numbers a
streaming client sees, from the scheduler's always-on samples.
LMRS_SERVE_MODEL overrides the preset (e.g. bench-8b)."""
import json, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.utils.env import env_str

MODEL = env_str("LMRS_SERVE_MODEL", "bench-1b")
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine

eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                             max_tokens=128, max_batch_slots=24, seed=0,
                             page_size=512, num_pages=1, decode_block=16,
                             prefill_chunk=4096, quantize="int8",
                             kv_quantize="int8", retry_delay=0.0),
                model_preset(MODEL))
rng = np.random.default_rng(0)
def mk(i, words):
    body = " ".join(f"w{rng.integers(0, 999)}" for _ in range(words))
    return GenerationRequest(prompt=body, request_id=i, temperature=0.3,
                            max_new_tokens=128)
# warmup compiles every shape the measured wave uses
eng.generate_batch([mk(i, 300) for i in range(24)])
sched = eng._scheduler
sched.reset_latency_stats()
m0 = dict(sched.metrics)
t0 = time.time()
out = eng.generate_batch([mk(100 + i, 300) for i in range(48)])
wall = time.time() - t0
rep = sched.metrics_report()
print(json.dumps({
    "config": MODEL
              + " int8 W+KV, decode_block=16, 24 slots, 48 reqs (~1.4k-token prompts)",
    "wall_s": round(wall, 2),
    "ttft_ms": rep["ttft_ms"],
    "decode_block_gap_ms": rep["decode_block_gap_ms"],
    "decode_dispatches": sched.metrics["decode_dispatches"] - m0["decode_dispatches"],
    "occupancy": round((sched.metrics["occupancy_sum"] - m0["occupancy_sum"]) /
                       max(sched.metrics["decode_dispatches"] - m0["decode_dispatches"], 1), 3),
    "failed": sum(r.error is not None for r in out),
}))
