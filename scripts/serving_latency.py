"""Serving-config latency percentiles on the real chip (PERF round 5),
with a mixed-batch on/off A/B arm (ISSUE 11): bench-1b int8 weights /
bf16 KV at decode_block=16 — the TTFT / per-block-gap numbers a
streaming client sees, from the scheduler's always-on samples, measured
with SARATHI mixed dispatch armed and disarmed over the SAME traffic.
(bf16 KV on purpose: int8 KV auto-disarms mixed dispatch — the mixed
arm would silently measure the alternating path; see run_arm.)

The A/B answers ROADMAP item 1's question directly: does decode cadence
continue through admission bursts (48 requests over 24 slots re-admit
continuously, so every slot turnover is an admission landing mid-decode)?
The mixed arm's block-gap tail should collapse toward its p50 — no
admission-correlated spike — while the off arm reproduces today's
alternating-wave gaps.  TTFT and gap percentile DELTAS are reported
alongside both arms' raw numbers.

Chip knobs: LMRS_SERVE_MODEL overrides the preset (e.g. bench-8b).
CPU/interpret smoke: LMRS_SERVE_MODEL=bench-smoke LMRS_SERVE_CPU=1 runs
the identical harness without int8 (the no-chip admission-interleave
demonstration CI quotes)."""
import json, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.utils.env import env_bool, env_str

MODEL = env_str("LMRS_SERVE_MODEL", "bench-1b")
CPU = env_bool("LMRS_SERVE_CPU", False)  # no int8: the mock/interpret arm
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine

rng = np.random.default_rng(0)
PROMPT_WORDS = 60 if CPU else 300
N_WARM = 8 if CPU else 24
N_MEAS = 16 if CPU else 48
SLOTS = 8 if CPU else 24


def mk(i, words):
    body = " ".join(f"w{rng.integers(0, 999)}" for _ in range(words))
    # STAGGERED budgets: uniform budgets finish whole waves together and
    # admissions then land on an idle batch (nothing to mix with); real
    # traffic staggers by EOS.  The spread keeps slots turning over while
    # neighbors decode — every admission is a mid-decode burst.
    budget = (8 + (i % 5) * 8) if CPU else (48 + (i % 5) * 24)
    return GenerationRequest(prompt=body, request_id=i, temperature=0.3,
                             max_new_tokens=budget)


DECODE_BLOCK = 8 if CPU else 16


def run_arm(mixed: bool) -> dict:
    # int8 WEIGHTS only: kv_quantize="int8" auto-disarms mixed dispatch
    # (a mixed chunk cannot own its slot's frozen prefill scales —
    # scheduler gate), so an int8-KV "mixed arm" would silently run the
    # alternating dispatch and the A/B would measure nothing.  Both arms
    # therefore run bf16 KV — apples to apples, and the bar in
    # docs/PERF.md is defined at this config.  bf16 KV doubles the page
    # bytes: at 8B shape budget the pool accordingly (num_pages=1 =
    # worst-case sizing still fits one v5e with the 2048 window).
    quant = {} if CPU else dict(quantize="int8")
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=48 if CPU else 168,
                                 max_batch_slots=SLOTS, seed=0,
                                 page_size=64 if CPU else 512,
                                 num_pages=1,
                                 decode_block=DECODE_BLOCK,
                                 prefill_chunk=4096, retry_delay=0.0,
                                 mixed_batch=mixed, **quant),
                    model_preset(MODEL))
    assert eng._scheduler._mixed == mixed, \
        "mixed arm disarmed itself — config incompatible with mixed dispatch"
    # warmup compiles every shape the measured wave uses (incl. the
    # bucketed mixed shapes on the mixed arm)
    eng.generate_batch([mk(i, PROMPT_WORDS) for i in range(N_WARM)])
    sched = eng._scheduler
    sched.reset_latency_stats()
    m0 = dict(sched.metrics)
    cost0 = sched._cost.report()
    an0 = sched.anatomy_snapshot()
    t0 = time.time()
    out = eng.generate_batch([mk(1000 + i, PROMPT_WORDS)
                              for i in range(N_MEAS)])
    wall = time.time() - t0
    rep = sched.metrics_report()
    m1 = sched.metrics
    arm = {
        "mixed": mixed,
        "wall_s": round(wall, 2),
        "ttft_ms": rep["ttft_ms"],
        # steady-state serving cadence: within-run dispatch gaps on live
        # traffic (NOT the batch-bench wave-level number — docs/PERF.md
        # "two block-gap numbers")
        "decode_block_gap_ms_steady_state": rep["decode_block_gap_ms"],
        "decode_dispatches": m1["decode_dispatches"] - m0["decode_dispatches"],
        "occupancy": round((m1["occupancy_sum"] - m0["occupancy_sum"]) /
                           max(m1["decode_dispatches"]
                               - m0["decode_dispatches"], 1), 3),
        # measured-window mixed stats (warmup's mixed dispatches excluded,
        # same windowing as decode_dispatches above)
        "mixed_batch": sched._mixed_report(m0),
        # measured-window ragged-span stats (same windowing)
        "rpa": sched._rpa_report(m0),
        # windowed cost/SLO attribution (ISSUE 15): per-tenant device-
        # seconds + goodput over the measured wave, and the burn-rate
        # state the wave left the host in — the A/B now reports WHO paid
        # for each arm's latency, not just the percentiles
        "cost": sched._cost.report(cost0),
        "slo": {"state": sched.slo_report().get("state", "ok")},
        # windowed step anatomy (ISSUE 18): host-segment split of the
        # measured wave + per-class p50/p95 — which microseconds between
        # dispatches each arm spends, not just how many.  Omitted (not
        # enabled:false) under LMRS_ANATOMY=0, wire-parity rule.
        **({"anatomy": sched.anatomy_report(an0)}
           if sched._an.enabled else {}),
        "failed": sum(r.error is not None for r in out),
    }
    eng.shutdown()
    return arm


def pct_delta(on: dict | None, off: dict | None) -> dict:
    if not on or not off:
        return {}
    return {p: round(on[p] - off[p], 1)
            for p in ("p50", "p90", "p99") if p in on and p in off}


off_arm = run_arm(False)
on_arm = run_arm(True)
print(json.dumps({
    "config": MODEL + (" cpu-smoke" if CPU else " int8 W, bf16 KV")
              + f", decode_block={DECODE_BLOCK}, {SLOTS} slots, "
              f"{N_MEAS} reqs (~{PROMPT_WORDS}-word prompts, staggered "
              "budgets), mixed A/B",
    "mixed_off": off_arm,
    "mixed_on": on_arm,
    # the ROADMAP item 1 numbers: negative = mixed is faster
    "delta_ms": {
        "ttft": pct_delta(on_arm["ttft_ms"], off_arm["ttft_ms"]),
        "decode_block_gap": pct_delta(
            on_arm["decode_block_gap_ms_steady_state"],
            off_arm["decode_block_gap_ms_steady_state"]),
    },
}, indent=1))
