"""Live-session incremental refresh A/B (ROADMAP item 5 / ISSUE 13).

The product scenario: a ~3-hour meeting transcript already summarized,
then ~5 minutes of new segments arrive and the summary refreshes.  Two
arms over the SAME grown transcript, both deviceless (SessionManager
over MockEngine — the mock's deterministic text + prefix-cache emulation
give the same accounting surface as the jax scheduler):

* ``full``: re-summarize from scratch — a FRESH session fed the grown
  transcript in one append (what every refresh would cost without the
  rolling state);
* ``incremental``: the live path — the warm session appends the 5
  minutes and refreshes, recomputing only the dirty tail chunks and the
  dirty reduce root path.

Reported: refresh-after-append wall clock, map chunks recomputed vs
reused, reduce nodes recomputed vs reused, and prompt tokens run through
the engine (the prefill-cost proxy; on a chip this is prefill work, here
it is the mock's token accounting).  PASS gate (ISSUE 13 acceptance):
the incremental arm reuses >= 90% of the grown tree's reduce nodes AND
its refreshed summary is byte-identical to the full arm's — incremental
must never trade correctness for latency.

CPU-only and fast (~seconds).  Knobs: LMRS_LIVE_AB_HOURS /
LMRS_LIVE_AB_APPEND_MIN (workload shape), LMRS_LIVE_AB_CHUNK_TOKENS
(chunk budget — smaller means a deeper tree).
"""

from __future__ import annotations

import _pathfix  # noqa: F401

import json
import random
import tempfile
import time

from lmrs_tpu.config import (ChunkConfig, EngineConfig, LiveConfig,
                             PipelineConfig, ReduceConfig)
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.live import SessionManager
from lmrs_tpu.utils.env import env_float, env_int

HOURS = env_float("LMRS_LIVE_AB_HOURS", 3.0, lo=0.1)
APPEND_MIN = env_float("LMRS_LIVE_AB_APPEND_MIN", 5.0, lo=0.5)
CHUNK_TOKENS = env_int("LMRS_LIVE_AB_CHUNK_TOKENS", 240, lo=120)

WORDS = ("the quarterly review covered the inference engine roadmap "
         "kernel design latency targets hiring plan budget allocation "
         "serving tier milestones decisions follow ups and the open "
         "questions everyone agreed to revisit next week").split()


def meeting_segments(seconds: float, seed: int = 11,
                     t0: float = 0.0) -> list[dict]:
    """Deterministic synthetic meeting audio: ~12s utterances, 2 speakers,
    ~2 words/second — a 3h meeting lands ~21k words (~28k approx tokens)."""
    rng = random.Random(f"{seed}:{t0}")
    segs = []
    t = t0
    while t < t0 + seconds:
        dur = 8.0 + rng.random() * 8.0
        n_words = int(dur * 2)
        text = " ".join(rng.choice(WORDS) for _ in range(n_words))
        segs.append({"start": round(t, 2), "end": round(t + dur, 2),
                     "text": text.capitalize() + ".",
                     "speaker": f"SPEAKER_{rng.randrange(2):02d}"})
        t += dur + 0.5
    return segs


def live_config() -> PipelineConfig:
    return PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=CHUNK_TOKENS,
                          overlap_tokens=0, context_tokens=60),
        engine=EngineConfig(backend="mock", temperature=0.0, max_tokens=64,
                            retry_delay=0.0),
        # arity 3 => a 3h transcript at this chunk budget forms a 4-level
        # tree with ~100+ nodes, so the dirty root path is a small slice
        reduce=ReduceConfig(max_summaries_per_batch=3),
        live=LiveConfig(class_default="bulk"))


class _CountingEngine:
    """Transparent wrapper counting the prompt tokens/requests an arm
    actually runs through the engine — the prefill-cost proxy (on a chip
    every counted token is prefill work; the radix cache then shaves the
    shared preambles off it on both arms equally)."""

    def __init__(self, inner: MockEngine):
        self._inner = inner
        self.prompt_tokens = 0
        self.requests = 0

    def generate_batch(self, requests, on_result=None, on_tokens=None):
        tok = self._inner._tok
        for r in requests:
            self.prompt_tokens += tok.count(r.prompt)
            self.requests += 1
        kw = {}
        if on_result is not None:
            kw["on_result"] = on_result
        if on_tokens is not None:
            kw["on_tokens"] = on_tokens
        return self._inner.generate_batch(requests, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def run() -> dict:
    base = meeting_segments(HOURS * 3600.0, seed=11, t0=0.0)
    tail = meeting_segments(APPEND_MIN * 60.0, seed=11,
                            t0=base[-1]["end"] + 0.5)
    cfg = live_config()

    # ---- warm the incremental arm on the base transcript
    with tempfile.TemporaryDirectory() as d:
        inc_engine = _CountingEngine(MockEngine(seed=0))
        inc = SessionManager(inc_engine, d, config=cfg)
        inc.create(session_id="live")
        inc.append("live", base, refresh=True)

        tokens0, reqs0 = inc_engine.prompt_tokens, inc_engine.requests
        t0 = time.time()
        doc = inc.append("live", tail, refresh=True)
        inc_wall = time.time() - t0
        r = doc["refresh"]
        inc_tokens = inc_engine.prompt_tokens - tokens0
        inc_reqs = inc_engine.requests - reqs0

    # ---- full arm: fresh session over the grown transcript
    with tempfile.TemporaryDirectory() as d:
        full_engine = _CountingEngine(MockEngine(seed=0))
        full = SessionManager(full_engine, d, config=cfg)
        full.create(session_id="cold")
        t0 = time.time()
        cold = full.append("cold", base + tail, refresh=True)["refresh"]
        full_wall = time.time() - t0
        full_tokens = full_engine.prompt_tokens
        full_reqs = full_engine.requests

    nodes_total = r["reduce_nodes_reused"] + r["reduce_nodes_computed"]
    node_reuse = r["reduce_nodes_reused"] / max(nodes_total, 1)
    identical = r["summary"] == cold["summary"]
    return {
        "workload": {
            "hours": HOURS, "append_minutes": APPEND_MIN,
            "segments": len(base) + len(tail),
            "chunks": r["num_chunks"], "reduce_levels": r["levels"],
        },
        "incremental": {
            "refresh_seconds": round(inc_wall, 3),
            "dirty_chunks": r["dirty_chunks"],
            "chunk_summaries_reused": r["chunk_summaries_reused"],
            "reduce_nodes_computed": r["reduce_nodes_computed"],
            "reduce_nodes_reused": r["reduce_nodes_reused"],
            "node_reuse_ratio": round(node_reuse, 3),
            "requests_run": inc_reqs,
            "prompt_tokens_run": inc_tokens,
        },
        "full": {
            "refresh_seconds": round(full_wall, 3),
            "chunks_computed": cold["num_chunks"],
            "reduce_nodes_computed": cold["reduce_nodes_computed"],
            "requests_run": full_reqs,
            "prompt_tokens_run": full_tokens,
        },
        "delta": {
            "speedup": round(full_wall / max(inc_wall, 1e-9), 2),
            "prompt_tokens_saved": full_tokens - inc_tokens,
            "tokens_saved_ratio": round(
                1.0 - inc_tokens / max(full_tokens, 1), 3),
        },
        "token_identical": identical,
    }


def main() -> int:
    out = run()
    print(json.dumps(out, indent=2))
    inc = out["incremental"]
    ok = (out["token_identical"] and inc["node_reuse_ratio"] >= 0.90)
    print(f"\nincremental: {inc['dirty_chunks']} dirty chunks, node reuse "
          f"{inc['node_reuse_ratio']:.1%}, {out['delta']['speedup']}x faster "
          f"than full; token-identical={out['token_identical']} "
          f"-> {'PASS' if ok else 'FAIL'} (gate: reuse >= 90% + identity)")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
