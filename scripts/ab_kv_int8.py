"""ABBA: int8 KV-cache pages vs bf16 at bench-1b scale (kv_quantize=int8,
both arms with int8 weights — the bench default).  Decode-heavy waves.
Run: python scripts/ab_kv_int8.py
"""
import _pathfix  # noqa: F401  (repo-root import shim)
import time

import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.utils.logging import setup_logging

from _bench_common import wave


def main():
    setup_logging(quiet=True)
    model = model_preset("bench-1b")

    def make(kv):
        return JaxEngine(EngineConfig(
            backend="jax", max_tokens=128, max_batch_slots=24,
            retry_delay=0.0, seed=0, page_size=512, num_pages=1,
            decode_block=128, prefill_chunk=4096, quantize="int8",
            kv_quantize=kv), model)

    engines = {"bf16kv": make(None), "int8kv": make("int8")}
    n, max_new = 48, 128
    for name, e in engines.items():
        wave(e, n, max_new, f"warm-{name}", words=(160, 161))
    sums = {k: [] for k in engines}
    for r in range(3):
        for name in ["bf16kv", "int8kv", "int8kv", "bf16kv"]:
            dt = wave(engines[name], n, max_new,
                      f"r{r}-{name}-{len(sums[name])}", words=(160, 161))
            sums[name].append(dt)
        line = "  ".join(f"{k}={np.mean(v):.2f}s" for k, v in sums.items())
        print(f"round {r}: {line}", flush=True)
    a, b = np.mean(sums["bf16kv"]), np.mean(sums["int8kv"])
    print(f"MEAN bf16kv={a:.2f}s int8kv={b:.2f}s  "
          f"int8kv {'wins' if b < a else 'LOSES'} {abs(1 - a/b)*100:+.1f}%")


if __name__ == "__main__":
    main()
