"""Where does the decode step spend its 15 ms?  Sweep live context at
bench-1b scale: per-step time vs live tokens separates the weight-stream
cost (intercept) from the KV-walk cost (slope).
Run: python scripts/decode_split.py
Env hooks: LMRS_SPLIT_MODEL (preset, default bench-1b),
LMRS_SPLIT_QUANT=int8 (int8 weights+KV, e.g. the bench-8b arm),
LMRS_SPLIT_PS (page_size, default 512),
LMRS_SPLIT_GROUP (decode_row_group, default 4; LMRS_MULTIROW=0 is the
per-row A/B control — the refreshed-intercept measurement for the
multi-row page walk is this script run with both settings),
LMRS_SPLIT_RPA=1 (sweep the unified ragged-span program — q_len=1 spans
through scheduler._get_rpa_fn — instead of the legacy decode-block fn:
the ISSUE-16 A/B is this script run with both settings; note the span
arm dispatches one step per call where the legacy arm scans
decode_block steps in-graph, so the intercept carries the per-dispatch
host cost the decode-block scan amortizes),
LMRS_SPLIT_ANATOMY=1 (ISSUE 18: instead of the raw-dispatch sweep, run
REAL scheduler-loop traffic through three step-class arms — plain
decode / mixed / spec-verify — and print each class's host-segment
p50/p95 split from the step-anatomy profiler, i.e. the 3x spec-step
mystery as named segments; runs on CPU with a tiny model),
LMRS_SPLIT_SPEC_TREE=1 (ISSUE 19: real scheduler-loop traffic on a
repetitive workload through three speculation arms — off / linear
(LMRS_SPEC_TREE=0) / tree — reporting accepted tokens per dispatched
row, the draft segment's host time (tree drafting is fused on-device,
so its draft segment must collapse vs linear's host n-gram scan) and
tok/s; runs on CPU with a tiny model).
"""
import json
import time


import _pathfix  # noqa: F401  (repo-root import shim)
import jax
import jax.numpy as jnp
import numpy as np

from lmrs_tpu.config import EngineConfig, ModelConfig, model_preset
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.utils.logging import setup_logging
from lmrs_tpu.utils.perf_model import decode_step_bytes, weight_bytes
from lmrs_tpu.utils.env import env_bool, env_int, env_str


def anatomy_main():
    """The LMRS_SPLIT_ANATOMY arm: host-segment p50/p95 per step class
    through the live scheduler loop (obs/anatomy.py)."""
    from lmrs_tpu.engine.api import GenerationRequest

    setup_logging(quiet=True)
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     dtype="float32")
    out = {}
    for arm, kw in (("plain", dict(mixed_batch=False)),
                    ("mixed", dict(mixed_batch=True)),
                    ("spec", dict(mixed_batch=False, speculate_k=4))):
        eng = JaxEngine(EngineConfig(
            backend="jax", scheduler="continuous", max_tokens=24,
            max_batch_slots=4, seed=0, decode_block=4, prefill_chunk=64,
            retry_delay=0.0, **kw), mc)
        sched = eng._scheduler
        reqs = [GenerationRequest(
            prompt="anatomy probe " * (3 + 4 * (i % 3)), request_id=i,
            temperature=0.0, max_new_tokens=12 + 4 * (i % 3))
            for i in range(8)]
        eng.generate_batch(reqs)  # warmup: compiles every shape
        an0 = sched.anatomy_snapshot()
        eng.generate_batch([mk_r for mk_r in (
            GenerationRequest(prompt="anatomy probe " * (3 + 4 * (i % 3)),
                              request_id=100 + i, temperature=0.0,
                              max_new_tokens=12 + 4 * (i % 3))
            for i in range(8))])
        rep = sched.anatomy_report(an0)
        assert sched.audit() == [], "anatomy conservation violated"
        out[arm] = {
            "host_overhead_us_step": rep.get("host_overhead_us_step"),
            "segments_ms": rep.get("segments_ms"),
            "classes": rep.get("classes"),
            "buckets": rep.get("buckets"),
            "rpa_pad_waste_ratio": rep.get("rpa_pad_waste_ratio"),
        }
        eng.shutdown()
    print(json.dumps(out, indent=1))


def spec_tree_main():
    """The LMRS_SPLIT_SPEC_TREE arm (ISSUE 19): speculation A/B/C through
    the live scheduler loop — accepted tokens/step, draft host time,
    tok/s.  The workload repeats itself so the n-gram draft has signal;
    the tree arm must match or beat linear acceptance while its draft
    segment collapses to dispatch-only."""
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.utils.env import env_override

    setup_logging(quiet=True)
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                     dtype="float32")
    out = {}
    for arm, k, tree in (("off", 0, "0"), ("linear", 4, "0"),
                         ("tree", 4, "1")):
        # the gate is read once at scheduler construction, so flipping
        # the env per engine gives all three arms in one process
        with env_override("LMRS_SPEC_TREE", tree):
            eng = JaxEngine(EngineConfig(
                backend="jax", scheduler="continuous", max_tokens=64,
                max_batch_slots=4, seed=0, decode_block=4,
                prefill_chunk=64, retry_delay=0.0, speculate_k=k), mc)
        sched = eng._scheduler

        def reqs(base):
            # repetitive prompt: the acceptance-rich case (summaries
            # quoting their source) — greedy, so arms are comparable
            return [GenerationRequest(
                prompt="the quick brown fox jumps over the lazy dog. " * 6,
                request_id=base + i, temperature=0.0, max_new_tokens=48)
                for i in range(8)]

        eng.generate_batch(reqs(0))  # warmup: compiles every shape
        an0 = sched.anatomy_snapshot()
        m0 = sched.metrics
        t0 = time.time()
        res = eng.generate_batch(reqs(100))
        wall = time.time() - t0
        rep = sched.anatomy_report(an0)
        st = sched._spec_tree_report(m0)
        assert sched.audit() == [], "span/page accounting violated"
        spec_cls = (rep.get("classes") or {}).get("spec") or {}
        toks = sum(r.completion_tokens for r in res)
        out[arm] = {
            "tok_s": round(toks / wall, 1),
            "accepted_tokens": (sched.metrics["spec_accepted_tokens"]
                                - m0["spec_accepted_tokens"]),
            "accept_per_step": st["accept_per_step"],
            "mean_accept_depth": st["mean_accept_depth"],
            "tree_dispatches": st["dispatches"],
            "draft_ms_total": (rep.get("segments_ms") or {}).get("draft"),
            "draft_p50_us_spec_step": (spec_cls.get("p50_us")
                                       or {}).get("draft"),
        }
        eng.shutdown()
    print(json.dumps(out, indent=1))
    lin_d = out["linear"]["draft_ms_total"] or 0.0
    tree_d = out["tree"]["draft_ms_total"] or 0.0
    print(f"draft host-ms: linear={lin_d} tree={tree_d} "
          f"({'COLLAPSED' if tree_d <= lin_d else 'REGRESSION'}); "
          f"accept/step: linear={out['linear']['accept_per_step']} "
          f"tree={out['tree']['accept_per_step']}", flush=True)


def main():
    setup_logging(quiet=True)
    model = model_preset(env_str("LMRS_SPLIT_MODEL", "bench-1b"))
    quant = env_str("LMRS_SPLIT_QUANT")
    eng = JaxEngine(EngineConfig(
        backend="jax", max_tokens=128, max_batch_slots=24,
        retry_delay=0.0, seed=0,
        page_size=env_int("LMRS_SPLIT_PS", 512, lo=8), num_pages=1,
        decode_block=128, prefill_chunk=4096, tokenizer="byte",
        decode_row_group=env_int("LMRS_SPLIT_GROUP", 4, lo=1),
        quantize=quant or None, kv_quantize=quant or None), model)
    sched = eng._scheduler
    print(f"decode_row_group={sched._row_group} "
          f"(LMRS_MULTIROW={'0 (per-row control)' if sched._row_group == 1 else 'on'})",
          flush=True)
    rng = np.random.default_rng(0)
    B, S = sched.B, model.max_seq_len
    w = sched.cache.max_pages_per_slot
    rpa = env_bool("LMRS_SPLIT_RPA", False)
    if rpa:
        from lmrs_tpu.engine.scheduler import _pow2_bucket
        from lmrs_tpu.ops.paged_attention import pack_spans

        qs_np, total = pack_spans(np.ones((B,), np.int32))
        tpb = _pow2_bucket(total, 16)
        rfn = sched._get_rpa_fn(tpb, w)
        print(f"arm=rpa token_bucket={tpb} window={w}", flush=True)
    else:
        dfn = sched._get_decode_fn(w)

    x = jnp.zeros((8,), jnp.float32)
    np.asarray(jax.device_get(x + 1))
    t0 = time.time(); np.asarray(jax.device_get(x + 1)); rtt = time.time() - t0

    seqs = [sched.cache.open_sequence(S) for _ in range(B)]
    table = jnp.asarray(sched.cache.page_table_array(seqs)[:, :w])
    onesB = jnp.ones((B,), jnp.float32)
    results = []
    for live in (64, 512, 1024, 1536, 1920):
        if rpa:
            # one q_len=1 span per row through the unified program; each
            # call is ONE decode step, so chain decode_block of them
            # async and sync once — the legacy arm's in-graph scan, done
            # at the dispatch layer
            tokens = jnp.zeros((1, tpb), jnp.int32).at[0, jnp.asarray(
                qs_np)].set(jnp.asarray(
                    rng.integers(1, 255, (B,), dtype=np.int32)))
            row_flat = jnp.full((tpb,), B, jnp.int32).at[jnp.asarray(
                qs_np)].set(jnp.arange(B, dtype=jnp.int32))
            rargs = (jnp.arange(B, dtype=jnp.int32), tokens,
                     jnp.asarray(qs_np), jnp.ones((B,), jnp.int32),
                     row_flat, jnp.full((B,), live, jnp.int32),
                     jnp.asarray(qs_np), table, jax.random.PRNGKey(8),
                     onesB, jnp.zeros((B,), jnp.int32), onesB)
            k, v, ks, vs = (sched.cache.k, sched.cache.v, sched.kscale,
                            sched.vscale)
            nxt, k, v, ks, vs = rfn(sched.params, k, v, ks, vs, *rargs)
            np.asarray(jax.device_get(nxt))
            t0 = time.time()
            for _ in range(3 * sched.decode_block):
                nxt, k, v, ks, vs = rfn(sched.params, k, v, ks, vs,
                                        *rargs)
            np.asarray(jax.device_get(nxt))
            wall = time.time() - t0 - rtt
            sched.cache.k, sched.cache.v = k, v
            sched.kscale, sched.vscale = ks, vs
            per_step = wall / (3 * sched.decode_block)
            gb = decode_step_bytes(model, B * live, quantized=bool(quant),
                                   kv_quantized=bool(quant)) / 1e9
            results.append((live, per_step, gb))
            print(f"live={live:5d}  {per_step*1e3:7.3f} ms/step  "
                  f"{gb:5.2f} GB/step  {gb/per_step:6.0f} GB/s",
                  flush=True)
            continue
        dargs = (jnp.asarray(rng.integers(1, 255, (B,), dtype=np.int32)),
                 jnp.full((B,), live, jnp.int32), table,
                 jnp.ones((B,), bool), jax.random.PRNGKey(8), onesB,
                 jnp.zeros((B,), jnp.int32), onesB)
        k, v = sched.cache.k, sched.cache.v
        toks, n_valid, k, v = dfn(sched.params, k, v, sched.kscale,
                          sched.vscale, None, *dargs)
        np.asarray(jax.device_get(n_valid))
        t0 = time.time()
        for _ in range(3):
            toks, n_valid, k, v = dfn(sched.params, k, v, sched.kscale,
                          sched.vscale, None, *dargs)
        np.asarray(jax.device_get(n_valid))
        wall = time.time() - t0 - rtt
        sched.cache.k, sched.cache.v = k, v
        per_step = wall / (3 * sched.decode_block)
        gb = decode_step_bytes(model, B * live, quantized=bool(quant),
                               kv_quantized=bool(quant)) / 1e9
        results.append((live, per_step, gb))
        print(f"live={live:5d}  {per_step*1e3:7.3f} ms/step  "
              f"{gb:5.2f} GB/step  {gb/per_step:6.0f} GB/s", flush=True)
    # linear fit: intercept = weight+fixed cost, slope = per-KV-token cost
    lv = np.array([r[0] for r in results], float)
    ms = np.array([r[1] for r in results], float) * 1e3
    A = np.vstack([lv, np.ones_like(lv)]).T
    slope, intercept = np.linalg.lstsq(A, ms, rcond=None)[0]
    wgb = weight_bytes(model, quantized=bool(quant))
    # per-token KV bytes via the perf model's own halving rule (one source
    # of truth with the GB/step column above)
    kvgb = B * (decode_step_bytes(model, 1, quantized=bool(quant),
                                  kv_quantized=bool(quant)) - wgb) / 1e9
    print(f"fit: intercept {intercept:.2f} ms (weights {wgb/1e9:.2f} GB "
          f"-> floor {wgb/819e9*1e3:.2f} ms), "
          f"slope {slope*1e3:.3f} us/live-token "
          f"(KV floor {kvgb/819*1e6:.3f} us/token)")
    for s_ in seqs:
        sched.cache.close_sequence(s_)


if __name__ == "__main__":
    if env_bool("LMRS_SPLIT_SPEC_TREE", False):
        spec_tree_main()
    elif env_bool("LMRS_SPLIT_ANATOMY", False):
        anatomy_main()
    else:
        main()
