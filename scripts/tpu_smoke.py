"""TPU correctness smoke: <60 s on one chip, the FIRST thing to run on real
hardware (VERDICT r2 item 7).

The pytest suite exercises the Pallas kernels in interpret mode only
(LMRS_FORCE_KERNELS=interpret); the Mosaic codegen paths — the 8-row aligned
RMW in the fused decode write, SMEM page-table walks, the cross-head DMA
pipeline — lower only on hardware, so a driver bench that fails for
environmental reasons would otherwise mask a kernel regression.  This script
is the cheap hardware-parity artifact:

1. flash prefill vs the XLA reference (``ops.attention.attention``), ragged
   lengths, bf16;
2. packed segment-masked prefill vs ``packed_attention``;
3. fused ragged paged decode (in-kernel kv-head fold + in-place K/V write)
   vs scatter + ``paged_decode_xla``;
4. an int8-quantized forward (weights-only quant through ``forward``) —
   finite logits, deq path lowered on hardware.

Exit 0 = all pass.  Prints one line per check + a final JSON summary.
``--interpret`` runs the same checks in interpret mode (CI keeps the script
itself from rotting; hardware is the point).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import _pathfix  # noqa: F401  (repo-root import shim)

import jax
import jax.numpy as jnp
import numpy as np


def _maxdiff(a, b) -> float:
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


def check_flash_prefill(interpret: bool) -> float:
    """Flash kernel vs XLA reference on ragged bf16 prefill."""
    from lmrs_tpu.ops.attention import attention
    from lmrs_tpu.ops.flash_attention import flash_attention

    b, s, h, kh, hd = 2, 512, 8, 4, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, kh, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, kh, hd)), jnp.bfloat16)
    lengths = jnp.asarray([s, 300], jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))

    got = flash_attention(q, k, v, lengths, interpret=interpret)
    want = attention(q, k, v, positions, lengths)
    # compare valid rows only (flash zeroes padded-q rows by design)
    row_ok = positions < lengths[:, None]
    got = jnp.where(row_ok[..., None, None], got, 0)
    want = jnp.where(row_ok[..., None, None], want, 0)
    return _maxdiff(got, want)


def check_packed_prefill(interpret: bool) -> float:
    """Segment-masked flash vs the packed XLA reference."""
    from lmrs_tpu.ops.attention import packed_attention
    from lmrs_tpu.ops.flash_attention import flash_attention

    b, s, h, kh, hd = 1, 512, 8, 4, 128
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, kh, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, kh, hd)), jnp.bfloat16)
    # three packed segments + padded tail
    seg = np.full((b, s), -1, np.int32)
    seg[0, :200] = 0
    seg[0, 200:330] = 1
    seg[0, 330:470] = 2
    seg_ids = jnp.asarray(seg)
    lengths = jnp.asarray([470], jnp.int32)

    got = flash_attention(q, k, v, lengths, interpret=interpret,
                          segment_ids=seg_ids)
    want = packed_attention(q, k, v, seg_ids, lengths)
    valid = (seg_ids >= 0)[..., None, None]
    return _maxdiff(jnp.where(valid, got, 0), jnp.where(valid, want, 0))


def check_fused_ragged_decode(interpret: bool) -> float:
    """Write-fused ragged decode (one program per batch row, kv heads folded
    in-kernel) vs XLA scatter + gather reference, ragged lengths spanning
    page boundaries and the 8-row RMW window."""
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_pallas_fused, paged_decode_xla)

    b, h, kh, hd, ps, n_pages = 3, 8, 4, 128, 128, 16
    w = 4  # pages per row window
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((b, h, hd)), jnp.bfloat16)
    k_new = jnp.asarray(rng.standard_normal((b, kh, hd)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((b, kh, hd)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((n_pages, kh, ps, hd)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((n_pages, kh, ps, hd)), jnp.bfloat16)
    # distinct pages per row; page 0 reserved as the null page
    tables = jnp.asarray(1 + np.arange(b * w).reshape(b, w), jnp.int32)
    # lengths: first-page partial / exact page boundary / mid window + odd
    # offset (exercises the non-8-aligned row inside the RMW window)
    kv_lens = jnp.asarray([5, ps, 2 * ps + 77], jnp.int32)

    got, kp_out, vp_out = paged_decode_pallas_fused(
        q, k_new, v_new, kp, vp, tables, kv_lens, interpret=interpret)

    # reference: scatter the new token, then gather-attend
    pos = np.asarray(kv_lens) - 1
    kp_ref, vp_ref = np.asarray(kp, np.float32), np.asarray(vp, np.float32)
    for i in range(b):
        page = int(np.asarray(tables)[i, pos[i] // ps])
        kp_ref[page, :, pos[i] % ps] = np.asarray(k_new, np.float32)[i]
        vp_ref[page, :, pos[i] % ps] = np.asarray(v_new, np.float32)[i]
    kp_ref = jnp.asarray(kp_ref, jnp.bfloat16)
    vp_ref = jnp.asarray(vp_ref, jnp.bfloat16)
    want = paged_decode_xla(q, kp_ref, vp_ref, tables, kv_lens)

    d = _maxdiff(got, want)
    # the in-place write must also land exactly (pool parity at the touched
    # slots — only compare allocated pages; untouched pages must be intact)
    d = max(d, _maxdiff(kp_out[1:1 + b * w], kp_ref[1:1 + b * w]))
    d = max(d, _maxdiff(vp_out[1:1 + b * w], vp_ref[1:1 + b * w]))
    return d


def check_multi_token_verify(interpret: bool) -> float:
    """Ragged multi-token verify (speculative decode) vs the XLA
    scatter+gather reference, spans straddling page and RMW-window
    boundaries."""
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla, paged_decode_pallas_multi)

    b, t, h, kh, hd, ps, n_pages = 2, 5, 8, 4, 128, 128, 12
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.standard_normal((b, t, h, hd)), jnp.bfloat16)
    k_new = jnp.asarray(rng.standard_normal((b, t, kh, hd)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((b, t, kh, hd)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((n_pages, kh, ps, hd)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((n_pages, kh, ps, hd)), jnp.bfloat16)
    tables = jnp.asarray(1 + np.arange(b * 3).reshape(b, 3), jnp.int32)
    kv_lens = jnp.asarray([ps + 2, 131], jnp.int32)  # page + window straddles

    want, k_ref, v_ref = paged_decode_multi_xla(
        q, k_new, v_new, kp, vp, tables, kv_lens)
    got, k_out, v_out = paged_decode_pallas_multi(
        q, k_new, v_new, kp, vp, tables, kv_lens, interpret=interpret)
    d = _maxdiff(got, want)
    d = max(d, _maxdiff(k_out[1:1 + b * 3], k_ref[1:1 + b * 3]))
    return max(d, _maxdiff(v_out[1:1 + b * 3], v_ref[1:1 + b * 3]))


def check_int8_forward() -> float:
    """Weights-only int8 through the full forward: finite logits, and
    close to the bf16 forward within quantization error."""
    from lmrs_tpu.config import ModelConfig
    from lmrs_tpu.models.transformer import forward, init_params
    from lmrs_tpu.ops.quant import quantize_params

    cfg = ModelConfig(vocab_size=512, dim=256, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=512, max_seq_len=256,
                      dtype="bfloat16")
    params = init_params(cfg, jax.random.PRNGKey(3))
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(1, 255, (1, 128)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(128)[None], (1, 128))
    base, _ = forward(params, cfg, tokens, positions)
    q8, _ = forward(quantize_params(params), cfg, tokens, positions)
    assert bool(jnp.all(jnp.isfinite(q8))), "int8 forward produced non-finite"
    # int8 weight error compounds over layers; this is a lowering check,
    # not a numerics gate — just require the outputs to be correlated
    corr = float(jnp.corrcoef(base.ravel(), q8.ravel())[0, 1])
    assert corr > 0.98, f"int8 forward decorrelated from bf16 (corr={corr:.3f})"
    return 1.0 - corr


def check_int8_kv_decode(interpret: bool) -> float:
    """Int8 KV pools through the dequantizing fused decode kernel (32-row
    RMW windows, q/acc-folded per-channel dequant) vs the int8 XLA
    scatter+gather path — the r3 kv_quantize hardware check."""
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_pallas_fused, paged_decode_xla)
    from lmrs_tpu.ops.quant import kv_quant

    rng = np.random.default_rng(9)
    B, H, K, hd, ps, P, W = 8, 16, 8, 128, 512, 40, 4
    kq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    tables = jnp.asarray(
        rng.permutation(P - 1)[: B * W].reshape(B, W) + 1, jnp.int32)
    lens = jnp.asarray(rng.integers(33, W * ps, (B,)), jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, hd)), jnp.bfloat16)
    kn = jnp.asarray(rng.standard_normal((B, K, hd)), jnp.bfloat16)
    vn = jnp.asarray(rng.standard_normal((B, K, hd)), jnp.bfloat16)
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)

    got, kq1, vq1 = paged_decode_pallas_fused(
        q, kn, vn, kq, vq, tables, lens, interpret=interpret,
        kscale=ks, vscale=vs)
    pos = lens - 1
    page = jnp.take_along_axis(tables, (pos // ps)[:, None], 1)[:, 0]
    off = pos % ps
    kq_ref = kq.at[page, :, off].set(
        kv_quant(kn[:, None].astype(jnp.float32), ks)[:, 0])
    vq_ref = vq.at[page, :, off].set(
        kv_quant(vn[:, None].astype(jnp.float32), vs)[:, 0])
    want = paged_decode_xla(q, kq_ref, vq_ref, tables, lens,
                            kv_scales=(ks, vs))
    wdiff = int(jnp.sum(kq1 != kq_ref)) + int(jnp.sum(vq1 != vq_ref))
    assert wdiff == 0, f"{wdiff} pool bytes differ from the XLA scatter"
    return _maxdiff(got, want)


def check_int8_multi_verify(interpret: bool) -> float:
    """Int8 pools through the dequantizing MULTI-token verify kernel
    (speculation × int8 KV, r5: the r4 construction gate fell) vs the
    int8 XLA multi path — same span-straddling shapes as the bf16 multi
    check, 32-row RMW windows, frozen per-channel scales."""
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla, paged_decode_pallas_multi)

    b, t, h, kh, hd, ps, n_pages = 2, 5, 8, 4, 128, 128, 12
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((b, t, h, hd)), jnp.bfloat16)
    k_new = jnp.asarray(rng.standard_normal((b, t, kh, hd)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((b, t, kh, hd)), jnp.bfloat16)
    kq = jnp.asarray(rng.integers(-127, 128, (n_pages, kh, ps, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (n_pages, kh, ps, hd)), jnp.int8)
    tables = jnp.asarray(1 + np.arange(b * 3).reshape(b, 3), jnp.int32)
    kv_lens = jnp.asarray([ps + 2, 131], jnp.int32)  # page + window straddles
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (b, kh, hd)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (b, kh, hd)), jnp.float32)

    want, k_ref, v_ref = paged_decode_multi_xla(
        q, k_new, v_new, kq, vq, tables, kv_lens, kv_scales=(ks, vs))
    got, k_out, v_out = paged_decode_pallas_multi(
        q, k_new, v_new, kq, vq, tables, kv_lens, interpret=interpret,
        kscale=ks, vscale=vs)
    wdiff = int(jnp.sum(k_out[1:1 + b * 3] != k_ref[1:1 + b * 3])) \
        + int(jnp.sum(v_out[1:1 + b * 3] != v_ref[1:1 + b * 3]))
    assert wdiff == 0, f"{wdiff} pool bytes differ from the XLA scatter"
    return _maxdiff(got, want)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--interpret", action="store_true",
                    help="run kernels in interpret mode (CI; no TPU needed)")
    args = ap.parse_args()

    if args.interpret:
        # the axon sitecustomize forces jax_platforms=axon via config.update,
        # which overrides the env var — an interpret run must not touch (or
        # hang on) the tunnel, so force CPU the same way tests/conftest does
        jax.config.update("jax_platforms", "cpu")
    from lmrs_tpu.utils.platform import on_tpu

    platform = jax.devices()[0].platform
    # on_tpu(), not a string compare: the tunneled chip reports platform
    # "axon", and that is exactly the hardware this script exists for
    if not on_tpu() and not args.interpret:
        print(f"no TPU visible (platform={platform}); pass --interpret to "
              "run the checks anyway", file=sys.stderr)
        return 2

    checks = [
        ("flash_prefill_vs_xla", lambda: check_flash_prefill(args.interpret), 0.03),
        ("packed_prefill_vs_xla", lambda: check_packed_prefill(args.interpret), 0.03),
        ("fused_ragged_decode_vs_xla",
         lambda: check_fused_ragged_decode(args.interpret), 0.03),
        ("multi_token_verify_vs_xla",
         lambda: check_multi_token_verify(args.interpret), 0.03),
        ("int8_forward", check_int8_forward, 0.02),
        # tol 0.1: the XLA reference dequantizes int8*scale INTO bf16
        # before its einsums (double rounding) while the kernel folds the
        # scales in f32 — the gap is reference precision, not kernel error
        ("int8_kv_fused_decode_vs_xla",
         lambda: check_int8_kv_decode(args.interpret), 0.1),
        # same 0.1 rationale as the fused int8 check: the XLA reference
        # double-rounds through bf16, the kernel folds scales in f32
        ("int8_multi_verify_vs_xla",
         lambda: check_int8_multi_verify(args.interpret), 0.1),
    ]
    results = {}
    failed = []
    t_all = time.time()
    for name, fn, tol in checks:
        t0 = time.time()
        try:
            diff = fn()
            ok = diff <= tol
        except Exception as e:  # noqa: BLE001 - report, keep going
            diff, ok = repr(e)[:200], False
        dt = time.time() - t0
        results[name] = {"diff": diff if isinstance(diff, str) else round(diff, 5),
                         "ok": ok, "seconds": round(dt, 1)}
        print(f"{'PASS' if ok else 'FAIL'} {name}: diff={diff} ({dt:.1f}s)")
        if not ok:
            failed.append(name)
    print(json.dumps({"tpu_smoke": results, "platform": platform,
                      "total_seconds": round(time.time() - t_all, 1),
                      "ok": not failed}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
