"""Shared harness for the chip A/B scripts: one wave() so every arm in
every script measures the identical workload (drift between copies would
silently bias the comparison)."""
import time

import numpy as np

from lmrs_tpu.engine.api import GenerationRequest


def wave(engine, n, max_new, tag, words=(160, 161), temperature=0.3,
         repetitive=False):
    """One timed generate_batch of n requests; prompt lengths drawn from
    ``words`` = (lo, hi) range (uniform ~1.3k-byte prompts by default).

    ``repetitive``: prompts are a short phrase repeated — a low-entropy
    workload where prompt-lookup drafting should reach high acceptance
    (the speculation WIN case; the default high-entropy prompts measure
    speculation's pure overhead instead)."""
    rng = np.random.default_rng(hash(tag) % 2**31)
    if repetitive:
        reqs = [GenerationRequest(
            prompt=f"[{i:02d}:00] " + " ".join(
                f"step{j % 7} leads to step{(j + 1) % 7}"
                for j in range(int(rng.integers(*words)) // 2)),
            request_id=i, temperature=temperature, max_new_tokens=max_new)
            for i in range(n)]
    else:
        reqs = [GenerationRequest(
            prompt=f"[{i:02d}:00] " + " ".join(
                f"word{rng.integers(0, 997)}"
                for _ in range(int(rng.integers(*words)))),
            request_id=i, temperature=temperature, max_new_tokens=max_new)
            for i in range(n)]
    t0 = time.time()
    out = engine.generate_batch(reqs)
    dt = time.time() - t0
    assert all(r.error is None for r in out)
    return dt
