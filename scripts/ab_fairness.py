"""Multi-tenant fairness A/B over a slot-limited mock fleet (ISSUE 17
acceptance).

Two arms over the SAME traffic shape against N MockEngine hosts, each
with ONE admission slot (``slots=1``) and real per-request service
latency — the deviceless stand-in for a saturated TPU pod, serving the
same admission-gate surface the jax scheduler's admit loop enforces:

* a NOISY tenant floods ``batch``-class requests from many concurrent
  client threads (round-robin over the fleet, one outstanding request
  per thread — a map-wave fan-out's signature), keeping every host's
  admission queue saturated for the whole measured window;
* a QUIET tenant sends paced ``interactive`` requests and measures its
  client-side completion wall (TTFT for the mock: the whole completion
  emits at first-token time).

The arms differ ONLY by the engines' ``qos`` switch (the constructor
mirror of the ``LMRS_QOS`` master knob, so the harness never mutates
process-wide environment):

* ``qos_on``: each host's admission gate orders waiting tickets by the
  fair-share policy (fleet/qos.py) — the quiet tenant's interactive
  requests jump the flooded queue, so its TTFT p95 holds within the
  SLO target;
* ``qos_off``: byte-for-byte FIFO admission — the quiet tenant queues
  behind the flood and its TTFT p95 breaches the target.

PASS gate (all must hold):
  1. quiet TTFT p95 <= target under qos_on;
  2. quiet TTFT p95 >  target under qos_off (the flood really contends —
     without this the fairness win would be vacuous);
  3. the quiet tenant's outputs are token-identical across arms (QoS
     reorders admission, never generation);
  4. ledger conservation on every host: per-tenant device-second rollups
     sum to the host totals exactly and ``live_requests == 0`` once the
     flood drains (nothing leaked through the admission gate).

Writes a ``FAIRNESS_r*.json``-shaped artifact with ``--artifact`` so
perf_sentry tracks the fairness trajectory across rounds.

CPU-only, ~15 s.  Usage:
    JAX_PLATFORMS=cpu python scripts/ab_fairness.py [--artifact FAIRNESS_r1.json]
"""

from __future__ import annotations

import _pathfix  # noqa: F401

import argparse
import itertools
import json
import sys
import threading
import time

N_HOSTS = 2
FLOOD_THREADS = 12
FLOOD_REQS_EACH = 8
QUIET_REQS = 10
QUIET_PACE_S = 0.15
LATENCY_S = 0.08          # per-request service time while holding the slot
TTFT_TARGET_MS = 300.0    # quiet SLO: flood FIFO wait is ~N_waiters * latency


def _p95(vals_ms: list[float]) -> float:
    vs = sorted(vals_ms)
    return vs[int(0.95 * (len(vs) - 1))] if vs else 0.0


def run_arm(qos_on: bool) -> dict:
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.mock import MockEngine

    engines = [MockEngine(seed=0, latency_s=LATENCY_S, slots=1, qos=qos_on)
               for _ in range(N_HOSTS)]
    if qos_on and any(e.qos is None for e in engines):
        # qos=True still defers to the master knob; an ambient LMRS_QOS=0
        # would silently turn the on-arm into a second FIFO arm
        raise SystemExit("ab_fairness: LMRS_QOS=0 in the environment — "
                         "the qos_on arm cannot arm; unset it and re-run")
    rr = itertools.count()
    rid = itertools.count()
    rid_lock = threading.Lock()

    def submit(prompt: str, tenant: str, klass: str):
        with rid_lock:
            i, r = next(rr), next(rid)
        req = GenerationRequest(prompt=prompt, request_id=r,
                                temperature=0.0, max_new_tokens=32,
                                tenant=tenant, qos_class=klass)
        res = engines[i % N_HOSTS].generate_batch([req])[0]
        assert res.error is None, res.error
        return res

    errors: list[str] = []

    def flood(k: int) -> None:
        try:
            for j in range(FLOOD_REQS_EACH):
                submit(f"bulk map chunk {k}-{j}: summarize this block of "
                       "deterministic mock content end to end.",
                       "noisy", "batch")
        except Exception as e:  # noqa: BLE001 - surfaced in the gate
            errors.append(f"flood {k}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=flood, args=(k,), daemon=True)
               for k in range(FLOOD_THREADS)]
    for t in threads:
        t.start()
    time.sleep(4 * LATENCY_S)  # let the gates saturate before measuring
    quiet_ms: list[float] = []
    quiet_texts: dict[str, str] = {}
    for i in range(QUIET_REQS):
        prompt = f"live session turn {i}: what changed since last time?"
        t0 = time.time()
        res = submit(prompt, "quiet", "interactive")
        quiet_ms.append((time.time() - t0) * 1e3)
        quiet_texts[prompt] = res.text
        time.sleep(QUIET_PACE_S)
    for t in threads:
        t.join(timeout=120.0)
    alive = sum(t.is_alive() for t in threads)

    # ledger conservation, per host: tenant rollups sum to totals and
    # nothing is still live once the flood drained
    conserved = True
    live = 0
    qos_tenants: set[str] = set()
    for e in engines:
        u = e.ledger.usage_report()
        tenant_sum = sum(r.get("device_seconds", 0.0)
                         for r in u["tenants"].values())
        if abs(tenant_sum - u["totals"].get("device_seconds", 0.0)) > 1e-9:
            conserved = False
        live += int(u.get("live_requests", 0))
        q = e.qos_report()
        if q.get("enabled"):
            qos_tenants |= set(q.get("tenants", {}))
    return {
        "arm": "qos_on" if qos_on else "qos_off",
        "quiet_ttft_p95_ms": round(_p95(quiet_ms), 1),
        "quiet_ttft_max_ms": round(max(quiet_ms), 1),
        "quiet_ttft_ms": [round(v, 1) for v in quiet_ms],
        "flood_errors": errors + ([f"{alive} flood threads stuck"]
                                  if alive else []),
        "usage_conserved": conserved,
        "live_requests_after": live,
        "qos_tenants": sorted(qos_tenants) or None,
        "texts": quiet_texts,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--artifact", default=None,
                    help="write a FAIRNESS_r*.json artifact here "
                         "(perf_sentry trajectory input)")
    args = ap.parse_args(argv)
    on = run_arm(qos_on=True)
    off = run_arm(qos_on=False)

    identical = on["texts"] == off["texts"]
    clean = (not on["flood_errors"] and not off["flood_errors"]
             and on["usage_conserved"] and off["usage_conserved"]
             and on["live_requests_after"] == 0
             and off["live_requests_after"] == 0)
    ok = (on["quiet_ttft_p95_ms"] <= TTFT_TARGET_MS
          and off["quiet_ttft_p95_ms"] > TTFT_TARGET_MS
          and identical and clean)
    detail = {
        "model": "mock-fleet",
        "hosts": N_HOSTS,
        "flood_requests": FLOOD_THREADS * FLOOD_REQS_EACH,
        "quiet_requests": QUIET_REQS,
        "latency_s": LATENCY_S,
        "ttft_target_ms": TTFT_TARGET_MS,
        "quiet_ttft_p95_ms_qos_on": on["quiet_ttft_p95_ms"],
        "quiet_ttft_p95_ms_qos_off": off["quiet_ttft_p95_ms"],
        "fairness_gain": round(
            off["quiet_ttft_p95_ms"] / max(on["quiet_ttft_p95_ms"], 1e-9),
            2),
    }
    report = {
        "object": "ab_fairness",
        "arms": [{k: v for k, v in arm.items() if k != "texts"}
                 for arm in (on, off)],
        "outputs_token_identical": identical,
        "detail": detail,
        "status": "PASS" if ok else "FAIL",
    }
    print(json.dumps(report, indent=2))
    if args.artifact:
        # the perf_sentry artifact shape: rc + parsed.detail metrics
        with open(args.artifact, "w", encoding="utf-8") as f:
            json.dump({"rc": 0 if ok else 1, "ok": ok,
                       "parsed": {"detail": detail}}, f, indent=2)
            f.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
