"""A/B: per-transcript completion skew under serial vs round-robin admission
(VERDICT r2 item 9, multi-transcript batching — BASELINE config #5).

Drives the real continuous scheduler with G groups of map-sized requests
submitted (A) group-serial — the pre-round-3 order — and (B) round-robin
interleaved — what MapExecutor.process_chunk_groups now does — and reports
each group's mean completion RANK (order of on_result delivery).  With
serial admission, group g's mean rank grows linearly with g (later
transcripts starve); round-robin should hold the means within a slot wave
of each other.

Ranks, not wall-clock: on a CPU test run, compile noise swamps timing, but
delivery order is exactly what a user of ``summarize_many`` experiences.

Usage: JAX_PLATFORMS=cpu python scripts/ab_fairness.py  (ranks are platform-
independent; run without the override to measure on a chip)
"""

from __future__ import annotations

import _pathfix  # noqa: F401  (repo-root import shim)


def main() -> None:
    from lmrs_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    G, per_group = 4, 8
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     dtype="float32")
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=16, max_batch_slots=4, seed=0,
                                 decode_block=8), mc)

    def run(order: list[tuple[int, int]], label: str) -> list[float]:
        reqs = [GenerationRequest(prompt=f"group {g} item {i} " * 6,
                                  request_id=g * per_group + i,
                                  temperature=0.7, max_new_tokens=16)
                for g, i in order]
        finished: list[int] = []
        eng.generate_batch(reqs, on_result=lambda r, s: finished.append(r.request_id))
        ranks = {rid: rank for rank, rid in enumerate(finished)}
        means = [sum(ranks[g * per_group + i] for i in range(per_group)) / per_group
                 for g in range(G)]
        print(f"{label}: per-group mean completion rank = "
              f"{[round(m, 1) for m in means]}  skew(max-min) = "
              f"{max(means) - min(means):.1f}")
        return means

    serial = [(g, i) for g in range(G) for i in range(per_group)]
    rr = [(g, i) for i in range(per_group) for g in range(G)]
    a = run(serial, "A serial admission   ")
    b = run(rr, "B round-robin (ours) ")
    print(f"skew reduction: {(max(a) - min(a)) / max(max(b) - min(b), 1e-9):.1f}x")
    eng.shutdown()


if __name__ == "__main__":
    main()
