"""perf_sentry — noise-aware perf-regression checker over the bench history.

Every hardware round appends a ``BENCH_r*.json`` / ``BENCH8B_r*.json`` /
``MULTICHIP_r*.json`` artifact to the repo root (and the A/B rounds
append ``FAIRNESS_r*.json`` / ``MIGRATE_r*.json``, scripts/ab_fairness.py
and scripts/ab_migrate.py), but nothing READ them:
a regression slipped into a round would sit unnoticed until a human
diffed the trajectory.  The sentry makes the history a gate:

* artifacts are grouped by kind and (for bench rounds) by ``detail.model``
  — trajectories only compare like against like;
* the LATEST round's tracked metrics compare against the MEDIAN of the
  prior rounds (median, not best: a one-round fluke must not become the
  permanent bar, and a one-round dip must not hide behind one old spike);
* a delta in the BAD direction beyond the relative band
  (``--band`` / ``LMRS_SENTRY_BAND``, default 0.15 — bench rounds carry
  real run-to-run noise) is a regression; fewer than
  ``LMRS_SENTRY_MIN_ROUNDS`` prior rounds means "no trajectory yet",
  reported but never failed;
* ``MULTICHIP`` rounds gate on the ok/rc flags (a round that stopped
  passing is a regression regardless of numbers).

Output: a JSON report (stdout, or ``--out``) + human summary on stderr;
exit 1 on any regression, 0 otherwise.  ``--report`` forces exit 0 —
the tier-1 CI arm runs report mode over the checked-in history (CPU
runners must surface drift, not block on chip-only noise), while the
hardware-round workflow runs gating mode after appending its artifact.
"""

from __future__ import annotations

import _pathfix  # noqa: F401

import argparse
import json
import re
import sys
from pathlib import Path

from lmrs_tpu.utils.env import env_float, env_int

# tracked bench detail metrics: name -> direction ("up" = higher is
# better).  Percentile dicts are addressed as "name.p50".
TRACKED = {
    "chunks_per_sec": "up",
    "prefill_tokens_per_sec": "up",
    "decode_tokens_per_sec": "up",
    "model_flops_utilization": "up",
    "hbm_bw_utilization": "up",
    "decode_step_ms": "down",
    "decode_row_us_rpa": "down",
    "ttft_ms.p50": "down",
    "decode_block_gap_ms.p50": "down",
    # fairness A/B rounds (FAIRNESS_r*.json, scripts/ab_fairness.py):
    # the quiet tenant's protected TTFT and the QoS-on/off separation
    "quiet_ttft_p95_ms_qos_on": "down",
    "fairness_gain": "up",
    # step-anatomy metrics (ISSUE 18, obs/anatomy.py): per-iteration host
    # overhead between dispatches, and the ragged-span family's padding
    # waste — both live under the bench detail's windowed "anatomy" block
    "anatomy.host_overhead_us_step": "down",
    "anatomy.rpa_pad_waste_ratio": "down",
    # tree speculation (ISSUE 19): accepted draft tokens per dispatched
    # row must trend up, and the host draft segment must stay collapsed
    # (drafting is fused on-device — a draft-segment climb means host
    # n-gram scans crept back into the loop)
    "spec_tree.accept_per_step": "up",
    "anatomy.segments_ms.draft": "down",
    # KV-fabric A/B rounds (MIGRATE_r*.json, scripts/ab_migrate.py): of
    # the preamble tokens the resume host re-serves after a drain, the
    # fraction that came off the fabric (migrated page sets) instead of
    # cold re-prefill — a drop means migration stopped delivering
    "migrate.tokens_from_fabric_ratio": "up",
}

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _round_no(path: Path) -> int:
    m = _ROUND_RE.search(path.name)
    return int(m.group(1)) if m else -1


def _lookup(detail: dict, dotted: str):
    cur = detail
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def load_bench_rounds(root: Path, prefix: str) -> list[dict]:
    """[{round, path, model, metrics{}}] for one artifact family, round
    order.  Unparseable artifacts are skipped with a note, never fatal —
    the sentry must not be brickable by one corrupt file."""
    rounds = []
    for path in sorted(root.glob(f"{prefix}_r*.json"), key=_round_no):
        try:
            doc = json.loads(path.read_text("utf-8"))
            detail = (doc.get("parsed") or {}).get("detail") or {}
            metrics = {}
            for name in TRACKED:
                v = _lookup(detail, name)
                if v is not None:
                    metrics[name] = float(v)
            val = (doc.get("parsed") or {}).get("value")
            if isinstance(val, (int, float)):
                metrics.setdefault("chunks_per_sec", float(val))
            rounds.append({"round": _round_no(path), "path": path.name,
                           "model": detail.get("model") or "?",
                           "rc": doc.get("rc"), "metrics": metrics})
        except (OSError, ValueError) as e:
            rounds.append({"round": _round_no(path), "path": path.name,
                           "error": f"{type(e).__name__}: {e}",
                           "model": "?", "metrics": {}})
    return rounds


def load_multichip_rounds(root: Path) -> list[dict]:
    rounds = []
    for path in sorted(root.glob("MULTICHIP_r*.json"), key=_round_no):
        try:
            doc = json.loads(path.read_text("utf-8"))
            rounds.append({"round": _round_no(path), "path": path.name,
                           "ok": bool(doc.get("ok")),
                           "skipped": bool(doc.get("skipped")),
                           "rc": doc.get("rc")})
        except (OSError, ValueError) as e:
            rounds.append({"round": _round_no(path), "path": path.name,
                           "error": f"{type(e).__name__}: {e}"})
    return rounds


def _median(vals: list[float]) -> float:
    vs = sorted(vals)
    n = len(vs)
    return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])


def check_family(rounds: list[dict], band: float,
                 min_rounds: int) -> tuple[list[dict], list[dict]]:
    """(regressions, checks) comparing each model-group's latest round
    against the median of its priors."""
    regressions: list[dict] = []
    checks: list[dict] = []
    by_model: dict[str, list[dict]] = {}
    for r in rounds:
        if r.get("metrics"):
            by_model.setdefault(r["model"], []).append(r)
    for model, group in by_model.items():
        if len(group) < 2:
            checks.append({"model": model, "rounds": len(group),
                           "status": "no-trajectory"})
            continue
        latest, prior = group[-1], group[:-1]
        for name, direction in TRACKED.items():
            cur = latest["metrics"].get(name)
            hist = [r["metrics"][name] for r in prior
                    if name in r["metrics"]]
            if cur is None or not hist:
                continue
            base = _median(hist)
            if base == 0:
                continue
            # signed relative delta in the GOOD direction (positive =
            # improved); a regression is delta < -band
            delta = (cur - base) / abs(base)
            if direction == "down":
                delta = -delta
            row = {"model": model, "metric": name, "latest": cur,
                   "median_prior": round(base, 4),
                   "rounds_prior": len(hist),
                   "latest_round": latest["path"],
                   "delta_rel": round(delta, 4),
                   "gated": len(hist) >= min_rounds}
            checks.append(row)
            if delta < -band and row["gated"]:
                regressions.append(row)
    return regressions, checks


def check_multichip(rounds: list[dict]) -> tuple[list[dict], list[dict]]:
    live = [r for r in rounds if not r.get("skipped") and "error" not in r]
    checks = [dict(r, path=str(r["path"])) for r in live]
    if len(live) < 2:
        return [], checks
    latest, prior = live[-1], live[:-1]
    if any(p["ok"] for p in prior) and not latest["ok"]:
        return [{"metric": "multichip_ok", "latest_round": latest["path"],
                 "latest": 0, "median_prior": 1, "delta_rel": -1.0,
                 "gated": True, "model": "multichip"}], checks
    return [], checks


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--dir", default=str(Path(__file__).parent.parent),
                    help="artifact directory (default: repo root)")
    ap.add_argument("--band", type=float,
                    default=env_float("LMRS_SENTRY_BAND", 0.15, lo=0.0),
                    help="relative regression band (default 0.15)")
    ap.add_argument("--min-rounds", type=int,
                    default=env_int("LMRS_SENTRY_MIN_ROUNDS", 2, lo=1),
                    help="prior rounds required before a metric gates")
    ap.add_argument("--report", action="store_true",
                    help="report mode: print the same JSON, always exit 0 "
                         "(the tier-1 CI arm)")
    ap.add_argument("--out", default=None, help="write the JSON report here")
    args = ap.parse_args(argv)

    root = Path(args.dir)
    regressions: list[dict] = []
    families: dict[str, dict] = {}
    for prefix in ("BENCH", "BENCH8B", "FAIRNESS", "MIGRATE"):
        rounds = load_bench_rounds(root, prefix)
        if not rounds:
            continue
        regs, checks = check_family(rounds, args.band, args.min_rounds)
        regressions += [dict(r, family=prefix) for r in regs]
        families[prefix] = {"rounds": len(rounds), "checks": checks}
    mc = load_multichip_rounds(root)
    if mc:
        regs, checks = check_multichip(mc)
        regressions += [dict(r, family="MULTICHIP") for r in regs]
        families["MULTICHIP"] = {"rounds": len(mc), "checks": checks}

    report = {
        "object": "perf_sentry",
        "band": args.band,
        "min_rounds": args.min_rounds,
        "families": families,
        "regressions": regressions,
        "status": "regression" if regressions else "ok",
    }
    text = json.dumps(report, indent=2)
    if args.out:
        Path(args.out).write_text(text + "\n", encoding="utf-8")
    print(text)
    for r in regressions:
        print(f"REGRESSION {r.get('family')}/{r['model']} {r['metric']}: "
              f"{r['latest']} vs median {r['median_prior']} "
              f"({r['delta_rel']:+.1%}, band -{args.band:.0%}) "
              f"in {r['latest_round']}", file=sys.stderr)
    if regressions and not args.report:
        return 1
    if regressions:
        print("report mode: regressions reported, exit 0", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
