"""ABBA: int8 weight-only quantization vs bf16 at bench-1b scale.

Two engines (params differ), alternating decode-heavy waves A B B A.
Run: python scripts/ab_int8.py
"""
import _pathfix  # noqa: F401  (repo-root import shim)
import time

import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.utils.logging import setup_logging

from _bench_common import wave


def main():
    setup_logging(quiet=True)
    model = model_preset("bench-1b")

    def make(quant):
        return JaxEngine(EngineConfig(
            backend="jax", max_tokens=128, max_batch_slots=24,
            retry_delay=0.0, seed=0, page_size=512, num_pages=1,
            decode_block=128, prefill_chunk=4096, quantize=quant), model)

    a = make(None)     # bf16
    b = make("int8")
    n, max_new = 48, 128  # decode-heavy: int8 pays in the weight stream
    wave(a, n, max_new, "warmA", words=(160, 161))
    wave(b, n, max_new, "warmB", words=(160, 161))

    rounds = []
    for r in range(3):
        res = {}
        for arm, eng in (("A", a), ("B", b), ("B2", b), ("A2", a)):
            res[arm] = wave(eng, n, max_new, f"{r}{arm}", words=(160, 161))
        am = (res["A"] + res["A2"]) / 2
        bm = (res["B"] + res["B2"]) / 2
        rounds.append((am, bm))
        print(f"round {r}: bf16={am:.2f}s int8={bm:.2f}s "
              f"int8 wins {100*(am-bm)/am:+.1f}% ({res})", flush=True)
    am = np.mean([r[0] for r in rounds]); bm = np.mean([r[1] for r in rounds])
    print(f"MEAN bf16={am:.2f}s int8={bm:.2f}s  int8 wins {100*(am-bm)/am:+.1f}%")


if __name__ == "__main__":
    main()
