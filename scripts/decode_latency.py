"""Decode-latency benchmark for the prefill_chunk default (VERDICT r1
item 10): distribution of decode-dispatch gaps for already-active slots
while a long prompt admits mid-stream, chunked (512) vs one-dispatch
(4096) prefill.  Dispatch timestamps come from the lifecycle tracer's
``decode_block`` span starts (obs/trace.py — the one dispatch-timestamp
path; the LMRS_TRACE_DISPATCH env hack this script used to flip is gone).
Run: python scripts/decode_latency.py
"""
import time

import _pathfix  # noqa: F401  (repo-root import shim)
import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.obs import TID_SCHED, enable_tracing
from lmrs_tpu.utils.logging import setup_logging


def run(prefill_chunk, label):
    tracer = enable_tracing()
    model = model_preset("bench-1b")
    eng = JaxEngine(EngineConfig(
        backend="jax", max_tokens=256, max_batch_slots=8,
        retry_delay=0.0, seed=0, page_size=512, num_pages=1,
        decode_block=8, prefill_chunk=prefill_chunk), model)
    rng = np.random.default_rng(0)
    # 6 active decoders (short prompts, long decodes)
    active = [GenerationRequest(
        prompt=" ".join(f"w{rng.integers(0, 97)}" for _ in range(30)),
        request_id=i, temperature=0.5, max_new_tokens=256) for i in range(6)]
    # 8 long prompts that admit mid-stream as slots churn
    longs = [GenerationRequest(
        prompt=" ".join(f"word{rng.integers(0, 997)}" for _ in range(230)),
        request_id=100 + i, temperature=0.5, max_new_tokens=8)
        for i in range(8)]
    eng.generate_batch(active[:2])  # warm compile
    tracer.clear()  # drop warmup dispatches (compile-time gaps)
    t0 = time.time()
    eng.generate_batch(active + longs)
    wall = time.time() - t0
    ts = np.asarray(tracer.timestamps("decode_block", tid=TID_SCHED))
    gaps = np.diff(ts) * 1e3
    print(f"{label}: wall={wall:.1f}s dispatches={len(ts)} "
          f"gap p50={np.percentile(gaps, 50):.0f}ms "
          f"p90={np.percentile(gaps, 90):.0f}ms "
          f"p99={np.percentile(gaps, 99):.0f}ms max={gaps.max():.0f}ms",
          flush=True)
    eng.shutdown()
    return gaps


def main():
    setup_logging(quiet=True)
    for pc, label in ((512, "chunked-512"), (4096, "one-dispatch"),
                      (4096, "one-dispatch-2"), (512, "chunked-512-2")):
        run(pc, label)


if __name__ == "__main__":
    main()
