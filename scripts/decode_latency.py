"""Decode-latency benchmark for the prefill_chunk default (VERDICT r1
item 10): distribution of decode-dispatch gaps for already-active slots
while a long prompt admits mid-stream, chunked (512) vs one-dispatch
(4096) prefill.  Dispatch timestamps come from the lifecycle tracer's
``decode_block`` span starts (obs/trace.py — the one dispatch-timestamp
path; the LMRS_TRACE_DISPATCH env hack this script used to flip is gone).

Run live:     python scripts/decode_latency.py
Read a trace: python scripts/decode_latency.py --from-trace stitched.json
              [--pod host:port]

``--from-trace`` analyzes an exported trace file instead of running an
engine — including a ROUTER-STITCHED multi-host trace (``GET /v1/trace``
on a router front, obs.stitch_traces), where each pod's scheduler track
is reported separately; ``--pod`` filters to process names containing
the given substring (a netloc, typically).
"""
import argparse
import time

import _pathfix  # noqa: F401  (repo-root import shim)
import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.obs import TID_SCHED, enable_tracing, validate_trace_file
from lmrs_tpu.utils.logging import setup_logging


def _gap_line(label: str, ts: np.ndarray, wall: float | None = None) -> None:
    if len(ts) < 2:
        print(f"{label}: only {len(ts)} dispatch(es); no gaps", flush=True)
        return
    gaps = np.diff(np.sort(ts)) * 1e3
    wall_part = f"wall={wall:.1f}s " if wall is not None else ""
    print(f"{label}: {wall_part}dispatches={len(ts)} "
          f"gap p50={np.percentile(gaps, 50):.0f}ms "
          f"p90={np.percentile(gaps, 90):.0f}ms "
          f"p99={np.percentile(gaps, 99):.0f}ms max={gaps.max():.0f}ms",
          flush=True)


def analyze_trace(path: str, pod: str | None = None) -> dict[str, np.ndarray]:
    """Decode-dispatch gap analysis of an exported trace file.  Handles
    both a single-host export (pid 1's scheduler track) and a stitched
    multi-host document (per-host pids; process names carry the netloc).
    Returns {pod name: dispatch start timestamps (s)}."""
    events = validate_trace_file(path)
    pnames = {e["pid"]: (e.get("args") or {}).get("name", "")
              for e in events
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    per_pod: dict[str, list[float]] = {}
    for e in events:
        if (e.get("name") == "decode_block" and e.get("ph") == "X"
                and e.get("tid") == TID_SCHED):
            name = pnames.get(e["pid"], f"pid{e['pid']}") or f"pid{e['pid']}"
            if pod is not None and pod not in name:
                continue
            per_pod.setdefault(name, []).append(e["ts"] / 1e6)
    if not per_pod:
        have = sorted(n for n in pnames.values() if "engine" in n)
        raise SystemExit(
            f"no decode_block dispatch spans matched"
            + (f" pod filter {pod!r}" if pod else "")
            + (f"; engine tracks present: {have}" if have else
               "; the trace has no engine tracks"))
    return {name: np.asarray(ts) for name, ts in sorted(per_pod.items())}


def run(prefill_chunk, label):
    from lmrs_tpu.engine.jax_engine import JaxEngine

    tracer = enable_tracing()
    model = model_preset("bench-1b")
    eng = JaxEngine(EngineConfig(
        backend="jax", max_tokens=256, max_batch_slots=8,
        retry_delay=0.0, seed=0, page_size=512, num_pages=1,
        decode_block=8, prefill_chunk=prefill_chunk), model)
    rng = np.random.default_rng(0)
    # 6 active decoders (short prompts, long decodes)
    active = [GenerationRequest(
        prompt=" ".join(f"w{rng.integers(0, 97)}" for _ in range(30)),
        request_id=i, temperature=0.5, max_new_tokens=256) for i in range(6)]
    # 8 long prompts that admit mid-stream as slots churn
    longs = [GenerationRequest(
        prompt=" ".join(f"word{rng.integers(0, 997)}" for _ in range(230)),
        request_id=100 + i, temperature=0.5, max_new_tokens=8)
        for i in range(8)]
    eng.generate_batch(active[:2])  # warm compile
    tracer.clear()  # drop warmup dispatches (compile-time gaps)
    t0 = time.time()
    eng.generate_batch(active + longs)
    wall = time.time() - t0
    ts = np.asarray(tracer.timestamps("decode_block", tid=TID_SCHED))
    _gap_line(label, ts, wall)
    eng.shutdown()
    return np.diff(ts) * 1e3


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--from-trace", default=None, metavar="PATH",
                    help="analyze an exported (possibly router-stitched "
                         "multi-host) trace file instead of running live")
    ap.add_argument("--pod", default=None,
                    help="with --from-trace: only tracks whose process "
                         "name contains this substring (a host netloc)")
    args = ap.parse_args()
    setup_logging(quiet=True)
    if args.from_trace:
        for name, ts in analyze_trace(args.from_trace, args.pod).items():
            _gap_line(name, ts)
        return
    if args.pod:
        raise SystemExit("--pod requires --from-trace")
    for pc, label in ((512, "chunked-512"), (4096, "one-dispatch"),
                      (4096, "one-dispatch-2"), (512, "chunked-512-2")):
        run(pc, label)


if __name__ == "__main__":
    main()
