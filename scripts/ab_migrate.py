"""Cross-host KV migration A/B over an in-process mock fleet (ISSUE 20
acceptance).

Two arms over the SAME traffic shape against two MockEngine-backed HTTP
workers behind a RouterEngine — the deviceless stand-in for a drain on a
live fleet:

* a WARM phase sends preamble-sharing map requests straight at host A,
  building the warm radix entries its /healthz summary advertises;
* a DRAIN takes host A out of the dispatch order; with migration armed
  the router moves A's warm page sets to host B over the /v1/kv wire
  (export ticket -> pull-import -> ack) before A is force-removed;
* a RESUME phase replays the same preamble traffic through the router —
  now served entirely by host B.

The arms differ ONLY by ``LMRS_KV_MIGRATE`` at construction time:

* ``migrate_on``: B's resume preamble queries hit the MIGRATED entries —
  the fabric re-serves the prefill tokens host A already paid for;
* ``migrate_off``: the /v1/kv surface answers 501, the router attempts
  no moves, and B cold-prefills the preamble from scratch (the byte-
  parity arm: no ``kv_migrate`` key appears in any metrics document).

The headline metric is ``migrate.tokens_from_fabric_ratio``: of the
preamble tokens B re-served during the resume, the fraction that came
off the fabric (reused from imported page sets) rather than cold
re-prefill.  perf_sentry tracks it across ``MIGRATE_r*.json`` rounds.

PASS gate (all must hold):
  1. migrate_on fabric ratio >= 0.5 (the ISSUE 20 floor);
  2. migrate_off fabric ratio == 0 with zero imports AND no kv_migrate
     key in either host's metrics (the kill switch restores today's
     metric surface byte-for-byte);
  3. resume outputs token-identical across arms (migration moves KV,
     never changes generation);
  4. ledger conservation on every host (tenant rollups sum to totals,
     nothing live after the traffic drains) and >= 1 router move on the
     on arm, 0 on the off arm.

CPU-only, ~10 s.  Usage:
    JAX_PLATFORMS=cpu python scripts/ab_migrate.py [--artifact MIGRATE_r1.json]
"""

from __future__ import annotations

import _pathfix  # noqa: F401

import argparse
import json
import sys
import time

N_WARM = 6
N_RESUME = 6
_PREAMBLE = ("You are summarizing one section of a long transcript. "
             "Keep every fact, decision, owner, date, and number exactly "
             "as stated; never invent content; answer with the summary "
             "only and preserve the section ordering. ")


def _reqs(base_rid: int, n: int):
    from lmrs_tpu.engine.api import GenerationRequest

    return [GenerationRequest(
        prompt=_PREAMBLE + f"Chunk {i}: milestone {i} closed on time.",
        request_id=base_rid + i, temperature=0.0, max_new_tokens=24,
        cache_prefix=len(_PREAMBLE)) for i in range(n)]


def run_arm(migrate_on: bool) -> dict:
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    engines = [MockEngine(seed=0) for _ in range(2)]
    servers = [EngineHTTPServer(e, port=0, batch_window_s=0.01)
               for e in engines]
    for s in servers:
        s.start_background()
    hosts = [f"127.0.0.1:{s.port}" for s in servers]
    router = RouterEngine(hosts)
    if router.kv_migrate and not migrate_on:
        # the off arm flips the SAME gate LMRS_KV_MIGRATE=0 sets at
        # construction, without mutating process-wide environment (the
        # ab_fairness constructor-mirror convention)
        router.kv_migrate = False
        for s in servers:
            s.kv_migrate = False
    elif migrate_on and not router.kv_migrate:
        raise SystemExit("ab_migrate: LMRS_KV_MIGRATE=0 in the "
                         "environment — the on arm cannot arm; unset "
                         "it and re-run")

    try:
        # warm host A directly: its radix picks up the shared preamble
        for r in engines[0].generate_batch(_reqs(0, N_WARM)):
            assert r.error is None, r.error

        # drain A; armed, the router migrates A's page sets to B first
        assert router.drain_host(hosts[0])
        deadline = time.time() + 20.0
        while (router.migrations_pending(hosts[0])
               and time.time() < deadline):
            time.sleep(0.05)
        pending = router.migrations_pending(hosts[0])
        assert router.remove_host(hosts[0], force=True)

        # resume through the router: only B is left to serve
        before = engines[1].engine_metrics()
        b_pc0 = before.get("prefix_cache") or {}
        resume = router.generate_batch(_reqs(100, N_RESUME))
        errors = [r.error for r in resume if r.error is not None]
        after = engines[1].engine_metrics()
        pc = after.get("prefix_cache") or {}
        mig = after.get("kv_migrate") or {}

        # fabric ratio: of the preamble tokens B re-served on resume,
        # the fraction reused from IMPORTED entries.  B held no warm
        # entries of its own before the drain, so with migration armed
        # every resume reuse is fabric-served; disarmed, imports are 0
        # and the ratio is 0 by definition (self-warmed reuse is local
        # re-prefill savings, not fabric traffic).
        queries = pc.get("queries", 0) - b_pc0.get("queries", 0)
        reused = pc.get("tokens_reused", 0) - b_pc0.get("tokens_reused", 0)
        imported = mig.get("tokens_imported", 0)
        if imported and queries:
            ratio = min(1.0, reused / (queries * imported))
        else:
            ratio = 0.0

        conserved, live = True, 0
        for e in engines:
            u = e.ledger.usage_report()
            tenant_sum = sum(r.get("device_seconds", 0.0)
                             for r in u["tenants"].values())
            if abs(tenant_sum
                   - u["totals"].get("device_seconds", 0.0)) > 1e-9:
                conserved = False
            live += int(u.get("live_requests", 0))
        rm = router.engine_metrics().get("kv_migrate") or {}
        return {
            "arm": "migrate_on" if migrate_on else "migrate_off",
            "errors": errors + (["migration still pending at removal"]
                                if pending else []),
            "resume_queries": queries,
            "resume_tokens_reused": reused,
            "tokens_imported": imported,
            "imports": mig.get("imports", 0),
            "router_moves": rm.get("moves", 0),
            "router_failures": rm.get("failures", 0),
            "tokens_from_fabric_ratio": round(ratio, 4),
            "kv_migrate_key_present": ("kv_migrate" in after
                                       or "kv_migrate" in before),
            "usage_conserved": conserved,
            "live_requests_after": live,
            "texts": {r.request_id: r.text for r in resume},
        }
    finally:
        router.shutdown()
        for s in servers:
            s.shutdown()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--artifact", default=None,
                    help="write a MIGRATE_r*.json artifact here "
                         "(perf_sentry trajectory input)")
    args = ap.parse_args(argv)
    on = run_arm(migrate_on=True)
    off = run_arm(migrate_on=False)

    identical = on["texts"] == off["texts"]
    clean = (not on["errors"] and not off["errors"]
             and on["usage_conserved"] and off["usage_conserved"]
             and on["live_requests_after"] == 0
             and off["live_requests_after"] == 0)
    ok = (on["tokens_from_fabric_ratio"] >= 0.5
          and on["imports"] >= 1 and on["router_moves"] >= 1
          and off["tokens_from_fabric_ratio"] == 0.0
          and off["imports"] == 0 and off["router_moves"] == 0
          and not off["kv_migrate_key_present"]
          and identical and clean)
    detail = {
        "model": "mock-fleet",
        "hosts": 2,
        "warm_requests": N_WARM,
        "resume_requests": N_RESUME,
        "migrate": {
            "tokens_from_fabric_ratio": on["tokens_from_fabric_ratio"],
            "tokens_imported": on["tokens_imported"],
            "router_moves": on["router_moves"],
        },
    }
    report = {
        "object": "ab_migrate",
        "arms": [{k: v for k, v in arm.items() if k != "texts"}
                 for arm in (on, off)],
        "outputs_token_identical": identical,
        "detail": detail,
        "status": "PASS" if ok else "FAIL",
    }
    print(json.dumps(report, indent=2))
    if args.artifact:
        # the perf_sentry artifact shape: rc + parsed.detail metrics
        with open(args.artifact, "w", encoding="utf-8") as f:
            json.dump({"rc": 0 if ok else 1, "ok": ok,
                       "parsed": {"detail": detail}}, f, indent=2)
            f.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
