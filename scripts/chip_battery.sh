#!/bin/bash
# Hardware measurement battery — run top-to-bottom the moment a TPU answers.
# Each stage gates the next (no point benching on a chip that fails parity).
# Usage: bash scripts/chip_battery.sh [outdir]
set -u -o pipefail
OUT=${1:-/tmp/chip_battery}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "=== 1. kernel parity smoke (<60s) ==="
# pipefail: the gate is the smoke's EXIT CODE — grepping '"ok": true' would
# match the per-check fields even when the overall summary says false
timeout 600 python scripts/tpu_smoke.py 2>&1 | tee "$OUT/smoke.log"
[ "${PIPESTATUS[0]}" -eq 0 ] || { echo "SMOKE FAILED — stop"; exit 1; }

echo "=== 2. decode fixed-cost/slope fit (kv-head fold ABBA target: 9.39ms -> <5ms fixed) ==="
timeout 1200 python scripts/decode_split.py 2>&1 | tee "$OUT/decode_split.log"

echo "=== 3. bench (median of 3 reps, full roofline detail) ==="
timeout 1800 python bench.py 2>&1 | tee "$OUT/bench.log"

echo "=== 4. speculation ABBA (multi-token verify kernel; was 12x loss) ==="
timeout 1200 python scripts/ab_spec.py 2>&1 | tee "$OUT/spec.log"

echo "=== 5. int8 x flash-tile sanity (should reproduce r2: ~41.5% MFU tile 512) ==="
timeout 1200 python scripts/ab_int8.py 2>&1 | tee "$OUT/int8.log"

echo "=== 6. 8B north-star bench (BASELINE model shape, int8 W+KV, one chip) ==="
# host-side random init of the 8B tree adds ~2-4 min before the first rep
LMRS_BENCH_MODEL=bench-8b LMRS_BENCH_DEADLINE_S=3600 \
  timeout 3900 python bench.py 2>&1 | tee "$OUT/bench8b.log"

echo "=== 7. serving-config latency percentiles (1B + 8B) ==="
# stdout (the one JSON line) to .json, log noise to .log — a merged tee
# would prepend JAX warnings and break downstream json.load
timeout 1800 python scripts/serving_latency.py \
  > "$OUT/serving_latency.json" 2> "$OUT/serving_latency.log"
cat "$OUT/serving_latency.json"
LMRS_SERVE_MODEL=bench-8b timeout 1800 python scripts/serving_latency.py \
  > "$OUT/serving_latency_8b.json" 2> "$OUT/serving_latency_8b.log"
cat "$OUT/serving_latency_8b.json"

echo "battery complete -> $OUT"
