"""In-process ABBA: packed vs per-prompt prefill at bench-1b scale.

One engine; sched._pack_prefill toggled between runs (both program families
compile once).  Order A B B A per round; map-stage wall per arm.
Run on the real chip: python scripts/ab_pack.py [max_new]
LMRS_AB_KV=int8: both arms run int8 KV pools (the r4 composition row —
packed+int8 vs unpacked+int8, VERDICT r3 item 3).
"""
import _pathfix  # noqa: F401  (repo-root import shim)
import sys
import time

import numpy as np

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.utils.logging import setup_logging

from _bench_common import wave

from lmrs_tpu.utils.env import env_str


def main():
    max_new = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    setup_logging(quiet=True)
    model = model_preset("bench-1b")
    kv = env_str("LMRS_AB_KV") or None
    eng = JaxEngine(EngineConfig(
        backend="jax", max_tokens=max_new, max_batch_slots=24,
        retry_delay=0.0, seed=0, page_size=512, num_pages=1,
        decode_block=max_new, prefill_chunk=4096, kv_quantize=kv), model)
    if kv:
        print(f"kv_quantize={kv} (both arms)", flush=True)
    sched = eng._scheduler
    n = 48  # two full admission waves

    # warm BOTH paths (compile everything)
    sched._pack_prefill = True
    wave(eng, n, max_new, "warmA", words=(60, 231))
    sched._pack_prefill = False
    wave(eng, n, max_new, "warmB", words=(60, 231))

    rounds = []
    for r in range(3):
        res = {}
        for arm in ("A", "B", "B2", "A2"):
            sched._pack_prefill = arm.startswith("A")
            res[arm] = wave(eng, n, max_new, f"{r}{arm}", words=(60, 231))
        a = (res["A"] + res["A2"]) / 2
        b = (res["B"] + res["B2"]) / 2
        rounds.append((a, b))
        print(f"round {r}: packed={a:.2f}s unpacked={b:.2f}s "
              f"delta={100*(b-a)/b:+.1f}% ({res})", flush=True)
    am = np.mean([r[0] for r in rounds]); bm = np.mean([r[1] for r in rounds])
    print(f"MEAN packed={am:.2f}s unpacked={bm:.2f}s  packed wins {100*(bm-am)/bm:+.1f}%")


if __name__ == "__main__":
    main()
