"""Make the repo root importable when a script runs without the editable
install (`python scripts/x.py` puts scripts/ on sys.path, not the root).
Import for its side effect: ``import _pathfix``."""
import sys
from pathlib import Path

_root = str(Path(__file__).resolve().parent.parent)
if _root not in sys.path:
    sys.path.insert(0, _root)
