"""OpenAI/Anthropic-wire-compatible HTTP server over the in-tree engine.

Endpoints (the two wire formats the reference's clients speak):

* ``POST /v1/chat/completions`` — OpenAI chat completions
  (request shape: llm_executor.py:278-289; response fields the reference
  reads: choices[0].message.content + usage, llm_executor.py:304-317);
* ``POST /v1/messages`` — Anthropic messages (request: llm_executor.py:343-371
  modulo its system-role bug, SURVEY.md §2.3.7; response fields read:
  content[0].text + usage, llm_executor.py:389-400);
* ``GET /v1/models``, ``GET /healthz``, ``GET /metrics``.

Concurrent requests micro-batch: a dispatcher thread drains the queue and
hands the whole wave to ``Engine.generate_batch`` — a reference-style client
fanning out N requests under its semaphore gets them pooled into one engine
wave instead of N serialized ones (continuous batching across HTTP clients).

Both endpoints support ``stream: true`` (SSE) in their own wire dialect —
chat.completion.chunk deltas / Anthropic message_start→message_stop events —
driven by the engine's ``on_tokens`` callback: the continuous scheduler
emits one delta per decode block, so streamed and pooled requests share the
same batch slots (a streaming request never gets a private engine).

stdlib only (``http.server``): the serving runtime must not pull in an async
web framework this image doesn't have.
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import queue
import re
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from lmrs_tpu.engine.api import Engine, GenerationRequest, GenerationResult
from lmrs_tpu.obs import get_tracer, new_trace_id
from lmrs_tpu.obs.ledger import DEFAULT_TENANT
from lmrs_tpu.serving.handoff import (ImportLog, TicketRegistry,
                                      decode_payload, encode_payload)
from lmrs_tpu.testing import faults

logger = logging.getLogger("lmrs.serving")

# X-LMRS-Trace values ride track names, tickets, and journals: confine
# them to a safe alphabet and length — a malformed header mints fresh
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_.:-]{1,64}$")


def clean_trace_id(raw) -> str | None:
    """A wire-supplied trace id, validated; None when absent/garbage."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _TRACE_ID_RE.match(raw) else None


def clean_tenant(raw, default: str | None = None) -> str | None:
    """A wire-supplied ``X-LMRS-Tenant`` label, validated against the
    same safe alphabet as trace ids (it rides journals, usage rollup
    keys, and Prometheus-adjacent docs); ``default`` when absent or
    garbage.  Completion ingress passes ``DEFAULT_TENANT`` so anonymous
    traffic is MINTED an explicit tenant (QoS weights and quota reports
    can then name unlabeled traffic, docs/SERVING.md); label-adoption
    sites (handoff payloads, job/session submits that default to their
    own identity) keep ``default=None`` so absence stays observable."""
    return clean_trace_id(raw) or default


def clean_qos_class(raw) -> str | None:
    """A wire-supplied ``X-LMRS-QoS-Class`` label (or ``qos_class`` body
    field): "interactive" | "batch", else None (fleet/qos.py resolves
    None to "interactive")."""
    from lmrs_tpu.fleet.qos import clean_qos_class as _clean

    return _clean(raw)


class _Job:
    __slots__ = ("request", "result", "event", "deltas", "rid", "cancelled",
                 "done_cb")

    def __init__(self, request: GenerationRequest, stream: bool = False,
                 done_cb=None):
        self.request = request
        self.result: GenerationResult | None = None
        self.event = threading.Event()
        # streaming jobs carry a per-job delta queue: the dispatcher routes
        # engine on_tokens callbacks here; a None sentinel (pushed AFTER
        # ``result`` is set) ends the stream
        self.deltas: queue.Queue[str | None] | None = (
            queue.Queue() if stream else None)
        self.rid: int | None = None  # wave-relative id, set by the dispatcher
        self.cancelled = False  # set by _Batcher.cancel (handler threads)
        # fired right after ``event`` (completion fan-in: _BatcherEngine
        # waits on ONE shared event for a whole set of jobs)
        self.done_cb = done_cb

    def done(self) -> None:
        self.event.set()
        if self.done_cb is not None:
            self.done_cb()


class _Batcher:
    """Micro-batching dispatcher: collect jobs for up to ``window_s`` (or
    ``max_batch``), run them as ONE ``generate_batch`` call."""

    def __init__(self, engine: Engine, window_s: float = 0.02, max_batch: int = 256):
        self.engine = engine
        self.window_s = window_s
        self.max_batch = max_batch
        self.queue: queue.Queue[_Job | None] = queue.Queue()
        self.closed = False
        # orders every submit() against shutdown(): a job is either enqueued
        # strictly before the sentinel (the dispatcher's final drain then
        # completes it) or rejected fast — event.wait() can never hang a
        # handler thread on a job the dispatcher will never see
        self._close_lock = threading.Lock()
        # jobs of the wave currently inside generate_batch, by wave rid —
        # cancel() consults it to route an abort into the running engine
        # call (handler threads read it; only the dispatcher writes it)
        self._inflight: dict[int, _Job] = {}
        # rids increase monotonically ACROSS waves: a cancel that races a
        # wave boundary (issued for wave N, observed by the engine around
        # wave N+1) can then never alias another client's request — the
        # stale id just no-ops (engine contract, engine/api.py)
        self._next_rid = 0
        self.batches_run = 0
        self.requests_served = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, request: GenerationRequest,
               poll_disconnect=None,
               poll_interval: float = 0.5) -> GenerationResult:
        """Enqueue and block until the result is ready.

        ``poll_disconnect``, when given, is called every ``poll_interval``
        seconds while waiting; returning True means the client went away —
        the job is cancelled (queued jobs are dropped before dispatch, a
        job inside the running wave is aborted through the engine's cancel
        hook) and submit() keeps waiting for the cancellation result.
        This closes the non-streaming disconnect gap: without it only SSE
        paths (which notice via OSError on a stream write) could abort an
        abandoned request, and a dropped non-stream request would decode
        to max_tokens holding its slot and pages."""
        job = _Job(request)
        with self._close_lock:
            self._assign_rid(job)
            if self.closed:
                return GenerationResult(request_id=job.rid,
                                        finish_reason="error",
                                        error="server shutting down")
            self.queue.put(job)
        if poll_disconnect is None:
            job.event.wait()
        else:
            while not job.event.wait(poll_interval):
                if not job.cancelled and poll_disconnect():
                    logger.debug(
                        "non-stream client disconnected; cancelling")
                    self.cancel(job)
        assert job.result is not None
        return job.result

    def submit_stream(self, request: GenerationRequest) -> _Job:
        """Enqueue WITHOUT blocking; the caller reads ``job.deltas`` until
        the None sentinel, then ``job.result`` is set (SSE handlers)."""
        job = _Job(request, stream=True)
        with self._close_lock:
            self._assign_rid(job)
            if self.closed:
                job.result = GenerationResult(
                    request_id=job.rid, finish_reason="error",
                    error="server shutting down")
                job.done()
                job.deltas.put(None)
                return job
            self.queue.put(job)
        return job

    def submit_job(self, request: GenerationRequest,
                   done_cb=None) -> _Job:
        """Enqueue WITHOUT blocking and return the job — no delta stream;
        the caller waits on ``job.event`` and reads ``job.result``.  The
        durable-job facade (:class:`_BatcherEngine`) uses this to pool a
        JobManager's chunk/reduce requests into the SAME engine waves as
        interactive traffic instead of calling the raw engine concurrently.
        ``done_cb`` (set before enqueue — no completion can race past it)
        fires on completion, letting that caller wait on one shared event
        for a whole request set."""
        job = _Job(request, done_cb=done_cb)
        with self._close_lock:
            self._assign_rid(job)
            if self.closed:
                job.result = GenerationResult(
                    request_id=job.rid, finish_reason="error",
                    error="server shutting down")
                job.done()
                return job
            self.queue.put(job)
        return job

    def _assign_rid(self, job: _Job) -> None:
        """Give the job its wave rid AT ENQUEUE (caller holds _close_lock).
        Rids were formerly assigned at dispatch, which left every
        rejection path (shutdown fast-fail, the sentinel drain) emitting a
        placeholder ``request_id=0`` that clients could not correlate —
        now every result, error or not, echoes the job's real id."""
        job.rid = self._next_rid
        self._next_rid += 1
        job.request.request_id = job.rid

    def cancel(self, job: _Job) -> None:
        """Abort ``job`` (client disconnected).  Queued jobs are dropped
        before dispatch; a job already inside the running engine wave is
        aborted through the engine's optional ``cancel`` hook — the
        continuous scheduler then frees its slot and pages at the next
        block boundary instead of decoding to max_tokens.  Thread-safe:
        called from HTTP handler threads."""
        job.cancelled = True
        rid = job.rid
        if rid is not None and self._inflight.get(rid) is job:
            eng_cancel = getattr(self.engine, "cancel", None)
            if eng_cancel is not None:
                eng_cancel(rid)

    def shutdown(self) -> None:
        with self._close_lock:
            self.closed = True
            self.queue.put(None)
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            job = self.queue.get()
            if job is None:
                self._drain_on_shutdown()
                return
            jobs = [job]
            deadline = time.monotonic() + self.window_s
            while len(jobs) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self.queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._run(jobs)
                    self._drain_on_shutdown()
                    return
                jobs.append(nxt)
            self._run(jobs)

    def _qos_order(self, jobs: list[_Job]) -> list[_Job]:
        """Fair-share wave order (fleet/qos.py): when the engine carries
        an armed QoS policy (the mock's admission gate; the jax
        scheduler reorders in its own admit loop instead), the wave
        dispatches in repeated-policy-pick order — interactive before
        batch, under-served tenants before flooding ones.  Identity when
        the engine has no policy or ``LMRS_QOS=0`` (the engine attribute
        is then None), so the kill-switch wave order is byte-for-byte
        FIFO."""
        pol = getattr(self.engine, "qos", None)
        if pol is None or len(jobs) < 2:
            return jobs
        remaining = list(jobs)
        out: list[_Job] = []
        while remaining:
            out.append(remaining.pop(
                pol.pick_index([j.request for j in remaining])))
        return out

    def _drain_on_shutdown(self) -> None:
        """Jobs enqueued behind the shutdown sentinel (multiple shutdown()
        calls can race a submit past an earlier sentinel) would otherwise
        block their submit() forever — complete them with an error result.
        Only the dispatcher thread runs this, after consuming a sentinel."""
        while True:
            try:
                job = self.queue.get_nowait()
            except queue.Empty:
                return
            if job is None:
                continue
            # echo the job's enqueue-time rid; direct-constructed jobs
            # (tests) fall back to the request's own id
            rid = job.rid if job.rid is not None else job.request.request_id
            job.result = GenerationResult(
                request_id=rid, finish_reason="error",
                error="server shutting down")
            job.done()
            if job.deltas is not None:
                job.deltas.put(None)

    def _run(self, jobs: list[_Job]) -> None:
        # rids were assigned at enqueue (_assign_rid): globally unique and
        # monotonic across waves, and every rejection path can echo them
        # publish the wave BEFORE dispatch so cancel() can route a
        # disconnect into the running engine call; then drop jobs already
        # cancelled while queued (their clients are gone — finish them
        # without spending engine work).  A cancel racing between these two
        # steps at worst does both: an inert engine.cancel for an
        # undispatched rid, cleared at the engine run's end.
        self._inflight = {j.rid: j for j in jobs}
        skipped = [j for j in jobs if j.cancelled]
        jobs = self._qos_order([j for j in jobs if not j.cancelled])
        for job in skipped:
            job.result = GenerationResult(request_id=job.rid,
                                          finish_reason="cancelled")
            job.done()
            if job.deltas is not None:
                job.deltas.put(None)
        if not jobs:
            self._inflight = {}
            return
        # route engine token deltas to their job's stream queue (rids are
        # the wave indices assigned above); queue.put is thread-safe, which
        # the replicated engine's concurrent fan-in requires
        stream_jobs = {j.rid: j for j in jobs if j.deltas is not None}
        on_tokens = None
        if stream_jobs:
            def on_tokens(rid: int, delta: str) -> None:
                j = stream_jobs.get(rid)
                if j is not None:
                    j.deltas.put(delta)
        try:
            # kwarg only when streaming: engines predating on_tokens keep
            # working for non-streamed waves
            kw = {"on_tokens": on_tokens} if on_tokens is not None else {}
            results = self.engine.generate_batch(
                [j.request for j in jobs], **kw)
        except Exception as e:  # degrade, never kill the dispatcher
            logger.exception("engine batch failure")
            results = [
                GenerationResult(request_id=j.rid, finish_reason="error",
                                 error=str(e))
                for j in jobs
            ]
        self.batches_run += 1
        self.requests_served += len(jobs)
        self._inflight = {}
        by_id = {r.request_id: r for r in results}
        for job in jobs:
            job.result = by_id.get(
                job.rid, GenerationResult(request_id=job.rid,
                                          finish_reason="error",
                                          error="engine returned no result"))
            job.done()
            if job.deltas is not None:  # sentinel strictly after result
                job.deltas.put(None)


class _BatcherEngine:
    """Engine facade routing the JobManager's requests through the server's
    micro-batcher (``_Batcher.submit_job``), so durable-job chunk/reduce
    work pools into the same engine waves as interactive HTTP traffic —
    and never calls the raw engine concurrently with the dispatcher (raw
    engines do not accept concurrent ``generate_batch``).

    Streaming granularity: the batcher completes jobs per engine WAVE, so
    ``on_result`` deliveries (and therefore journal appends) advance at
    wave boundaries here; the direct pipeline path (JobManager over a raw
    continuous-scheduler engine) journals per request.  Either way the
    WAL advances inside the run, not at end-of-map."""

    schedules_internally = True  # the batcher admission-controls

    def __init__(self, batcher: _Batcher):
        self._batcher = batcher
        self._inflight: dict[int, _Job] = {}  # caller rid -> batcher job
        self._lock = threading.Lock()

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None,
                       on_tokens=None) -> list[GenerationResult]:
        import dataclasses

        # one shared completion signal for the whole call: any finishing
        # job sets it (done_cb rides the enqueue, so no completion can
        # race past the hookup) and the streaming loop wakes exactly then
        wake = threading.Event()

        def submit_one(req: GenerationRequest) -> _Job:
            # the batcher reassigns request_id at enqueue — submit a COPY
            # so the caller's id survives for result normalization
            job = self._batcher.submit_job(dataclasses.replace(req),
                                           done_cb=wake.set)
            with self._lock:
                self._inflight[req.request_id] = job
            return job

        def finish(req: GenerationRequest, job: _Job) -> GenerationResult:
            with self._lock:
                self._inflight.pop(req.request_id, None)
            return dataclasses.replace(job.result,
                                       request_id=req.request_id)

        if on_result is None:
            jobs = [(r, submit_one(r)) for r in requests]
            for _, job in jobs:
                job.event.wait()
            return [finish(r, j) for r, j in jobs]
        # streaming: deliver each result as its batcher job completes
        # (completion order), collecting retry submissions into the run
        pending = list(requests)
        live: list[tuple[GenerationRequest, _Job]] = []
        results: list[GenerationResult] = []

        def submit(more: list[GenerationRequest]) -> None:
            pending.extend(more)

        while pending or live:
            while pending:
                req = pending.pop(0)
                live.append((req, submit_one(req)))
            idx = next((k for k, (_r, j) in enumerate(live)
                        if j.event.is_set()), None)
            if idx is None:
                # clear-then-rescan: a completion between the scan above
                # and this wait already set ``wake``, so the wait returns
                # immediately and the next scan finds it
                wake.wait()
                wake.clear()
                continue
            req, job = live.pop(idx)
            res = finish(req, job)
            results.append(res)
            on_result(res, submit)
        return results

    def cancel(self, request_id: int) -> None:
        with self._lock:
            job = self._inflight.get(request_id)
        if job is not None:
            self._batcher.cancel(job)

    def shutdown(self) -> None:  # the server owns the real engine
        pass


def _anthropic_stop_reason(res: GenerationResult) -> str:
    """GenerationResult -> Anthropic ``stop_reason`` (one mapping for the
    plain and SSE paths).  ``deadline`` and ``shed`` pass through as
    extension values: collapsing them into ``max_tokens`` would make a
    zero-work shed indistinguishable from a normal truncated completion
    (docs/ROBUSTNESS.md promises the deadline outcomes stay visible)."""
    if res.stop_sequence is not None:
        return "stop_sequence"
    if res.finish_reason == "stop":
        return "end_turn"
    if res.finish_reason in ("deadline", "shed"):
        return res.finish_reason
    return "max_tokens"


def _clamp_max_tokens(value, cap: int) -> int:
    """0 is a real request for zero completion tokens — only None defaults."""
    n = 1000 if value is None else int(value)
    return min(max(n, 0), cap)


def _cache_prefix(body: dict) -> int | None:
    """The wire's ``cache_prefix`` hint (router-forwarded prefix-cache
    extension field): an integer char count, or None when absent/garbage.
    Bools are rejected — ``true`` is not a prefix length."""
    raw = body.get("cache_prefix")
    if isinstance(raw, bool) or not isinstance(raw, (int, float)):
        return None
    return int(raw)


def _flatten_messages(messages: list) -> tuple[list[str], list[str]]:
    """Shared messages[] collapse for both wire formats: system messages join
    the system prompt; user/tool turns concatenate in order; assistant turns
    become role-tagged context for the next user turn (multi-turn becomes a
    single serving prompt — same collapse the reference performs in reverse
    when it wraps one prompt as a messages array)."""
    system_parts, user_parts = [], []
    for msg in messages:
        role = msg.get("role", "user")
        content = msg.get("content", "")
        if isinstance(content, list):  # content-blocks form
            content = "".join(
                blk.get("text", "") for blk in content if isinstance(blk, dict))
        if role == "system":
            system_parts.append(content)
        elif role == "user" or role == "tool":
            user_parts.append(content)
        else:  # assistant turns are context for the next user turn
            user_parts.append(f"[assistant]: {content}")
    return system_parts, user_parts


def _chat_to_request(body: dict, max_tokens_cap: int) -> GenerationRequest:
    """OpenAI ``messages`` → one GenerationRequest."""
    system_parts, user_parts = _flatten_messages(body.get("messages", []))
    stop = body.get("stop") or body.get("stop_sequences") or ()
    if isinstance(stop, str):
        stop = (stop,)
    return GenerationRequest(
        prompt="\n\n".join(user_parts),
        system_prompt="\n\n".join(system_parts) or None,
        max_new_tokens=_clamp_max_tokens(body.get("max_tokens"),
                                         max_tokens_cap),
        temperature=float(body.get("temperature", 0.3)),
        top_p=float(body.get("top_p", 1.0)),
        # OpenAI extension (vLLM et al. accept it too); the router
        # forwards it so backend='http' samples like backend='jax'
        top_k=int(body.get("top_k", 0)),
        stop=tuple(stop),
        seed=body.get("seed"),
        cache_prefix=_cache_prefix(body),
    )


def _messages_to_request(body: dict, max_tokens_cap: int) -> GenerationRequest:
    """Anthropic messages → GenerationRequest (top-level ``system`` field —
    the real API shape; also tolerates the reference's system-role-in-messages
    bug, llm_executor.py:350-358, by routing those into the system prompt)."""
    system = body.get("system") or None
    if isinstance(system, list):  # content-block form of top-level system
        system = "".join(
            blk.get("text", "") for blk in system if isinstance(blk, dict))
    msg_system, user_parts = _flatten_messages(body.get("messages", []))
    system_parts = ([system] if system else []) + msg_system
    stop = body.get("stop_sequences") or ()
    if isinstance(stop, str):  # bare-string form, same guard as the chat path
        stop = (stop,)
    return GenerationRequest(
        prompt="\n\n".join(user_parts),
        system_prompt="\n\n".join(system_parts) or None,
        max_new_tokens=_clamp_max_tokens(body.get("max_tokens"),
                                         max_tokens_cap),
        temperature=float(body.get("temperature", 0.3)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0)),  # native Anthropic param
        stop=tuple(stop),
        cache_prefix=_cache_prefix(body),
    )


class EngineHTTPServer:
    """Threaded HTTP server bound to an Engine via the micro-batcher."""

    def __init__(self, engine: Engine, host: str = "127.0.0.1", port: int = 8000,
                 model_name: str = "lmrs-tpu", max_tokens_cap: int = 4096,
                 batch_window_s: float = 0.02, role: str = "both",
                 handoff_ttl_s: float = 60.0, jobs_dir: str | None = None,
                 live_dir: str | None = None, pipeline_config=None):
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"unknown serving role {role!r}; "
                             "want prefill|decode|both")
        self.engine = engine
        self.model_name = model_name
        self.max_tokens_cap = max_tokens_cap
        self.batcher = _Batcher(engine, window_s=batch_window_s)
        self.started = time.time()
        # Durable async jobs (docs/ROBUSTNESS.md § Durable jobs): with a
        # jobs_dir, POST/GET/DELETE /v1/jobs run a journaled JobManager
        # whose engine traffic rides the micro-batcher; interrupted
        # journals found in the directory re-queue at startup, so a job
        # survives a server crash/restart.  jobs_dir=None falls back to
        # LMRS_JOBS_DIR (JobsConfig); empty disables the API (501 — or
        # forwarding, when the engine is a router with job_request).
        self.jobs = None
        self.live = None
        if jobs_dir is None or live_dir is None or pipeline_config is not None:
            from lmrs_tpu.config import PipelineConfig

            pipeline_config = pipeline_config or PipelineConfig()
            if jobs_dir is None:
                jobs_dir = pipeline_config.jobs.jobs_dir
            if live_dir is None:
                live_dir = pipeline_config.live.sessions_dir
        if jobs_dir:
            from lmrs_tpu.jobs.manager import JobManager

            self.jobs = JobManager(_BatcherEngine(self.batcher), jobs_dir,
                                   config=pipeline_config)
            recovered = self.jobs.recover()
            if recovered:
                logger.info("job recovery: %d interrupted job(s) re-queued "
                            "from %s", recovered, jobs_dir)
        # Live sessions (docs/SERVING.md § Live sessions): with a
        # live_dir, POST/GET/DELETE /v1/sessions* run a journaled
        # SessionManager whose refresh waves ride the micro-batcher
        # (pooled with interactive traffic); session journals found in
        # the directory rehydrate at startup, so a session survives a
        # server crash/restart.  live_dir=None falls back to
        # LMRS_LIVE_DIR (LiveConfig); empty disables the API (501 — or
        # forwarding, when the engine is a router with session_request).
        if live_dir:
            from lmrs_tpu.live import SessionManager

            self.live = SessionManager(_BatcherEngine(self.batcher),
                                       live_dir, config=pipeline_config)
            rehydrated = self.live.recover()
            if rehydrated:
                logger.info("session recovery: %d live session(s) "
                            "rehydrated from %s", rehydrated, live_dir)
        # Disaggregated serving (docs/SERVING.md): the ROLE is a policy,
        # not a capability — a prefill-role server short-circuits only
        # requests that carry the handoff flag (plain requests still run
        # to completion, which is what makes the router's colocated
        # fallback graceful), and a decode-role server refuses to mint
        # tickets but serves everything else.
        self.role = role
        self.handoff_ttl_s = handoff_ttl_s
        self.handoff = TicketRegistry()       # prefill side: live tickets
        self._imported = ImportLog()          # decode side: dedup
        # Cross-host KV migration (docs/SERVING.md "KV fabric"): page-SET
        # tickets over the same export→fetch→ack lifecycle as request
        # handoff.  LMRS_KV_MIGRATE=0 disarms the whole surface — the
        # /v1/kv endpoints answer 501 and no migration state is reported,
        # so the wire stays byte-identical to the pre-fabric server.
        from lmrs_tpu.utils.env import env_bool

        self.kv_migrate = env_bool("LMRS_KV_MIGRATE", True)
        self.kv_tickets = TicketRegistry()    # export side: live page sets
        self._kv_imported = ImportLog()       # import side: dedup
        self._kv_lock = threading.Lock()
        # ticket -> encoded wire blob, pinned host-side until ack/expiry
        self._kv_payloads: dict[str, bytes] = {}  # guarded-by: _kv_lock
        from lmrs_tpu.obs import MetricsRegistry
        self._handoff_reg = MetricsRegistry()
        hc, hh = self._handoff_reg.counter, self._handoff_reg.histogram
        self._c_tickets = hc("lmrs_handoff_tickets_total",
                             "handoff tickets published (prefill side)")
        self._c_acks = hc("lmrs_handoff_acks_total",
                          "handoff acks accepted (prefill side)")
        self._c_dup_rejects = hc("lmrs_handoff_duplicate_rejects_total",
                                 "duplicate/stale imports rejected "
                                 "idempotently (decode side)")
        self._c_ack_failures = hc("lmrs_handoff_ack_failures_total",
                                  "acks lost after retries (pages left to "
                                  "the prefill orphan sweep)")
        self._h_transfer = hh("lmrs_handoff_transfer_seconds",
                              help="payload fetch prefill→decode",
                              unit="seconds")
        self._sweep_stop = threading.Event()
        self._sweeper = threading.Thread(target=self._sweep_loop, daemon=True)
        self._sweeper.start()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through our logger
                logger.debug("%s " + fmt, self.address_string(), *args)

            def _send(self, code: int, payload: dict) -> None:
                self._send_text(code, json.dumps(payload),
                                "application/json")

            def _send_text(self, code: int, text: str,
                           content_type: str) -> None:
                data = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _read_json(self) -> dict | None:
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    return json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return None

            def do_GET(self):
                if self.path == "/healthz":
                    # watchdog-degraded engines answer 503 + wedged:true
                    # (optional Engine hook, getattr convention): the
                    # supervisor SIGKILL-respawns on this signature and
                    # the router's probe refuses to re-admit the host —
                    # a wedged backend must not read as healthy just
                    # because its HTTP stack still answers
                    wedged = False
                    wedged_hook = getattr(outer.engine, "wedged", None)
                    if wedged_hook is not None:
                        try:
                            wedged = bool(wedged_hook())
                        except Exception:  # noqa: BLE001 - stay healthy
                            logger.debug("wedged hook failed",
                                         exc_info=True)
                    payload = {"status": "wedged" if wedged else "ok",
                               "wedged": wedged, "role": outer.role,
                               "uptime_s": round(
                                   time.time() - outer.started, 1)}
                    if wedged:
                        self._send(503, payload)
                        return
                    # compact radix summary (prefix-aware fleet routing,
                    # docs/SERVING.md): rides the probe path so the
                    # router's placement refresh costs one existing
                    # control-plane GET, no new endpoint.  Guarded —
                    # health must answer even if the summary hook breaks.
                    summary = getattr(outer.engine, "prefix_summary", None)
                    if summary is not None:
                        try:
                            payload["prefix_summary"] = summary()
                        except Exception:  # noqa: BLE001 - stay healthy
                            logger.debug("prefix summary failed",
                                         exc_info=True)
                    # burn-rate SLO state rides the probe path too (the
                    # router's placement penalty reads it); guarded the
                    # same way — health must answer even if it breaks
                    slo = getattr(outer.engine, "slo_report", None)
                    if slo is not None:
                        try:
                            payload["slo"] = slo()
                        except Exception:  # noqa: BLE001 - stay healthy
                            logger.debug("slo report failed",
                                         exc_info=True)
                    self._send(200, payload)
                elif self.path == "/v1/usage":
                    self._get_usage()
                elif self.path == "/v1/anatomy":
                    self._get_anatomy()
                elif self.path == "/v1/trace":
                    self._get_trace()
                elif self.path.startswith("/v1/handoff/"):
                    self._get_handoff(self.path.split("/")[3])
                elif self.path.startswith("/v1/kv/"):
                    self._get_kv(self.path.split("/")[3])
                elif (self.path == "/v1/jobs"
                        or self.path.startswith("/v1/jobs/")):
                    code, payload = outer._job_http("GET", self.path, None)
                    self._send(code, payload)
                elif (self.path.split("?", 1)[0] == "/v1/sessions"
                        or self.path.startswith("/v1/sessions/")):
                    path, _, query = self.path.partition("?")
                    code, payload = outer._session_http("GET", path, None,
                                                        query=query)
                    self._send(code, payload)
                elif self.path == "/v1/models":
                    self._send(200, {"object": "list", "data": [
                        {"id": outer.model_name, "object": "model",
                         "owned_by": "lmrs-tpu"}]})
                elif self.path == "/metrics":
                    # content negotiation: Prometheus text for scrapers
                    # (Accept: text/plain / OpenMetrics), the original JSON
                    # report otherwise — existing clients (the router's
                    # aggregate, the tests) keep their wire format
                    accept = self.headers.get("Accept", "") or ""
                    if "text/plain" in accept or "openmetrics" in accept:
                        self._send_text(
                            200, outer.prometheus_text(),
                            "text/plain; version=0.0.4; charset=utf-8")
                        return
                    payload = {
                        "engine": outer.engine.engine_metrics(),
                        "http_batches": outer.batcher.batches_run,
                        "http_requests": outer.batcher.requests_served,
                        "handoff": outer.handoff_stats(),
                    }
                    if outer.kv_migrate:
                        # key absent with LMRS_KV_MIGRATE=0: the kill
                        # switch keeps this wire document byte-identical
                        payload["kv_migrate"] = outer.kv_stats()
                    # the radix summary rides the JSON control plane too
                    # (operators' view; the router refreshes via /healthz)
                    summary = getattr(outer.engine, "prefix_summary", None)
                    if summary is not None:
                        try:
                            payload["prefix_summary"] = summary()
                        except Exception:  # noqa: BLE001 - best effort
                            pass
                    if outer.jobs is not None:
                        payload["jobs"] = outer.jobs.stats()
                    if outer.live is not None:
                        payload["live"] = outer.live.stats()
                    self._send(200, payload)
                else:
                    self._send(404, {"error": {"message": f"no route {self.path}"}})

            def _client_gone(self) -> bool:
                """Best-effort disconnect probe for non-streaming waits: a
                MSG_PEEK read returning b'' means the peer sent FIN.  The
                request body was fully read before submit, so pending data
                (→ still connected) is not expected but also not an error.

                Known tradeoff: a client that half-closes after POSTing
                (shutdown(SHUT_WR)) but still reads peeks identically to a
                gone client, so its generation is cancelled and it gets a
                truncated finish_reason="cancelled" response.  Treating an
                early client FIN as abort matches common HTTP server
                practice (e.g. nginx's default); half-close POST clients
                are rare and still receive a well-formed response."""
                # injection site: a fired plan reports the client gone —
                # driving the disconnect->cancel propagation path without
                # a real socket teardown
                if faults.check("server.client_disconnect"):
                    return True
                try:
                    self.connection.setblocking(False)
                    try:
                        data = self.connection.recv(1, socket.MSG_PEEK)
                    finally:
                        self.connection.setblocking(True)
                except (BlockingIOError, InterruptedError):
                    return False  # nothing to read: still connected
                except OSError:
                    return True
                return data == b""

            def _apply_trace(self, req: GenerationRequest) -> None:
                """Anchor (or MINT — this server is ingress) the request's
                distributed trace id from the ``X-LMRS-Trace`` header.
                Every request gets one: the engine keys its span track on
                it, forwards resend it, and the handoff ticket/journal
                carry it.  ``_trace_minted`` records whether the id was
                born here — a locally-minted id yields to the trace a
                handoff payload arrives with (_apply_handoff)."""
                supplied = clean_trace_id(self.headers.get("X-LMRS-Trace"))
                self._trace_minted = supplied is None
                req.trace_id = supplied or new_trace_id()

            def _apply_tenant(self, req: GenerationRequest,
                              body: dict) -> None:
                """Anchor the request's cost-attribution tenant from the
                ``X-LMRS-Tenant`` header (or the ``tenant`` body field —
                header wins), minted at THIS ingress and propagated like
                the trace id.  Absent/garbage mints the explicit
                "default" tenant — anonymous ingress shares ONE named
                bucket QoS weights can be configured for, instead of an
                implicit None.  The QoS priority class
                (``X-LMRS-QoS-Class`` / ``qos_class`` body field) rides
                the same ingress, parsed only while LMRS_QOS is armed so
                the kill switch keeps the wire byte-identical."""
                supplied = (clean_tenant(self.headers.get("X-LMRS-Tenant"))
                            or clean_tenant(body.get("tenant")))
                # minted-here flag (the _trace_minted analog): a locally
                # minted "default" yields to the tenant a handoff payload
                # carried across the pod boundary (_apply_handoff)
                self._tenant_minted = supplied is None
                req.tenant = supplied or DEFAULT_TENANT
                from lmrs_tpu.fleet.qos import qos_enabled

                if qos_enabled():
                    req.qos_class = (
                        clean_qos_class(
                            self.headers.get("X-LMRS-QoS-Class"))
                        or clean_qos_class(body.get("qos_class")))

            def _apply_deadline(self, req: GenerationRequest,
                                body: dict) -> bool:
                """Anchor the wire deadline budget (RELATIVE seconds from
                the ``X-LMRS-Deadline`` header, or the ``deadline_s`` body
                field — header wins) to this server's clock.  Returns
                False (after answering 400) on an unparseable value: a
                silently dropped deadline would run the request
                unbounded, the opposite of what the client asked for."""
                raw = self.headers.get("X-LMRS-Deadline")
                if raw is None:
                    raw = body.get("deadline_s")
                if raw is None:
                    return True
                try:
                    budget = float(raw)
                    # NaN poisons every downstream comparison (a NaN
                    # deadline sheds on one engine and runs unbounded on
                    # another) and inf is "no deadline" spelled wrong —
                    # both are garbage, not budgets
                    if not math.isfinite(budget):
                        raise ValueError(budget)
                except (TypeError, ValueError):
                    self._send(400, {"error": {
                        "message": f"invalid deadline budget {raw!r} "
                                   "(want finite seconds as a number)"}})
                    return False
                req.deadline_s = time.time() + budget
                return True

            # ---------------------------------------------- usage export

            def _get_usage(self) -> None:
                """``GET /v1/usage``: this host's per-tenant cost-ledger
                rollups (or, when the engine is a router, the FLEET
                aggregation — RouterEngine.usage_report pulls every
                backend's page and merges).  501 when the engine carries
                no ledger hook."""
                hook = getattr(outer.engine, "usage_report", None)
                if hook is None:
                    self._send(501, {"error": {
                        "message": "this engine backend has no cost "
                                   "ledger", "type": "usage_error"}})
                    return
                try:
                    doc = hook()
                    # per-tenant quota/burn chargeback block (fleet/
                    # qos.py): windowed device-seconds against configured
                    # weight.  Guarded getattr like the /healthz slo
                    # block — engines without the policy (or routers
                    # whose report already aggregated one) just omit it.
                    qos = getattr(outer.engine, "qos_report", None)
                    if qos is not None and "qos" not in doc:
                        try:
                            q = qos()
                            # omitted (not enabled:false) when disarmed:
                            # LMRS_QOS=0 keeps the wire byte-identical
                            if q.get("enabled"):
                                doc["qos"] = q
                        except Exception:  # noqa: BLE001 - stay healthy
                            logger.debug("qos report failed",
                                         exc_info=True)
                    self._send(200, doc)
                except Exception as e:  # noqa: BLE001 - marked error
                    logger.exception("usage report failed")
                    self._send(502, {"error": {
                        "message": f"usage report failed: "
                                   f"{type(e).__name__}: {e}",
                        "type": "usage_error"}})

            def _get_anatomy(self) -> None:
                """``GET /v1/anatomy``: this host's step-anatomy document
                (or, when the engine is a router, the fleet merge —
                RouterEngine.anatomy_report pulls every backend's page).
                501 when the backend carries no anatomy (static
                scheduler, or LMRS_ANATOMY=0 on this host)."""
                hook = getattr(outer.engine, "anatomy_report", None)
                if hook is None:
                    self._send(501, {"error": {
                        "message": "this engine backend has no step "
                                   "anatomy", "type": "anatomy_error"}})
                    return
                try:
                    doc = hook()
                    if not doc.get("enabled"):
                        self._send(501, {"error": {
                            "message": "step anatomy is disabled "
                                       "(LMRS_ANATOMY=0)",
                            "type": "anatomy_error"}})
                        return
                    self._send(200, doc)
                except Exception as e:  # noqa: BLE001 - marked error
                    logger.exception("anatomy report failed")
                    self._send(502, {"error": {
                        "message": f"anatomy report failed: "
                                   f"{type(e).__name__}: {e}",
                        "type": "anatomy_error"}})

            # --------------------------------------- trace export / profile

            def _get_trace(self) -> None:
                """``GET /v1/trace``: this host's trace ring as a Chrome-
                trace JSON document — or, when the engine is a router
                (``stitched_trace`` hook), the whole fleet's buffers
                pulled, clock-aligned, and merged into one Perfetto trace
                (obs.stitch_traces).  409 when tracing is off here (arm
                with LMRS_TRACE=1 / ``lmrs-serve --trace``)."""
                stitch = getattr(outer.engine, "stitched_trace", None)
                if stitch is not None:
                    try:
                        self._send(200, stitch())
                    except Exception as e:  # noqa: BLE001 - marked error
                        logger.exception("trace stitch failed")
                        self._send(502, {"error": {
                            "message": f"trace stitch failed: "
                                       f"{type(e).__name__}: {e}",
                            "type": "trace_error"}})
                    return
                tr = get_tracer()
                if tr is None:
                    self._send(409, {"error": {
                        "message": "tracing is not enabled on this host "
                                   "(start lmrs-serve with --trace or "
                                   "LMRS_TRACE=1)",
                        "type": "trace_error"}})
                    return
                self._send(200, tr.payload(
                    host=f"{outer.host}:{outer.port}"))

            def _post_profile(self, body: dict) -> None:
                """``POST /v1/debug/profile``: bounded on-demand
                jax.profiler capture via the engine's ``debug_profile``
                hook.  Body: ``{"duration_s": 2.0, "out_dir": "..."}``
                (out_dir defaults to LMRS_PROFILE_DIR)."""
                hook = getattr(outer.engine, "debug_profile", None)
                if hook is None:
                    self._send(501, {"error": {
                        "message": "this engine backend has no profiler "
                                   "(jax backend only)",
                        "type": "profile_error"}})
                    return
                try:
                    duration = float(body.get("duration_s", 2.0))
                except (TypeError, ValueError):
                    self._send(400, {"error": {
                        "message": "duration_s must be a number",
                        "type": "profile_error"}})
                    return
                from lmrs_tpu.obs.perf import default_profile_dir

                out_dir = body.get("out_dir") or default_profile_dir()
                ok, msg = hook(duration, str(out_dir))
                if not ok:
                    self._send(409, {"error": {"message": msg,
                                               "type": "profile_error"}})
                    return
                self._send(200, {"status": "capturing", "dir": msg,
                                 "duration_s": duration})

            # -------------------------------------- disaggregated handoff

            def _get_handoff(self, ticket: str) -> None:
                """Serve a pinned page-set payload to the pulling decode
                pod.  Unknown / expired / consumed tickets are 410 Gone —
                the decode side then reports a handoff error and the
                router re-prefills (at-most-once: a consumed ticket can
                never be served again)."""
                rec = outer.handoff.lookup(ticket)
                export = getattr(outer.engine, "export_handoff", None)
                if rec is None or export is None:
                    self._send(410, {"error": {
                        "message": f"handoff ticket {ticket} gone "
                                   "(expired, consumed, or unknown)",
                        "type": "handoff_error"}})
                    return
                try:
                    data = encode_payload(export(rec["rid"]))
                except KeyError:
                    # pinned pages already swept (engine-side TTL)
                    self._send(410, {"error": {
                        "message": f"handoff ticket {ticket} gone "
                                   "(pages reclaimed)",
                        "type": "handoff_error"}})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _ack_handoff(self, ticket: str) -> None:
                """Consume a ticket exactly once and release its pinned
                pages.  Duplicate/late acks answer 410 and free nothing
                (release is idempotent engine-side too)."""
                rid = outer.handoff.consume(ticket)
                if rid is None:
                    self._send(410, {"error": {
                        "message": f"handoff ticket {ticket} not ackable "
                                   "(expired, consumed, or unknown)",
                        "type": "handoff_error"}})
                    return
                release = getattr(outer.engine, "release_handoff", None)
                pages = release(rid) if release is not None else 0
                outer._c_acks.inc()
                self._send(200, {"status": "acked", "pages_released": pages})

            def _apply_handoff(self, req: GenerationRequest,
                               body: dict) -> bool:
                """Wire the body's ``handoff`` field onto the request.
                ``true`` asks for a prefill-role export (ignored — i.e.
                colocated full generation — when this server's role or
                engine cannot honor it, or when the client streams);
                a descriptor object asks for a decode-role import (the
                payload is pulled from the source pod and acked here).
                Returns False after answering an error response."""
                h = body.get("handoff")
                if h in (None, False):
                    return True
                supported = getattr(outer.engine, "supports_handoff", False)
                if h is True:
                    if (outer.role != "decode" and supported
                            and not body.get("stream")):
                        req.handoff_export = True
                    return True
                if not isinstance(h, dict):
                    self._send(400, {"error": {
                        "message": "handoff must be true or a ticket "
                                   "descriptor object",
                        "type": "handoff_error"}})
                    return False
                if outer.role == "prefill" or not supported:
                    self._send(409, {"error": {
                        "message": "this host does not import handoffs "
                                   f"(role={outer.role})",
                        "type": "handoff_error"}})
                    return False
                payload, err = outer._fetch_handoff(h)
                if err is not None:
                    self._send(err[0], err[1])
                    return False
                req.handoff_state = payload
                # a locally-minted trace id yields to the one the payload
                # carried across the pod boundary (a router-forwarded
                # request sent the header, so the two are already equal)
                if (getattr(self, "_trace_minted", False)
                        and clean_trace_id(payload.get("trace_id"))):
                    req.trace_id = payload["trace_id"]
                # same adoption rule for the tenant label: the decode leg
                # bills to the tenant the prefill leg was billed to (a
                # locally-MINTED "default" counts as absent — only a
                # client-supplied label outranks the payload's)
                if ((req.tenant is None
                     or getattr(self, "_tenant_minted", False))
                        and clean_tenant(payload.get("tenant"))):
                    req.tenant = payload["tenant"]
                # the class label rides the payload the same way — the
                # decode leg competes in the class the prefill leg was
                # admitted under
                if req.qos_class is None:
                    req.qos_class = clean_qos_class(
                        payload.get("qos_class"))
                return True

            # ------------------------------------ KV-fabric migration wire
            # (docs/SERVING.md "KV fabric"): the same pull-model
            # export→fetch→ack lifecycle as request handoff, but the unit
            # is a PREAMBLE PAGE SET, not an in-flight request.  All four
            # routes answer 501 when LMRS_KV_MIGRATE=0 or the engine lacks
            # the hooks — the kill switch hides the surface entirely.

            def _kv_disarmed(self) -> bool:
                if outer.kv_migrate:
                    return False
                self._send(501, {"error": {
                    "message": "KV migration disabled (LMRS_KV_MIGRATE=0)",
                    "type": "kv_migrate_error"}})
                return True

            def _post_kv_export(self, body: dict) -> None:
                """Capture one warm preamble's page set and publish a
                ticket for it.  404 when the preamble is cold/unknown here
                (or the engine is mid-run — the caller retries); the blob
                stays pinned server-side until ack or TTL expiry."""
                if self._kv_disarmed():
                    return
                export = getattr(outer.engine, "kv_export", None)
                if export is None:
                    self._send(501, {"error": {
                        "message": "this engine backend has no KV page-set "
                                   "export", "type": "kv_migrate_error"}})
                    return
                preamble = body.get("preamble")
                if not isinstance(preamble, str) or not preamble:
                    self._send(400, {"error": {
                        "message": "body needs a preamble hash string",
                        "type": "kv_migrate_error"}})
                    return
                try:
                    payload = export(preamble)
                except Exception as e:  # noqa: BLE001 - marked error
                    logger.exception("kv export failed")
                    self._send(502, {"error": {
                        "message": f"kv export failed: "
                                   f"{type(e).__name__}: {e}",
                        "type": "kv_migrate_error"}})
                    return
                if payload is None:
                    self._send(404, {"error": {
                        "message": f"preamble {preamble} is not warm here "
                                   "(cold, unknown, or engine busy)",
                        "type": "kv_migrate_error"}})
                    return
                data = encode_payload(payload)
                ttl = outer.handoff_ttl_s
                tid = outer.kv_tickets.create(preamble, time.time() + ttl)
                with outer._kv_lock:
                    outer._kv_payloads[tid] = data
                self._send(200, {
                    "object": "kv.ticket",
                    "ticket": tid,
                    "preamble": preamble,
                    "tokens": int(payload.get("tokens", 0)),
                    "bytes": len(data),
                    "expires_in_s": ttl,
                })

            def _get_kv(self, ticket: str) -> None:
                """Serve a pinned page-set blob to the pulling sibling.
                Unknown / expired / consumed → 410 (at-most-once, same
                contract as request-handoff tickets)."""
                if self._kv_disarmed():
                    return
                rec = outer.kv_tickets.lookup(ticket)
                with outer._kv_lock:
                    data = outer._kv_payloads.get(ticket)
                if rec is None or data is None:
                    self._send(410, {"error": {
                        "message": f"kv ticket {ticket} gone (expired, "
                                   "consumed, or unknown)",
                        "type": "kv_migrate_error"}})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _ack_kv(self, ticket: str) -> None:
                """Consume a kv ticket exactly once and drop its pinned
                blob.  Duplicate/late acks answer 410 and free nothing —
                a LOST ack leaves the blob to the orphan sweep."""
                if self._kv_disarmed():
                    return
                if outer.kv_tickets.consume(ticket) is None:
                    self._send(410, {"error": {
                        "message": f"kv ticket {ticket} not ackable "
                                   "(expired, consumed, or unknown)",
                        "type": "kv_migrate_error"}})
                    return
                with outer._kv_lock:
                    outer._kv_payloads.pop(ticket, None)
                self._send(200, {"status": "acked"})

            def _post_kv_import(self, body: dict) -> None:
                """Pull a page-set blob from its source host and install
                it into this engine's prefix cache.  Duplicate tickets
                are 409 idempotent (the source's pages free via the
                orphan sweep even when the first import's ack was lost);
                geometry mismatch is 409 too — the router falls back to
                cold resume, never a wedged import."""
                if self._kv_disarmed():
                    return
                imp = getattr(outer.engine, "kv_import", None)
                if imp is None:
                    self._send(501, {"error": {
                        "message": "this engine backend has no KV page-set "
                                   "import", "type": "kv_migrate_error"}})
                    return
                tid, source = body.get("ticket"), body.get("source")
                if not tid or not source:
                    self._send(400, {"error": {
                        "message": "body needs ticket + source",
                        "type": "kv_migrate_error"}})
                    return
                if outer._kv_imported.seen(tid):
                    self._send(409, {"error": {
                        "message": f"duplicate kv import of ticket {tid} "
                                   "(already imported on this host)",
                        "type": "kv_migrate_error"}})
                    return
                payload, err = outer._fetch_kv(tid, source)
                if err is not None:
                    self._send(err[0], err[1])
                    return
                try:
                    tokens = imp(payload)
                except RuntimeError as e:  # engine busy: retryable
                    self._send(503, {"error": {
                        "message": f"kv import deferred: {e}",
                        "type": "kv_migrate_error"}})
                    return
                except ValueError as e:  # geometry/framing: permanent
                    self._send(409, {"error": {
                        "message": f"kv import rejected: {e}",
                        "type": "kv_migrate_error"}})
                    return
                except Exception as e:  # noqa: BLE001 - marked error
                    logger.exception("kv import failed")
                    self._send(502, {"error": {
                        "message": f"kv import failed: "
                                   f"{type(e).__name__}: {e}",
                        "type": "kv_migrate_error"}})
                    return
                if not outer._kv_imported.add(tid):
                    # raced a concurrent duplicate of the same ticket:
                    # the cache insert is idempotent (same ids, same
                    # bytes), so answer 409 without undoing anything
                    self._send(409, {"error": {
                        "message": f"duplicate kv import of ticket {tid}",
                        "type": "kv_migrate_error"}})
                    return
                outer._send_kv_ack(tid, source)
                self._send(200, {"status": "imported",
                                 "imported_tokens": tokens})

            def do_DELETE(self):
                if self.path.startswith("/v1/jobs/"):
                    code, payload = outer._job_http("DELETE", self.path, None)
                    self._send(code, payload)
                elif self.path.startswith("/v1/sessions/"):
                    code, payload = outer._session_http("DELETE", self.path,
                                                        None)
                    self._send(code, payload)
                else:
                    self._send(404, {"error": {"message": f"no route {self.path}"}})

            def do_POST(self):
                if (self.path.startswith("/v1/handoff/")
                        and self.path.endswith("/ack")):
                    self._ack_handoff(self.path.split("/")[3])
                    return
                if (self.path.startswith("/v1/kv/")
                        and self.path.endswith("/ack")):
                    self._ack_kv(self.path.split("/")[3])
                    return
                body = self._read_json()
                if body is None:
                    self._send(400, {"error": {"message": "invalid JSON body"}})
                    return
                if self.path == "/v1/kv/export":
                    self._post_kv_export(body)
                    return
                if self.path == "/v1/kv/import":
                    self._post_kv_import(body)
                    return
                if self.path == "/v1/debug/profile":
                    self._post_profile(body)
                    return
                if self.path == "/v1/jobs":
                    code, payload = outer._job_http(
                        "POST", self.path, body,
                        trace_id=clean_trace_id(
                            self.headers.get("X-LMRS-Trace")),
                        tenant=clean_tenant(
                            self.headers.get("X-LMRS-Tenant")))
                    self._send(code, payload)
                    return
                if (self.path == "/v1/sessions"
                        or self.path.startswith("/v1/sessions/")):
                    code, payload = outer._session_http(
                        "POST", self.path, body,
                        trace_id=clean_trace_id(
                            self.headers.get("X-LMRS-Trace")),
                        tenant=clean_tenant(
                            self.headers.get("X-LMRS-Tenant")))
                    self._send(code, payload)
                    return
                try:
                    if self.path == "/v1/chat/completions":
                        req = _chat_to_request(body, outer.max_tokens_cap)
                        self._apply_trace(req)
                        self._apply_tenant(req, body)
                        if not self._apply_deadline(req, body):
                            return
                        if not self._apply_handoff(req, body):
                            return
                        if body.get("stream"):
                            self._stream_openai(
                                body, outer.batcher.submit_stream(req))
                            return
                        res = outer.batcher.submit(
                            req, poll_disconnect=self._client_gone)
                        # always attempt the write: a half-closed client
                        # (shutdown(SHUT_WR)) peeks as gone but still reads,
                        # and a disconnect can race normal completion — a
                        # dead socket just raises, swallowed below
                        try:
                            if res.finish_reason == "handoff":
                                self._respond_ticket(res, req)
                            else:
                                self._respond_openai(body, res)
                        except OSError:
                            logger.debug("client gone before response write")
                        return
                    elif self.path == "/v1/messages":
                        req = _messages_to_request(body, outer.max_tokens_cap)
                        self._apply_trace(req)
                        self._apply_tenant(req, body)
                        if not self._apply_deadline(req, body):
                            return
                        if not self._apply_handoff(req, body):
                            return
                        if body.get("stream"):
                            self._stream_anthropic(
                                body, outer.batcher.submit_stream(req))
                            return
                        res = outer.batcher.submit(
                            req, poll_disconnect=self._client_gone)
                        try:
                            if res.finish_reason == "handoff":
                                self._respond_ticket(res, req)
                            else:
                                self._respond_anthropic(body, res)
                        except OSError:
                            logger.debug("client gone before response write")
                        return
                    else:
                        self._send(404, {"error": {"message": f"no route {self.path}"}})
                except Exception as e:
                    logger.exception("request handling failed")
                    self._send(500, {"error": {"message": str(e)}})

            def _respond_ticket(self, res: GenerationResult,
                                req: GenerationRequest) -> None:
                """Publish a handoff ticket for a prefill-role completion:
                the request stopped after its first token with pages
                pinned; the ticket is what the router follows to the
                decode pool.  Never reaches plain clients — only requests
                that ASKED for handoff can produce finish_reason='handoff'.
                The request's trace id rides the ticket so the decode leg
                continues the same distributed trace."""
                ttl = outer.handoff_ttl_s
                tid = outer.handoff.create(res.request_id,
                                           time.time() + ttl,
                                           trace_id=req.trace_id)
                outer._c_tickets.inc()
                self._send(200, {
                    "object": "handoff.ticket",
                    "handoff": {
                        "ticket": tid,
                        "first_text": res.text,
                        "prompt_tokens": res.prompt_tokens,
                        "completion_tokens": res.completion_tokens,
                        "expires_in_s": ttl,
                        "trace": req.trace_id,
                    },
                })

            # ------------------------------------------------ SSE streaming

            def _sse_headers(self) -> None:
                # no Content-Length: the connection closes to end the body
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                self.close_connection = True

            def _sse(self, data: str, event: str | None = None) -> None:
                frame = (f"event: {event}\n" if event else "") + f"data: {data}\n\n"
                self.wfile.write(frame.encode())
                self.wfile.flush()

            def _drain(self, job: _Job):
                """Yield deltas until the dispatcher's sentinel; afterwards
                ``job.result`` is guaranteed set.  Polls for client
                disconnect WHILE WAITING: SSE write failures only catch a
                disconnect when deltas flow, but a stream can be silent
                for long stretches (prefill phase; byte models emitting
                invalid-UTF-8 partials that never flush) — without the
                poll an abandoned silent stream decodes to max_tokens."""
                while True:
                    try:
                        d = job.deltas.get(timeout=0.5)
                    except queue.Empty:
                        if not job.cancelled and self._client_gone():
                            logger.debug(
                                "silent stream client disconnected; "
                                "cancelling")
                            outer.batcher.cancel(job)
                        continue
                    if d is None:
                        return
                    yield d

            def _stream_openai(self, body: dict, job: _Job) -> None:
                """OpenAI chat.completion.chunk SSE (llm_executor.py:292's
                API, streaming form): role chunk, content deltas, finish
                chunk (+usage with stream_options.include_usage), [DONE]."""
                cid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
                created = int(time.time())
                model = body.get("model") or outer.model_name

                def chunk(delta: dict, finish=None, usage=None) -> None:
                    payload = {
                        "id": cid, "object": "chat.completion.chunk",
                        "created": created, "model": model,
                        "choices": [{"index": 0, "delta": delta,
                                     "finish_reason": finish}],
                    }
                    if usage is not None:
                        payload["usage"] = usage
                    self._sse(json.dumps(payload))

                self._sse_headers()
                try:
                    chunk({"role": "assistant", "content": ""})
                    for delta in self._drain(job):
                        chunk({"content": delta})
                    res = job.result
                    if res.error is not None:
                        self._sse(json.dumps({"error": {
                            "message": res.error, "type": "engine_error"}}))
                    else:
                        want_usage = (body.get("stream_options") or {}).get(
                            "include_usage")
                        chunk({}, finish=res.finish_reason,
                              usage={"prompt_tokens": res.prompt_tokens,
                                     "completion_tokens": res.completion_tokens,
                                     "total_tokens": res.total_tokens,
                                     **({"cost": res.usage}
                                        if res.usage else {})}
                              if want_usage else None)
                    self._sse("[DONE]")
                except OSError:  # client went away: stop writing AND abort
                    # the generation — without this the engine decodes an
                    # abandoned request to max_tokens holding its slot+pages
                    logger.debug("stream client disconnected; cancelling")
                    outer.batcher.cancel(job)

            def _stream_anthropic(self, body: dict, job: _Job) -> None:
                """Anthropic messages SSE (llm_executor.py:378's API,
                streaming form): message_start, one text content block of
                deltas, message_delta with stop_reason/usage, message_stop."""
                mid = f"msg_{uuid.uuid4().hex[:24]}"
                model = body.get("model") or outer.model_name
                self._sse_headers()
                try:
                    self._sse(json.dumps({
                        "type": "message_start",
                        "message": {
                            "id": mid, "type": "message", "role": "assistant",
                            "model": model, "content": [],
                            "stop_reason": None, "stop_sequence": None,
                            # input_tokens unknown until the engine encodes:
                            # corrected in the closing message_delta usage
                            "usage": {"input_tokens": 0, "output_tokens": 0},
                        }}), event="message_start")
                    self._sse(json.dumps({
                        "type": "content_block_start", "index": 0,
                        "content_block": {"type": "text", "text": ""}}),
                        event="content_block_start")
                    for delta in self._drain(job):
                        self._sse(json.dumps({
                            "type": "content_block_delta", "index": 0,
                            "delta": {"type": "text_delta", "text": delta}}),
                            event="content_block_delta")
                    res = job.result
                    if res.error is not None:
                        self._sse(json.dumps({
                            "type": "error",
                            "error": {"type": "api_error",
                                      "message": res.error}}), event="error")
                        return
                    self._sse(json.dumps({
                        "type": "content_block_stop", "index": 0}),
                        event="content_block_stop")
                    self._sse(json.dumps({
                        "type": "message_delta",
                        "delta": {"stop_reason": _anthropic_stop_reason(res),
                                  "stop_sequence": res.stop_sequence},
                        "usage": {"input_tokens": res.prompt_tokens,
                                  "output_tokens": res.completion_tokens,
                                  **({"cost": res.usage}
                                     if res.usage else {})}}),
                        event="message_delta")
                    self._sse(json.dumps({"type": "message_stop"}),
                              event="message_stop")
                except OSError:  # same contract as the OpenAI stream path
                    logger.debug("stream client disconnected; cancelling")
                    outer.batcher.cancel(job)

            def _respond_openai(self, body: dict, res: GenerationResult) -> None:
                if res.error is not None:
                    self._send(500, {"error": {"message": res.error,
                                               "type": "engine_error"}})
                    return
                self._send(200, {
                    "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
                    "object": "chat.completion",
                    "created": int(time.time()),
                    "model": body.get("model") or outer.model_name,
                    "choices": [{
                        "index": 0,
                        "message": {"role": "assistant", "content": res.text},
                        "finish_reason": res.finish_reason,
                    }],
                    "usage": {
                        "prompt_tokens": res.prompt_tokens,
                        "completion_tokens": res.completion_tokens,
                        "total_tokens": res.total_tokens,
                        # ledger extension: absent (byte-identical wire)
                        # with LMRS_COST_LEDGER=0
                        **({"cost": res.usage} if res.usage else {}),
                    },
                })

            def _respond_anthropic(self, body: dict, res: GenerationResult) -> None:
                if res.error is not None:
                    self._send(500, {"type": "error",
                                     "error": {"type": "api_error",
                                               "message": res.error}})
                    return
                self._send(200, {
                    "id": f"msg_{uuid.uuid4().hex[:24]}",
                    "type": "message",
                    "role": "assistant",
                    "model": body.get("model") or outer.model_name,
                    "content": [{"type": "text", "text": res.text}],
                    "stop_reason": _anthropic_stop_reason(res),
                    "stop_sequence": res.stop_sequence,
                    "usage": {"input_tokens": res.prompt_tokens,
                              "output_tokens": res.completion_tokens,
                              **({"cost": res.usage} if res.usage else {})},
                })

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address[:2]

    # ------------------------------------------------ durable-job plumbing

    def _job_http(self, method: str, path: str, body: dict | None,
                  trace_id: str | None = None,
                  tenant: str | None = None):
        """The /v1/jobs surface: returns ``(status, payload)``.

        Local-first: a configured JobManager answers here.  Without one,
        an engine exposing ``job_request`` (RouterEngine) forwards to the
        backend fleet — jobs live next to the engine that runs them, so
        their journals survive that host's restarts.  Neither → 501.
        ``trace_id`` (the submit header) rides into the job journal so a
        recovered job continues its trace."""
        if self.jobs is None:
            forward = getattr(self.engine, "job_request", None)
            if forward is not None:
                try:
                    return forward(method, path, body, trace_id=trace_id,
                                   tenant=tenant)
                except Exception as e:  # noqa: BLE001 - marked, never a 500 crash
                    logger.exception("job forward failed")
                    return 502, {"error": {
                        "message": f"job forward failed: "
                                   f"{type(e).__name__}: {e}",
                        "type": "job_error"}}
            return 501, {"error": {
                "message": "job API disabled on this host; start lmrs-serve "
                           "with --jobs-dir (or LMRS_JOBS_DIR)",
                "type": "job_error"}}
        if method == "POST":
            transcript = (body or {}).get("transcript")
            if not isinstance(transcript, dict) or not isinstance(
                    transcript.get("segments"), list):
                return 400, {"error": {
                    "message": "body needs transcript.segments (a transcript "
                               "JSON object), plus optional params",
                    "type": "job_error"}}
            try:
                job = self.jobs.submit(transcript,
                                       (body or {}).get("params"),
                                       trace_id=trace_id, tenant=tenant)
            except ValueError as e:  # unknown/malformed param values
                return 400, {"error": {"message": str(e),
                                       "type": "job_error"}}
            except Exception as e:  # noqa: BLE001 - e.g. jobs_dir disk full:
                # a 5xx body, never a dropped connection
                logger.exception("job submit failed")
                return 500, {"error": {
                    "message": f"job submit failed: {type(e).__name__}: {e}",
                    "type": "job_error"}}
            return 200, self.jobs.status_doc(job)
        if method == "GET" and path == "/v1/jobs":
            return 200, {"object": "list",
                         "data": [self.jobs.status_doc(j)
                                  for j in self.jobs.jobs()]}
        jid = path.split("/v1/jobs/", 1)[-1].strip("/")
        job = self.jobs.get(jid)
        if job is None:
            return 404, {"error": {"message": f"no job {jid}",
                                   "type": "job_error"}}
        if method == "DELETE":
            job = self.jobs.cancel(jid) or job
        return 200, self.jobs.status_doc(job)

    # ---------------------------------------------- live-session plumbing

    def _session_http(self, method: str, path: str, body: dict | None,
                      trace_id: str | None = None, query: str = "",
                      tenant: str | None = None):
        """The /v1/sessions surface: returns ``(status, payload)``.

        Local-first like jobs: a configured SessionManager answers here;
        without one, an engine exposing ``session_request``
        (RouterEngine) forwards to the backend fleet sticky-by-session-id
        — a session's journal AND its warm prefix tree live with the
        backend that runs it.  Neither → 501."""
        if self.live is None:
            forward = getattr(self.engine, "session_request", None)
            if forward is not None:
                try:
                    full = path + (f"?{query}" if query else "")
                    return forward(method, full, body, trace_id=trace_id,
                                   tenant=tenant)
                except Exception as e:  # noqa: BLE001 - marked, never a crash
                    logger.exception("session forward failed")
                    return 502, {"error": {
                        "message": f"session forward failed: "
                                   f"{type(e).__name__}: {e}",
                        "type": "session_error"}}
            return 501, {"error": {
                "message": "session API disabled on this host; start "
                           "lmrs-serve with --live-dir (or LMRS_LIVE_DIR)",
                "type": "session_error"}}
        body = body or {}
        try:
            if method == "POST" and path.rstrip("/") == "/v1/sessions":
                session = self.live.create(body.get("params"),
                                           session_id=body.get("session_id"),
                                           trace_id=trace_id,
                                           tenant=tenant)
                return 200, self.live.status_doc(session)
            if method == "GET" and path.rstrip("/") == "/v1/sessions":
                return 200, {"object": "list",
                             "data": [self.live.status_doc(s)
                                      for s in self.live.sessions()]}
            rest = path.split("/v1/sessions/", 1)[-1].strip("/")
            sid, _, sub = rest.partition("/")
            if not sid:
                return 404, {"error": {"message": f"no route {path}",
                                       "type": "session_error"}}
            if self.live.get(sid) is None:
                # cross-host resume (docs/SERVING.md "KV fabric"): an
                # unknown session may have a journal in the SHARED live
                # directory, written by a drained/killed sibling —
                # rehydrate it on demand before answering 404
                self.live.recover_one(sid)
            if method == "POST" and sub == "segments":
                return 200, self.live.append(sid, body.get("segments"),
                                             refresh=body.get("refresh"),
                                             klass=body.get("class"))
            if method == "POST" and sub == "refresh":
                return 200, self.live.refresh(sid, body.get("class"))
            if method == "GET" and sub == "summary":
                from urllib.parse import parse_qs

                q = parse_qs(query or "")
                if q.get("refresh", ["0"])[-1] not in ("0", "false", ""):
                    self.live.refresh(sid, (q.get("class") or [None])[-1])
                return 200, self.live.summary_doc(sid)
            if method == "GET" and not sub:
                session = self.live.get(sid)
                if session is None or session.closed:
                    raise KeyError(sid)
                return 200, self.live.status_doc(session)
            if method == "DELETE" and not sub:
                session = self.live.close(sid)
                if session is None:
                    raise KeyError(sid)
                return 200, {"object": "session", "id": sid,
                             "status": "closed"}
            return 404, {"error": {"message": f"no route {method} {path}",
                                   "type": "session_error"}}
        except KeyError:
            return 404, {"error": {"message": f"no session {sid}",
                                   "type": "session_error"}}
        except ValueError as e:
            return 400, {"error": {"message": str(e),
                                   "type": "session_error"}}
        except Exception as e:  # noqa: BLE001 - a 5xx body, never a crash
            logger.exception("session request failed")
            return 500, {"error": {
                "message": f"session request failed: "
                           f"{type(e).__name__}: {e}",
                "type": "session_error"}}

    # ------------------------------------------------ handoff plumbing

    def _fetch_handoff(self, desc: dict):
        """Pull a handoff payload from its source pod, dedup against
        tickets already imported here, and ack the import.  Returns
        ``(payload, None)`` or ``(None, (status, error_body))`` — every
        failure is a MARKED handoff error the router can act on (retry a
        sibling decode host or re-prefill), never an empty success."""
        tid, source = desc.get("ticket"), desc.get("source")
        if not tid or not source:
            return None, (400, {"error": {
                "message": "handoff descriptor needs ticket + source",
                "type": "handoff_error"}})
        if self._imported.seen(tid):
            self._c_dup_rejects.inc()
            return None, (409, {"error": {
                "message": f"duplicate handoff import of ticket {tid} "
                           "(already imported on this host)",
                "type": "handoff_error"}})
        t0 = time.time()
        conn = None
        try:
            conn = http.client.HTTPConnection(source, timeout=30.0)
            conn.request("GET", f"/v1/handoff/{tid}")
            resp = conn.getresponse()
            if resp.status != 200:
                return None, (502, {"error": {
                    "message": f"handoff payload fetch from {source} "
                               f"failed: HTTP {resp.status}",
                    "type": "handoff_error"}})
            chunks = []
            first = True
            while True:
                chunk = resp.read(1 << 16)
                if first:
                    # injection site: a transfer fault MID-PAYLOAD — one
                    # occurrence per import (plans count imports, not
                    # chunks), fired after the first body read so part of
                    # the page data has genuinely arrived; decode_payload
                    # rejects the truncation and the import is a marked
                    # failure
                    first = False
                    faults.fire("handoff.transfer", OSError)
                if not chunk:
                    break
                chunks.append(chunk)
            payload = decode_payload(b"".join(chunks))
        except Exception as e:  # noqa: BLE001 - marked handoff failure
            return None, (502, {"error": {
                "message": f"handoff transfer from {source} failed: "
                           f"{type(e).__name__}: {e}",
                "type": "handoff_error"}})
        finally:
            if conn is not None:
                conn.close()
        self._h_transfer.observe(time.time() - t0)
        if not self._imported.add(tid):  # raced a concurrent duplicate
            self._c_dup_rejects.inc()
            return None, (409, {"error": {
                "message": f"duplicate handoff import of ticket {tid}",
                "type": "handoff_error"}})
        self._send_ack(tid, source)
        return payload, None

    def _send_ack(self, tid: str, source: str) -> bool:
        """Ack an import so the prefill pod releases its pinned pages.
        Best-effort with one retry: a LOST ack is not a failure of the
        request — the prefill side's orphan sweep reclaims the pages at
        the ticket deadline (the crash-safety backstop this design leans
        on), and the dedup log here keeps a re-delivered ticket from
        double-importing."""
        for attempt in range(2):
            conn = None
            try:
                # injection site: the ack vanishes on the wire — pages
                # stay pinned on the prefill pod until the orphan sweep
                faults.fire("handoff.ack", OSError)
                conn = http.client.HTTPConnection(source, timeout=5.0)
                conn.request("POST", f"/v1/handoff/{tid}/ack")
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    return True
                logger.warning("handoff ack for %s rejected: HTTP %d",
                               tid, resp.status)
                return False  # 410 = consumed/expired: retrying won't help
            except Exception as e:  # noqa: BLE001 - retried once
                logger.warning("handoff ack for %s failed (attempt %d): "
                               "%s: %s", tid, attempt + 1,
                               type(e).__name__, e)
            finally:
                if conn is not None:
                    conn.close()
            time.sleep(0.05 * (attempt + 1))
        self._c_ack_failures.inc()
        logger.warning("handoff ack for %s lost; prefill pages will be "
                       "orphan-swept at the ticket deadline", tid)
        return False

    def _fetch_kv(self, tid: str, source: str):
        """Pull a page-set blob from its source host.  Returns
        ``(payload, None)`` or ``(None, (status, error_body))`` — every
        failure is a MARKED error the caller (router) falls back from,
        never an empty success.  Same transfer discipline as
        ``_fetch_handoff`` (the ``handoff.transfer`` fault site is that
        path's own; this one stays clean so a transfer-fault plan aimed
        at request handoff cannot silently fail migrations too)."""
        conn = None
        try:
            conn = http.client.HTTPConnection(source, timeout=30.0)
            conn.request("GET", f"/v1/kv/{tid}")
            resp = conn.getresponse()
            if resp.status != 200:
                return None, (502, {"error": {
                    "message": f"kv payload fetch from {source} failed: "
                               f"HTTP {resp.status}",
                    "type": "kv_migrate_error"}})
            payload = decode_payload(resp.read())
        except Exception as e:  # noqa: BLE001 - marked failure
            return None, (502, {"error": {
                "message": f"kv transfer from {source} failed: "
                           f"{type(e).__name__}: {e}",
                "type": "kv_migrate_error"}})
        finally:
            if conn is not None:
                conn.close()
        return payload, None

    def _send_kv_ack(self, tid: str, source: str) -> bool:
        """Ack a kv import so the source drops its pinned blob.
        Best-effort with one retry — a LOST ack leaves the blob to the
        source's orphan sweep (the crash-safety backstop), and the dedup
        log here keeps a re-delivered ticket from double-importing."""
        for attempt in range(2):
            conn = None
            try:
                conn = http.client.HTTPConnection(source, timeout=5.0)
                conn.request("POST", f"/v1/kv/{tid}/ack")
                resp = conn.getresponse()
                resp.read()
                return resp.status == 200
            except Exception as e:  # noqa: BLE001 - retried once
                logger.warning("kv ack for %s failed (attempt %d): %s: %s",
                               tid, attempt + 1, type(e).__name__, e)
            finally:
                if conn is not None:
                    conn.close()
            time.sleep(0.05 * (attempt + 1))
        logger.warning("kv ack for %s lost; pinned blob will be "
                       "orphan-swept at the ticket deadline", tid)
        return False

    def sweep_handoffs(self, now: float | None = None) -> int:
        """One orphan-sweep pass (the background sweeper's body; callable
        directly with an explicit ``now`` from tests).  Expired un-acked
        tickets release their pinned pages as orphans; the engine-side
        TTL sweep backstops pins whose ticket was never minted.  KV
        page-set tickets sweep on the same pass — their pinned state is
        the encoded blob, dropped here whether acked or lost."""
        released = 0
        release = getattr(self.engine, "release_handoff", None)
        for tid, rid, consumed in self.handoff.sweep(now):
            if not consumed and release is not None:
                released += release(rid, orphaned=True)
                logger.warning("handoff ticket %s expired un-acked; "
                               "pinned pages reclaimed", tid)
        for tid, _preamble, consumed in self.kv_tickets.sweep(now):
            with self._kv_lock:
                dropped = self._kv_payloads.pop(tid, None)
            if dropped is not None and not consumed:
                released += 1
                logger.warning("kv ticket %s expired un-acked; pinned "
                               "blob dropped", tid)
        sweep = getattr(self.engine, "sweep_handoffs", None)
        if sweep is not None:
            released += sweep(now)
        return released

    def _sweep_loop(self) -> None:
        interval = max(0.5, self.handoff_ttl_s / 4.0)
        while not self._sweep_stop.wait(interval):
            try:
                self.sweep_handoffs()
            except Exception:  # noqa: BLE001 - the sweeper must survive
                logger.exception("handoff orphan sweep failed")

    def kv_stats(self) -> dict:
        """KV-migration ticket state for the JSON /metrics document."""
        with self._kv_lock:
            pinned_bytes = sum(len(b) for b in self._kv_payloads.values())
        return {**self.kv_tickets.stats(), "pinned_bytes": pinned_bytes}

    def handoff_stats(self) -> dict:
        return {
            "role": self.role,
            **self.handoff.stats(),
            "tickets_published": int(self._c_tickets.value),
            "acks": int(self._c_acks.value),
            "duplicate_rejects": int(self._c_dup_rejects.value),
            "ack_failures": int(self._c_ack_failures.value),
        }

    def prometheus_text(self) -> str:
        """Prometheus exposition for ``GET /metrics`` with ``Accept:
        text/plain``: the engine's typed registry (optional Engine hooks —
        ``prometheus_metrics()`` for aggregating engines like the router,
        ``metrics_registry()`` for scheduler-backed ones) plus this
        server's own HTTP counters.  Parts merge through
        ``merge_expositions``: a router-backed engine's fleet page carries
        the SAME family names as this server's own counters (every
        backend is an EngineHTTPServer too), and the text format demands
        one HELP/TYPE header per family with contiguous samples."""
        from lmrs_tpu.obs import MetricsRegistry, merge_expositions

        parts: list[str] = []
        prom = getattr(self.engine, "prometheus_metrics", None)
        reg_fn = getattr(self.engine, "metrics_registry", None)
        if prom is not None:
            parts.append(prom())
        elif reg_fn is not None:
            reg = reg_fn()
            if reg is not None:
                parts.append(reg.render_prometheus())
        http_reg = MetricsRegistry()
        c = http_reg.counter("lmrs_http_batches_total",
                             "engine waves dispatched by the micro-batcher")
        c.inc(self.batcher.batches_run)
        c = http_reg.counter("lmrs_http_requests_total",
                             "HTTP requests served through the batcher")
        c.inc(self.batcher.requests_served)
        g = http_reg.gauge("lmrs_uptime_seconds", "server uptime", "seconds")
        g.set(time.time() - self.started)
        parts.append(http_reg.render_prometheus())
        parts.append(self._handoff_reg.render_prometheus())
        if self.jobs is not None:  # lmrs_jobs_* (docs/OBSERVABILITY.md)
            parts.append(self.jobs.registry.render_prometheus())
        if self.live is not None:  # lmrs_live_* (docs/OBSERVABILITY.md)
            parts.append(self.live.registry.render_prometheus())
        return merge_expositions(parts)

    def serve_forever(self) -> None:
        logger.info("serving on http://%s:%d (model=%s)",
                    self.host, self.port, self.model_name)
        self.httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._sweep_stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.jobs is not None:
            # before the batcher: the job worker's in-flight requests must
            # drain (or fast-fail) through a still-open dispatch queue
            self.jobs.shutdown()
        if self.live is not None:
            # same ordering: in-flight refresh waves drain or fast-fail
            # through the open dispatch queue, then journals close
            self.live.shutdown()
        self.batcher.shutdown()


def serve(engine: Engine, host: str = "127.0.0.1", port: int = 8000,
          **kw) -> EngineHTTPServer:
    """Build + start (foreground).  Returns on shutdown()."""
    server = EngineHTTPServer(engine, host, port, **kw)
    server.serve_forever()
    return server
