"""Hang-survival tier, layer 4: supervised restart (``lmrs-serve --supervise``).

The watchdog (engine/watchdog.py) turns a wedged dispatch into bounded
results and a degraded fail-fast engine — but a process whose dispatch
thread is permanently stuck on a hung chip can only be FIXED by a
restart, and "restart the process" used to be an operator runbook entry.
This module makes it a first-class, chaos-tested code path:

* the engine runs in a CHILD process (the exact ``lmrs-serve`` argv,
  minus ``--supervise``); the parent owns nothing but the child's
  lifecycle;
* the parent polls ``GET /healthz``: the server answers 503 with
  ``"wedged": true`` while its engine is watchdog-degraded, so a wedge
  is observable from outside the process;
* a wedged child is SIGKILLed immediately; an unreachable child (hung
  HTTP stack, OOM livelock) is SIGKILLed after
  ``LMRS_SUPERVISE_FAILS`` consecutive failed polls; a child that dies
  on its own is simply respawned;
* every respawn re-runs the server's startup recovery: the PR 7 jobs
  WAL and the PR 12 live-session journals make interrupted jobs and
  sessions resume token-identical across the bounce — the supervisor
  adds no state of its own, so it can never disagree with the journals.

Operational surface: ``LMRS_SUPERVISE_POLL_S`` (health-poll cadence),
``LMRS_SUPERVISE_FAILS`` (unreachable polls before the kill),
``LMRS_SUPERVISE_BACKOFF_S`` (respawn backoff), and
``LMRS_SUPERVISE_PIDFILE`` (the live child's pid, rewritten per spawn —
chaos tests and init systems target the child through it).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

from lmrs_tpu.utils.env import env_float, env_int, env_str

logger = logging.getLogger("lmrs.supervisor")

# a cold start legitimately takes a while (checkpoint load, XLA compile,
# journal recovery): unreachable polls before the FIRST healthy answer
# never count against the kill threshold inside this window
STARTUP_GRACE_S = 300.0


class Supervisor:
    """Spawn-and-watch loop around one ``lmrs-serve`` child process."""

    def __init__(self, child_argv: list[str], host: str = "127.0.0.1",
                 port: int = 8000):
        self.child_argv = list(child_argv)
        self.host = host if host not in ("0.0.0.0", "::") else "127.0.0.1"
        self.port = port
        self.poll_s = env_float("LMRS_SUPERVISE_POLL_S", 2.0, lo=0.1)
        self.fail_threshold = env_int("LMRS_SUPERVISE_FAILS", 3, lo=1)
        self.backoff_s = env_float("LMRS_SUPERVISE_BACKOFF_S", 0.5, lo=0.0)
        self.pidfile = env_str("LMRS_SUPERVISE_PIDFILE")
        self.restarts = 0
        self.child: subprocess.Popen | None = None
        self._stop = False

    # ------------------------------------------------------------- lifecycle

    def _spawn(self) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "lmrs_tpu.serving.cli",
               *self.child_argv]
        child = subprocess.Popen(cmd)
        logger.info("supervisor: child pid %d spawned (restart #%d)",
                    child.pid, self.restarts)
        if self.pidfile:
            try:
                with open(self.pidfile, "w", encoding="utf-8") as fh:
                    fh.write(str(child.pid))
            except OSError:
                logger.warning("supervisor: pidfile %s not writable",
                               self.pidfile, exc_info=True)
        return child

    def _kill(self, child: subprocess.Popen, why: str) -> None:
        logger.error("supervisor: SIGKILL child pid %d (%s)",
                     child.pid, why)
        try:
            child.kill()
        except OSError:
            pass
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            logger.error("supervisor: child pid %d did not reap", child.pid)

    def _poll_health(self) -> tuple[bool, bool]:
        """(healthy, wedged) from one /healthz poll.  A 503 whose body
        carries ``"wedged": true`` is the watchdog-degraded signature;
        anything else non-200 (or unreachable) is a plain failed poll."""
        url = f"http://{self.host}:{self.port}/healthz"
        try:
            with urllib.request.urlopen(
                    url, timeout=max(1.0, min(self.poll_s, 5.0))) as resp:
                return resp.status == 200, False
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read() or b"{}")
            except ValueError:
                doc = {}
            return False, bool(doc.get("wedged"))
        except OSError:
            return False, False

    def _watch(self, child: subprocess.Popen) -> tuple[str, bool]:
        """Block until the child needs replacing; returns (why, the
        child ever answered a healthy poll) — the health bit drives the
        crash-loop backoff in run()."""
        fails = 0
        seen_healthy = False
        started = time.monotonic()
        while not self._stop:
            time.sleep(self.poll_s)
            rc = child.poll()
            if rc is not None:
                return f"child exited rc={rc}", seen_healthy
            healthy, wedged = self._poll_health()
            if healthy:
                fails, seen_healthy = 0, True
                continue
            if wedged:
                # the engine itself declared the wedge (watchdog): no
                # point waiting out the threshold — the dispatch thread
                # is stuck and only a bounce frees the device
                self._kill(child, "engine wedged (watchdog-degraded)")
                return "wedged", seen_healthy
            if not seen_healthy and time.monotonic() - started \
                    < STARTUP_GRACE_S:
                continue  # still starting up: don't count the poll
            fails += 1
            if fails >= self.fail_threshold:
                self._kill(child, f"{fails} consecutive failed health "
                                  "polls")
                return "unreachable", seen_healthy
        return "stopped", seen_healthy

    def run(self) -> int:
        """Supervise until terminated.  SIGTERM/SIGINT forward to the
        child (graceful stop) and end the loop; returns the last child's
        exit code."""
        def _forward(signum, _frame):
            self._stop = True
            child = self.child
            if child is not None and child.poll() is None:
                try:
                    child.terminate()
                except OSError:
                    pass

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _forward)
            except ValueError:
                pass  # not the main thread (tests drive run() directly)
        rc = 0
        # crash-loop containment: a child that dies without EVER becoming
        # healthy (bad flags, broken checkpoint) doubles the backoff up
        # to a cap instead of respawning ~2x/second forever; one healthy
        # child resets it.  Respawns themselves stay unbounded — a
        # supervisor that gives up is just a slower crash.
        backoff = max(self.backoff_s, 0.1)
        while not self._stop:
            self.child = self._spawn()
            why, was_healthy = self._watch(self.child)
            rc = self.child.poll()
            if self._stop:
                break
            self.restarts += 1
            if was_healthy:
                backoff = max(self.backoff_s, 0.1)
            else:
                backoff = min(backoff * 2, 30.0)
                logger.error("supervisor: child never became healthy; "
                             "backoff now %.1fs", backoff)
            logger.warning("supervisor: respawning after %s (restart #%d)",
                           why, self.restarts)
            time.sleep(backoff)
        child = self.child
        if child is not None and child.poll() is None:
            try:
                child.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self._kill(child, "graceful stop timed out")
        if self.pidfile:
            try:
                os.unlink(self.pidfile)
            except OSError:
                pass
        # a graceful stop (SIGTERM/SIGINT forwarded to the child) is a
        # clean exit for the SUPERVISOR even though the child reports the
        # signal; a supervisor ending any other way surfaces the child rc
        if self._stop or not isinstance(rc, int) or rc < 0:
            return 0
        return rc
