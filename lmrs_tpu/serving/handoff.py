"""Crash-safe KV page handoff: wire codec + ticket lifecycle bookkeeping.

The disaggregated serving tier (docs/SERVING.md) splits a request across
two pods: a PREFILL pod writes the prompt's KV pages and samples the first
token, a DECODE pod imports those pages and continues.  This module owns
the pieces both sides share:

* **payload codec** — ``encode_payload``/``decode_payload`` serialize the
  ``PagedKVCache.export_sequence`` dict (numpy page data, bf16 or int8,
  plus sampler/slot state) into one self-framing byte blob: a JSON header
  with dtype/shape metadata followed by the raw array bytes.  stdlib +
  numpy only — the serving runtime pulls in no pickle (payloads cross
  trust boundaries) and no extra deps.

* **TicketRegistry** — the prefill side's record of published handoffs.
  A ticket is created when a request finishes with
  ``finish_reason="handoff"`` (its pages stay PINNED in the engine), is
  consumed exactly once by the decode side's ack, and expires at its
  deadline — the orphan sweeper then releases the pinned pages.  At-most-
  once: a consumed or expired ticket answers ``410``-style ``None`` to
  every later fetch/ack, so a duplicate ack can never double-free.

* **ImportLog** — the decode side's dedup set.  An ack lost on the wire
  makes the router (or chaos) able to re-deliver a ticket this host has
  already imported; the log rejects the duplicate idempotently instead of
  double-importing (double pages, double decode, two results).
"""

from __future__ import annotations

import json
import threading
import time
import uuid

import numpy as np

__all__ = ["encode_payload", "decode_payload", "TicketRegistry", "ImportLog"]


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its string name, covering the ml_dtypes extensions
    (bfloat16 et al.) numpy alone does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency, always present with jax

        return np.dtype(getattr(ml_dtypes, name))


def encode_payload(payload: dict) -> bytes:
    """Serialize a handoff payload dict to one self-framing byte blob.

    Layout: ``<8-byte big-endian header length><JSON header><raw bytes>``.
    ndarray values are replaced in the header by ``{"__nd__": [dtype,
    shape, offset, nbytes]}`` descriptors pointing into the raw section —
    page data travels as raw dtype bytes (bf16/int8 exactly as stored),
    never base64-in-JSON (a 33% tax on the hot transfer path)."""
    header: dict = {}
    blobs: list[bytes] = []
    off = 0
    for key, val in payload.items():
        if isinstance(val, np.ndarray):
            raw = np.ascontiguousarray(val).tobytes()
            header[key] = {"__nd__": [str(val.dtype), list(val.shape),
                                      off, len(raw)]}
            blobs.append(raw)
            off += len(raw)
        else:
            header[key] = val
    head = json.dumps(header).encode("utf-8")
    return len(head).to_bytes(8, "big") + head + b"".join(blobs)


def decode_payload(data: bytes) -> dict:
    """Inverse of :func:`encode_payload`.  Raises ``ValueError`` on a
    truncated or malformed blob (a transfer fault mid-payload must surface
    as a rejected import, never as silently-short page data)."""
    if len(data) < 8:
        raise ValueError("handoff payload truncated (no header frame)")
    hlen = int.from_bytes(data[:8], "big")
    if len(data) < 8 + hlen:
        raise ValueError("handoff payload truncated (header incomplete)")
    try:
        header = json.loads(data[8:8 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"handoff payload header unparseable: {e}") from e
    body = data[8 + hlen:]
    out: dict = {}
    for key, val in header.items():
        if isinstance(val, dict) and "__nd__" in val:
            dtype_name, shape, off, nbytes = val["__nd__"]
            if off + nbytes > len(body):
                raise ValueError(
                    f"handoff payload truncated: array {key!r} needs "
                    f"{off + nbytes} body bytes, have {len(body)}")
            arr = np.frombuffer(body[off:off + nbytes],
                                dtype=_np_dtype(dtype_name))
            out[key] = arr.reshape(shape)
        else:
            out[key] = val
    return out


class TicketRegistry:
    """Prefill-side ticket table: id -> (request id, deadline, consumed).

    Thread-safe (HTTP handler threads create/fetch/ack concurrently; the
    sweeper thread expires).  ``clock`` is injectable for tests."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._tickets: dict[str, dict] = {}  # guarded-by: _lock

    def create(self, request_id: int, deadline_t: float,
               trace_id: str | None = None) -> str:
        """Publish a ticket.  ``trace_id`` rides the record (and the
        ticket descriptor the serving layer returns) so the prefill→
        decode hop stays on one distributed trace even for clients that
        follow the ticket without the router."""
        tid = uuid.uuid4().hex
        with self._lock:
            self._tickets[tid] = {"rid": request_id, "deadline_t": deadline_t,
                                  "consumed": False, "trace_id": trace_id,
                                  "created_t": self._clock()}
        return tid

    def lookup(self, ticket: str) -> dict | None:
        """Live-ticket record (a copy), or None when unknown, consumed, or
        expired — the fetch path's 410 condition."""
        now = self._clock()
        with self._lock:
            rec = self._tickets.get(ticket)
            if rec is None or rec["consumed"] or rec["deadline_t"] <= now:
                return None
            return dict(rec)

    def consume(self, ticket: str) -> int | None:
        """Ack: mark the ticket consumed exactly once and return its
        request id; None for unknown/expired/already-consumed (duplicate
        acks are idempotent rejections, never double-frees)."""
        now = self._clock()
        with self._lock:
            rec = self._tickets.get(ticket)
            if rec is None or rec["consumed"] or rec["deadline_t"] <= now:
                return None
            rec["consumed"] = True
            return rec["rid"]

    def sweep(self, now: float | None = None) -> list[tuple[str, int, bool]]:
        """Drop expired and consumed-and-expired tickets; returns
        ``[(ticket, rid, was_consumed)]`` — un-consumed entries are the
        ORPHANS whose pinned pages the caller must release."""
        now = self._clock() if now is None else now
        out: list[tuple[str, int, bool]] = []
        with self._lock:
            for tid in [t for t, r in self._tickets.items()
                        if r["deadline_t"] <= now]:
                rec = self._tickets.pop(tid)
                out.append((tid, rec["rid"], rec["consumed"]))
        return out

    def stats(self) -> dict:
        with self._lock:
            live = sum(1 for r in self._tickets.values() if not r["consumed"])
            return {"tickets": len(self._tickets), "unconsumed": live}


class ImportLog:
    """Decode-side dedup of imported ticket ids (bounded FIFO set)."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._lock = threading.Lock()
        # insertion-ordered FIFO
        self._seen: dict[str, None] = {}  # guarded-by: _lock

    def seen(self, ticket: str) -> bool:
        with self._lock:
            return ticket in self._seen

    def add(self, ticket: str) -> bool:
        """Record an import; False when the ticket was already imported
        here (the duplicate-rejection signal)."""
        with self._lock:
            if ticket in self._seen:
                return False
            self._seen[ticket] = None
            while len(self._seen) > self._cap:
                self._seen.pop(next(iter(self._seen)))
            return True
