"""HTTP serving front-end.

The reference sits on the CLIENT side of the OpenAI/Anthropic HTTP APIs
(llm_executor.py:250-409).  This package provides the SERVER side of those
same wire formats over the in-tree TPU engine, so reference-style clients
(including the reference itself, pointed at this base URL) run against the
pod unchanged.
"""

from lmrs_tpu.serving.server import EngineHTTPServer, serve

__all__ = ["EngineHTTPServer", "serve"]
