"""Cross-process / multi-host serving: an Engine over remote lmrs-serve hosts.

The multi-host serving deployment is one ``lmrs-serve`` process per TPU host
(each engine owns its host's local devices; within a host, TP rides ICI) and
this router in front, fanning one request queue over the fleet — the
cross-process analog of ``engine/replicated.py``'s in-process DP replicas,
and the TPU-native successor of the reference's concurrent HTTPS fan-out
(`/root/reference/llm_executor.py:133-147` — there the fleet was OpenAI's;
here it is ours).  DCN carries only requests and completions, never tensor
traffic (SURVEY.md §5.8).

Design choices:

* **Engine protocol, not a new API** (engine/api.py): the executor, the
  pipeline, and ``lmrs-serve`` itself compose with ``RouterEngine``
  unchanged — a router can even front other routers.
* **One thread per in-flight request**, stdlib ``http.client`` only: the
  per-host server micro-batches concurrent arrivals into engine waves
  (server.py ``_Batcher``) and admission-controls itself, so router-side
  threading is pure dispatch — the reference's client-side semaphore
  (llm_executor.py:133) has no router analog on purpose; backpressure
  lives where the slots are.
* **Cancel = hang up.**  ``cancel(rid)`` closes the in-flight socket; the
  remote server's disconnect detection (SSE write failure or the
  non-stream MSG_PEEK poll, server.py) aborts the request server-side and
  frees its slot and pages.  The cancellation contract crosses process
  boundaries with no extra wire protocol.
* **Degrade-and-continue** (llm_executor.py:219-225): a request that fails
  on one host retries once on the next healthy host, then surfaces as an
  error result.  Only a CONNECTION-phase failure marks the host unhealthy
  (a slow or truncated response on an established connection is a
  per-request fault, not a dead host); each wave launches a /healthz
  probe at unhealthy hosts so a restarted worker re-admits — the same
  route-around → probe → re-admit loop as ReplicatedEngine's.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import urlsplit

from lmrs_tpu.engine.api import (GenerationRequest, GenerationResult,
                                 drain_with_callback, preamble_key,
                                 remaining_budget)
from lmrs_tpu.obs import new_trace_id, stitch_traces
from lmrs_tpu.testing import faults
from lmrs_tpu.utils.env import env_bool, env_float, env_int

logger = logging.getLogger("lmrs.router")


def _request_body(req: GenerationRequest) -> dict:
    body: dict = {
        "messages": ([{"role": "system", "content": req.system_prompt}]
                     if req.system_prompt else [])
        + [{"role": "user", "content": req.prompt}],
        "max_tokens": req.max_new_tokens,
        "temperature": req.temperature,
        "top_p": req.top_p,
    }
    if req.stop:
        body["stop"] = list(req.stop)
    if req.top_k:
        body["top_k"] = req.top_k
    if req.seed is not None:
        body["seed"] = req.seed
    if req.deadline_s is not None:
        # the wire carries the REMAINING budget, re-derived at send time:
        # absolute wall-clock never crosses a host boundary (clock skew),
        # and a retry on a later host automatically forwards less budget
        body["deadline_s"] = max(0.0, remaining_budget(req))
    if req.cache_prefix is not None:
        # the prefix-cache hint must reach the backend radix tree: it
        # caps what the backend donates (scheduler._cache_insert) and
        # keys the published radix summary this router routes on — a
        # dropped hint silently bloats the remote tree with per-chunk
        # unique bodies
        body["cache_prefix"] = int(req.cache_prefix)
    if req.qos_class is not None:
        # fair-share admission runs on the BACKEND scheduler: the class
        # label the front door resolved must cross the wire or every
        # forwarded request lands in the default class.  None when QoS
        # is disarmed (api.TenantStampEngine gates the stamp), so the
        # LMRS_QOS=0 wire shape is byte-identical to before.
        body["qos_class"] = req.qos_class
    return body


class _HostConnectError(ConnectionError):
    """Connection-phase failure: the HOST is down/unreachable (marks it
    unhealthy), as opposed to a per-request failure on an established
    connection (slow completion, truncated stream) which must NOT evict
    an otherwise-live host from the fleet."""


class _Host:
    """One backend lmrs-serve process.  ``role`` is the POOL it belongs to
    ("prefill" | "decode" | "both") — pool membership is a routing policy;
    every host can serve a full request (the colocated-fallback
    invariant), prefill-role hosts just additionally mint handoff tickets
    and decode-role hosts import them.

    Health is a CIRCUIT BREAKER, not a binary bit (docs/ROBUSTNESS.md §
    Router circuit breaker): ``LMRS_BREAKER_FAILURES`` consecutive
    request-path failures of ANY kind (connect faults, timeouts, wedged
    backends) OPEN the breaker — the host leaves the dispatch order even
    though its TCP port may still accept connections (the wedged-backend
    signature a connect-phase check can never see).  After
    ``LMRS_BREAKER_COOLDOWN_S`` the paced recovery path moves it to
    HALF-OPEN and sends one tiny golden canary request; success closes
    the breaker, failure re-opens it for another cooldown.  The legacy
    connect-phase belief (``_down``) still short-circuits on host-down
    class failures exactly as before; ``healthy`` is now the derived
    view both signals feed, and its setter keeps the existing
    router/test surface (``h.healthy = True`` force-closes everything).
    ``LMRS_BREAKER_FAILURES=0`` disables the breaker — the pre-breaker
    binary bit, byte-for-byte."""

    def __init__(self, url: str, role: str = "both",
                 clock=time.monotonic):
        u = urlsplit(url if "//" in url else f"http://{url}")
        self.netloc = u.netloc or u.path  # tolerate bare host:port
        self.url = f"http://{self.netloc}"
        self.role = role
        self.clock = clock
        # ``_down``/breaker fields are bare STORES (atomic under the GIL,
        # last writer wins — acceptable belief flags); the request
        # counters are read-modify-writes and increment under the
        # per-host lock: _one() runs per request on the dispatch pool,
        # and bare ``+=`` from concurrent legs was losing updates (the
        # same class as the PR 6 handoff-counter fix, now machine-checked
        # via guarded-by).
        self._down = False
        self.breaker_state = "closed"  # closed | open | half_open
        self.breaker_opened_t = 0.0    # clock() when last opened
        # Drain flag (autoscaler scale-down, fleet/autoscale.py): a
        # draining host leaves the dispatch order like an open breaker
        # but is NEVER probed back — in-flight requests finish, nothing
        # new lands, and remove_host() completes the exit once idle.
        self.draining = False
        self._count_lock = threading.Lock()
        self.served = 0  # guarded-by: _count_lock
        self.failed = 0  # guarded-by: _count_lock
        self.inflight = 0  # request legs on this host now  guarded-by: _count_lock
        self.consec_failures = 0  # guarded-by: _count_lock
        self.breaker_opens = 0    # guarded-by: _count_lock
        # earliest clock time the next recovery probe may launch (probe
        # pacing lives in RouterEngine._launch_probes; 0 = probe freely)
        self.next_probe_t = 0.0

    @property
    def healthy(self) -> bool:
        """Request-path availability: connect-phase belief AND breaker
        AND not draining.  A half-open host stays OUT of the dispatch
        order — only its canary may touch it until the breaker closes."""
        return (not self._down and not self.draining
                and self.breaker_state == "closed")

    def note_leg(self, delta: int) -> None:
        """In-flight leg accounting (drain-until-idle needs an exact
        count, and concurrent legs make bare ``+=`` lossy)."""
        with self._count_lock:
            self.inflight += delta

    @healthy.setter
    def healthy(self, value: bool) -> None:
        # True = the force-close every success path (and tests) use;
        # False = the legacy connect-phase condemnation
        if value:
            self._down = False
            self.breaker_state = "closed"
            with self._count_lock:
                self.consec_failures = 0
        else:
            self._down = True

    def note_served(self) -> None:
        with self._count_lock:
            self.served += 1
            self.consec_failures = 0

    def note_failed(self) -> None:
        threshold = env_int("LMRS_BREAKER_FAILURES", 3, lo=0)
        opened = False
        with self._count_lock:
            self.failed += 1
            self.consec_failures += 1
            if (threshold and self.consec_failures >= threshold
                    and self.breaker_state == "closed"):
                opened = True
                self.breaker_opens += 1
        if opened:
            self.breaker_state = "open"
            self.breaker_opened_t = self.clock()
            logger.warning("host %s: breaker OPEN after %d consecutive "
                           "failures", self.netloc, threshold)

    def reopen_breaker(self) -> None:
        """A half-open canary failed: back to open, cooldown restarts."""
        if self.breaker_state != "closed":
            self.breaker_state = "open"
            self.breaker_opened_t = self.clock()

    def breaker_due(self) -> bool:
        """True when an open breaker's cooldown has elapsed (eligible
        for the half-open canary)."""
        if self.breaker_state != "open":
            return False
        cooldown = env_float("LMRS_BREAKER_COOLDOWN_S", 5.0, lo=0.1)
        return self.clock() - self.breaker_opened_t >= cooldown

    def canary(self, timeout: float = 10.0) -> bool:
        """Half-open probe: ONE tiny golden generation (1 greedy token)
        through the real request path — a wedged backend accepts TCP but
        cannot answer this, which is exactly what /healthz alone misses.
        Success closes the breaker; failure re-opens it."""
        self.breaker_state = "half_open"
        conn = None
        try:
            conn = http.client.HTTPConnection(self.netloc, timeout=timeout)
            conn.request("POST", "/v1/chat/completions",
                         body=json.dumps({
                             "messages": [{"role": "user",
                                           "content": "breaker canary"}],
                             "max_tokens": 1, "temperature": 0.0}),
                         headers={"Content-Type": "application/json"})
            ok = conn.getresponse().status == 200
        except Exception:  # noqa: BLE001 - still down
            ok = False
        finally:
            if conn is not None:
                conn.close()
        if ok:
            logger.info("host %s: canary succeeded, breaker CLOSED",
                        self.netloc)
            self.healthy = True
        else:
            self.reopen_breaker()
        return ok

    def connect(self, timeout: float) -> http.client.HTTPConnection:
        # injection site: a connection-phase fault, raised AS the
        # host-down class so it exercises the unhealthy-marking +
        # failover path exactly like a dead backend
        faults.fire("router.connect", _HostConnectError)
        return http.client.HTTPConnection(self.netloc, timeout=timeout)

    def probe(self) -> bool:
        """GET /healthz; clears the connect-phase condemnation when the
        host answers.  Deliberately NOT a breaker close: a wedged backend
        still answers /healthz — an OPEN breaker only closes through the
        half-open canary (real request path).  With the breaker disabled
        this is exactly the old re-admission."""
        conn = None
        try:
            # own injection site, own connection: probes run on pool
            # threads and must neither consume nor race the request
            # path's ``router.connect`` occurrences (plan replay stays
            # deterministic); a plan targets probes explicitly instead
            faults.fire("router.probe", _HostConnectError)
            conn = http.client.HTTPConnection(self.netloc, timeout=2.0)
            conn.request("GET", "/healthz")
            ok = conn.getresponse().status == 200
        except Exception:  # noqa: BLE001 - still down
            ok = False
        finally:
            if conn is not None:
                conn.close()
        if ok:
            self._down = False
        return ok


class RouterEngine:
    """Engine-protocol fan-out over N lmrs-serve backends (multi-host DP)."""

    schedules_internally = True  # each backend admission-controls itself

    def __init__(self, hosts: list[str], timeout_s: float = 600.0,
                 probe_floor_s: float = 5.0, probe_jitter_s: float = 2.5,
                 clock=time.monotonic, prefill_hosts: list[str] = (),
                 decode_hosts: list[str] = (),
                 prefix_route: bool | None = None,
                 summary_ttl_s: float | None = None,
                 slo_route: bool | None = None):
        # Per-role pools (disaggregated serving, docs/SERVING.md): when
        # BOTH the prefill and decode pools have members, requests run the
        # two-tier handoff — admission to the prefill pool, KV-page ticket
        # to the decode pool; a pool going empty or fully degraded falls
        # the tier back to colocated operation over every full-capable
        # host.  Plain deployments pass ``hosts`` only: one "both" pool,
        # identical behavior to before.
        self.hosts = ([_Host(h, clock=clock) for h in hosts]
                      + [_Host(h, "prefill", clock=clock)
                         for h in prefill_hosts]
                      + [_Host(h, "decode", clock=clock)
                         for h in decode_hosts])
        if not self.hosts:
            raise ValueError("RouterEngine needs at least one backend host")
        self.pools: dict[str, list[_Host]] = {
            role: [h for h in self.hosts if h.role == role]
            for role in ("both", "prefill", "decode")}
        # handoff accounting (Prometheus via prometheus_metrics).  _one
        # runs concurrently on the dispatch pool, so increments go through
        # _count (a bare += is a read-modify-write that loses updates)
        self._handoffs = 0          # guarded-by: _stats_lock
        self._handoff_retries = 0   # guarded-by: _stats_lock
        self._handoff_fallbacks = 0  # guarded-by: _stats_lock
        self._stats_lock = threading.Lock()
        # Durable-job forwarding (docs/ROBUSTNESS.md § Durable jobs): the
        # front server calls job_request() for /v1/jobs traffic; jobs
        # stick to the backend whose journal holds them.  The map is a
        # CACHE, not the truth — a router restart rebuilds it by scanning
        # the fleet on the first GET/DELETE of an unknown id — so it is
        # bounded (oldest-pinned evicted; an evicted id just re-scans),
        # same pattern as the handoff ImportLog.
        # jobs AND live sessions share the pinned-placement cache: both
        # are id->backend stickiness with identical semantics (the
        # journal lives with the backend; an evicted pin re-scans)
        self._job_hosts: dict[str, str] = {}   # guarded-by: _job_lock
        self._job_hosts_max = 4096
        self._job_lock = threading.Lock()
        self._jobs_forwarded = 0  # guarded-by: _stats_lock
        self._sessions_forwarded = 0  # guarded-by: _stats_lock
        # per-recv socket timeout: must exceed the worst-case SILENT wait —
        # a non-streamed generation sends nothing until it completes
        self.timeout_s = timeout_s
        # Recovery-probe pacing: a dead host under heavy traffic formerly
        # drew one /healthz probe per WAVE — a probe storm scaling with
        # offered load, each probe burning a pool thread on a 2 s connect
        # timeout.  Probes now space at least ``probe_floor_s`` apart per
        # host plus a random jitter in [0, probe_jitter_s) so a fleet of
        # hosts dying together doesn't re-probe in lockstep.  ``clock`` is
        # injectable for tests (fake time).
        self.probe_floor_s = probe_floor_s
        self.probe_jitter_s = probe_jitter_s
        self._clock = clock
        self._probe_rng = random.Random(0x90BE)
        self._probe_lock = threading.Lock()  # waves race _launch_probes
        self._pool = ThreadPoolExecutor(
            max_workers=max(8, 4 * len(self.hosts)),
            thread_name_prefix="lmrs-router")
        # rid -> live connection (pre-connect) or RAW SOCKET (post-connect,
        # the hangup target — getresponse() DETACHES the socket from the
        # HTTPConnection for Connection:close responses like the server's
        # SSE, so conn.sock is None exactly when a hangup matters most);
        # the lock guards the dict, not the sockets: shutting down a
        # socket another thread is reading is the POINT
        self._inflight: dict[int, object] = {}  # guarded-by: _inflight_lock
        self._inflight_lock = threading.Lock()
        # cancel ids are WAVE-scoped (created per _wave, dropped with it):
        # a persistent set would let a stale cancel for a rid that never
        # appears poison an identically-numbered request in a LATER wave,
        # violating the unknown-ids-no-op contract.  A cancel landing
        # between waves no-ops — same contract as an already-finished id.
        # A LIST of the live waves' sets, not a singleton: waves can run
        # concurrently (routers fronting routers, the jobs facade), and a
        # singleton slot would let wave B's registration clobber wave A's
        # — a cancel for an A-rid would land only in B's set and A would
        # misclassify its own hangup as a host failure.  cancel() adds
        # the rid to every wave live AT CANCEL TIME (a rid matches checks
        # only in the wave that owns it, so foreign sets are inert), and
        # waves created later never see it — the staleness contract above
        # holds per wave.
        self._wave_cancel_sets: list[set[int]] = []  # guarded-by: _stats_lock
        # round-robin base advances ACROSS waves: a wave-local index would
        # pin every single-request wave (hierarchical reduce tails) onto
        # hosts[0] while the rest of the fleet idles.  Engine-protocol
        # callers may run waves concurrently (a router can front other
        # routers, and the jobs facade shares the dispatch pool), so the
        # advance is a locked fetch-add, not a bare +=.
        self._rr_base = 0  # guarded-by: _stats_lock
        # Prefix-aware placement (docs/SERVING.md § routing policy): a
        # request with a shareable preamble (api.preamble_key over
        # system prompt + cache_prefix head) routes sticky onto the host
        # whose published radix summary predicts the deepest hit — or,
        # with no fresh summary predicting one, onto a deterministic
        # rendezvous-hash host so same-preamble traffic converges from
        # cold start instead of scattering round-robin.  The preferred
        # host goes FIRST in the failover order; everything else about
        # dispatch (health, retry, pools) is unchanged, so greedy outputs
        # are placement-invariant.  LMRS_PREFIX_ROUTE=0 restores pure
        # load/health ordering (the A/B arm).
        self.prefix_route = (env_bool("LMRS_PREFIX_ROUTE", True)
                             if prefix_route is None else bool(prefix_route))
        self.summary_ttl_s = (env_float("LMRS_PREFIX_SUMMARY_TTL", 10.0,
                                        lo=0.5, hi=300.0)
                              if summary_ttl_s is None
                              else float(summary_ttl_s))
        # netloc -> {"at": clock, "map": {hash -> summary row}}; refreshed
        # from /healthz on the dispatch pool (control-plane, bare
        # connections like probes), at most every ttl/2 per host
        self._summaries: dict[str, dict] = {}  # guarded-by: _summary_lock
        self._summary_inflight: set[str] = set()  # guarded-by: _summary_lock
        self._summary_lock = threading.Lock()
        self._prefix_routed = 0     # guarded-by: _stats_lock
        self._prefix_predicted = 0  # guarded-by: _stats_lock
        self._prefix_fallback = 0   # guarded-by: _stats_lock
        # SLO-aware placement (docs/SERVING.md § routing policy): each
        # host's /healthz now carries its burn-rate SLO state (obs/slo.py)
        # and the dispatch order demotes degraded hosts as a GRADED
        # penalty (ok < warn < critical) BEFORE the breaker would have to
        # open — a host converting overload into deadline misses sheds
        # traffic while it still answers probes.  LMRS_SLO_ROUTE=0
        # restores pure load/health ordering byte-for-byte (the A/B
        # arm); states ride the same summary cache as prefix routing.
        self.slo_route = (env_bool("LMRS_SLO_ROUTE", True)
                          if slo_route is None else bool(slo_route))
        self._slo_penalized = 0     # guarded-by: _stats_lock
        # Chargeback-aware placement (docs/SERVING.md § Tenant QoS): a
        # tenant's traffic sticks to the host that LAST SERVED it — warm
        # prefixes and spilled KV live there, so repeat traffic from the
        # same tenant hits instead of re-prefetching fleet-wide.  Weakest
        # placement opinion: consulted only when prefix placement has
        # none, and _targets still drops it when the host's published
        # SLO degrades (stickiness never outranks burn).  The map is a
        # bounded LRU cache, not truth — an evicted tenant just round-
        # robins until it lands again.  LMRS_TENANT_ROUTE=0 disarms
        # byte-for-byte.
        self.tenant_route = env_bool("LMRS_TENANT_ROUTE", True)
        self._tenant_hosts: dict[str, str] = {}  # guarded-by: _stats_lock
        self._tenant_hosts_max = 1024
        self._tenant_routed = 0     # guarded-by: _stats_lock
        # Tail hedging (LMRS_HEDGE_MS, default 0 = off): a straggling
        # NON-STREAMED request duplicates to a sibling host after a
        # p99-derived delay; first non-error result wins, the loser is
        # hung up through the existing cancel plumbing (the backend's
        # disconnect detection frees its slot).  Fan-out-safe: results
        # are keyed by request id, and greedy outputs are host-invariant,
        # so whichever leg wins the text is identical.
        self._hedges = 0       # guarded-by: _stats_lock
        self._hedge_wins = 0   # guarded-by: _stats_lock
        # Global KV fabric (docs/SERVING.md § KV migration): with
        # LMRS_KV_MIGRATE armed the router MOVES warm KV page sets over
        # the backends' /v1/kv wire — a draining host's hottest
        # preambles (and its pinned sessions/jobs) migrate to a healthy
        # sibling before the autoscaler reclaims the pod, and a wave
        # whose preamble group spreads past its warm host prefetches
        # the predicted prefix into the siblings about to serve the
        # spread.  Disarmed, no /v1/kv call is ever made and every
        # metric key below is omitted — byte parity with the
        # pre-fabric router.
        self.kv_migrate = env_bool("LMRS_KV_MIGRATE", True)
        self._kv_lock = threading.Lock()
        self._kv_migrating: set[str] = set()  # guarded-by: _kv_lock
        # (target netloc, preamble key) -> last attempt clock: spread
        # prefetches dedup within a summary TTL so a hot preamble does
        # not re-export every wave; bounded like the other pin caches
        self._kv_prefetched: dict[tuple[str, str], float] = {}  # guarded-by: _kv_lock
        self._kv_prefetched_max = 256
        self._kv_moves = 0       # guarded-by: _stats_lock
        self._kv_prefetches = 0  # guarded-by: _stats_lock
        self._kv_failures = 0    # guarded-by: _stats_lock
        from collections import deque

        self._lat_s = deque(maxlen=512)  # guarded-by: _stats_lock

    def _count(self, attr: str) -> None:
        """Increment a handoff counter atomically (dispatch-pool threads)."""
        with self._stats_lock:
            setattr(self, attr, getattr(self, attr) + 1)

    # ------------------------------------------------------------------ API

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None, on_tokens=None) -> list[GenerationResult]:
        if on_result is not None:
            return drain_with_callback(
                lambda reqs: self._wave(reqs, on_tokens), requests, on_result)
        return self._wave(requests, on_tokens)

    def cancel(self, request_id: int) -> None:
        """Abort a request by hanging up its backend connection — the
        server's disconnect detection cancels it remotely.  Unknown ids
        (including cancels landing between waves) no-op (engine
        contract).  Non-streamed cancels lose any partly generated text
        (the only copy was on the hung-up socket); streamed cancels keep
        the deltas already received."""
        with self._stats_lock:
            for wave in self._wave_cancel_sets:
                wave.add(request_id)
        with self._inflight_lock:
            # hedge/failover legs register under ("hedge", rid): a cancel
            # landing after the primary leg finished must still reach the
            # duplicate's socket, or the abandoned leg would run its full
            # generation and come back as a "success"
            targets = [self._inflight.get(request_id),
                       self._inflight.get(("hedge", request_id))]
        for target in targets:
            self._hangup(target)

    @staticmethod
    def _hangup(target) -> None:
        """Force-close one in-flight leg's connection/socket (cancel()
        and the hedge loser path share this).  shutdown(), not close():
        while the dispatch thread is blocked reading the response,
        socket.makefile's _io_refs defer a close() — no FIN would ever
        reach the server and the "hangup" would silently no-op.
        shutdown() sends the FIN immediately and unblocks the local
        read.  Pre-connect the target is the HTTPConnection (no socket
        yet; _post's post-request re-check covers that window)."""
        if target is None:
            return
        import socket as _socket

        try:
            if isinstance(target, _socket.socket):
                target.shutdown(_socket.SHUT_RDWR)
            else:
                target.close()
        except OSError:
            pass

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def engine_metrics(self) -> dict:
        per = []
        for h in self.hosts:
            row = {"host": h.netloc, "role": h.role, "healthy": h.healthy,
                   "breaker": h.breaker_state,
                   "served": h.served, "failed": h.failed}
            conn = None
            try:
                conn = h.connect(timeout=2.0)
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                row["metrics"] = json.loads(resp.read())
            except Exception as e:  # noqa: BLE001 - metrics are best-effort
                # but never SILENT: a dead backend must be visible in the
                # aggregate, not just missing its metrics block
                logger.debug("metrics fetch failed for %s: %s: %s",
                             h.netloc, type(e).__name__, e)
                row["metrics_unreachable"] = True
            finally:
                if conn is not None:
                    conn.close()
            per.append(row)
        with self._summary_lock:
            now = self._clock()
            ages = {netloc: round(now - s["at"], 1)
                    for netloc, s in self._summaries.items()}
        doc = {"hosts": len(self.hosts),
               "healthy_hosts": sum(h.healthy for h in self.hosts),
               "pools": {role: {"size": len(pool),
                                "healthy": sum(h.healthy for h in pool)}
                         for role, pool in self.pools.items() if pool},
               "handoff": {"handoffs": self._handoffs,
                           "retries": self._handoff_retries,
                           "fallbacks": self._handoff_fallbacks},
               "hedge": {"hedges": self._hedges,
                         "wins": self._hedge_wins},
               "prefix_route": {"enabled": self.prefix_route,
                                "routed": self._prefix_routed,
                                "predicted": self._prefix_predicted,
                                "fallback": self._prefix_fallback,
                                "summary_age_s": ages},
               "slo_route": {"enabled": self.slo_route,
                             "penalized": self._slo_penalized,
                             "states": {h.netloc: self._slo_penalty(h)
                                        for h in self.hosts}},
               "tenant_route": {"enabled": self.tenant_route,
                                "routed": self._tenant_routed,
                                "tenants": len(self._tenant_hosts)},
               "per_host": per}
        if self.kv_migrate:
            # key present only when armed: LMRS_KV_MIGRATE=0 keeps the
            # aggregate byte-identical to the pre-fabric router
            doc["kv_migrate"] = {"enabled": True,
                                 "moves": self._kv_moves,
                                 "prefetches": self._kv_prefetches,
                                 "failures": self._kv_failures}
        return doc

    def prometheus_metrics(self) -> str:
        """Fleet-wide Prometheus exposition: each backend's text-format
        ``/metrics`` page relabeled with ``host=<netloc>`` so per-host
        series never collide, merged with HELP/TYPE dedup, plus the
        router's own per-host series (same label):
        ``lmrs_router_host_up`` (the router's request-path health belief),
        ``lmrs_router_host_scrape_ok`` (did THIS scrape fetch the host's
        page — the alertable signal for a backend that is routable but
        unscrapeable), and served/failed counters.  Backends are scraped
        CONCURRENTLY on the dispatch pool — serial 2 s connect timeouts
        would stack past a scraper's own deadline and fail the whole
        fleet page; hosts already marked unhealthy are not scraped at all
        (they still appear through the router-side series)."""
        from lmrs_tpu.obs import (MetricsRegistry, add_label_to_exposition,
                                  merge_expositions)

        def scrape(h: _Host) -> str | None:
            conn = None
            try:
                conn = h.connect(timeout=2.0)
                conn.request("GET", "/metrics",
                             headers={"Accept": "text/plain"})
                resp = conn.getresponse()
                body = resp.read().decode("utf-8", "replace")
                ctype = resp.getheader("Content-Type", "")
                if resp.status == 200 and "text/plain" in ctype:
                    return body
                logger.debug("host %s served no Prometheus page "
                             "(status %s, type %s)", h.netloc, resp.status,
                             ctype)
            except Exception as e:  # noqa: BLE001 - scrape is best-effort
                logger.debug("prometheus scrape failed for %s: %s: %s",
                             h.netloc, type(e).__name__, e)
            finally:
                if conn is not None:
                    conn.close()
            return None

        import time as _time

        futures = {h: self._pool.submit(scrape, h)
                   for h in self.hosts if h.healthy}
        # ONE deadline across the gather: per-future timeouts would stack
        # back into the serial worst case whenever the dispatch pool is
        # saturated by in-flight generation (futures queued, not running)
        deadline = _time.time() + 3.0
        bodies: dict[str, str | None] = {}
        for h, fut in futures.items():
            try:
                bodies[h.netloc] = fut.result(
                    timeout=max(0.0, deadline - _time.time()))
            except Exception:  # noqa: BLE001 - timeout / pool saturation
                bodies[h.netloc] = None
        pages: list[str] = []
        for h in self.hosts:
            body = bodies.get(h.netloc)
            if body is not None:
                pages.append(add_label_to_exposition(body, "host", h.netloc))
            reg = MetricsRegistry()
            reg.gauge("lmrs_router_host_up",
                      "1 when the router considers the host healthy "
                      "(request-path belief)").set(float(h.healthy))
            reg.gauge("lmrs_router_host_scrape_ok",
                      "1 when this scrape fetched the host's metrics "
                      "page").set(float(body is not None))
            reg.counter("lmrs_router_host_served_total",
                        "requests completed on this host").inc(h.served)
            reg.counter("lmrs_router_host_failed_total",
                        "requests failed on this host").inc(h.failed)
            reg.gauge("lmrs_router_breaker_state",
                      "circuit-breaker state for this host "
                      "(0=closed, 1=open, 2=half_open)").set(
                {"closed": 0.0, "open": 1.0,
                 "half_open": 2.0}.get(h.breaker_state, 0.0))
            reg.counter("lmrs_router_breaker_opens_total",
                        "times this host's breaker opened "
                        "(consecutive-failure threshold crossed)"
                        ).inc(h.breaker_opens)
            reg.gauge("lmrs_router_host_slo_state",
                      "the host's last published SLO burn-rate state "
                      "(0=ok/unknown, 1=warn, 2=critical)").set(
                float(self._slo_penalty(h)))
            pages.append(add_label_to_exposition(
                reg.render_prometheus(), "host", h.netloc))
        # Per-role pool gauges (disaggregated serving).  Only pools with
        # members are emitted, so a colocated deployment reports exactly
        # one "both" pool — dashboards never fork on topology.
        for role, pool in self.pools.items():
            if not pool:
                continue
            reg = MetricsRegistry()
            reg.gauge("lmrs_router_pool_size",
                      "backend hosts in this role pool").set(len(pool))
            reg.gauge("lmrs_router_pool_healthy",
                      "healthy hosts in this role pool").set(
                sum(h.healthy for h in pool))
            pages.append(add_label_to_exposition(
                reg.render_prometheus(), "pool", role))
        hreg = MetricsRegistry()
        hreg.counter("lmrs_handoff_total",
                     "prefill→decode handoff tickets followed by the "
                     "router").inc(self._handoffs)
        hreg.counter("lmrs_handoff_retries_total",
                     "failed decode-leg attempts (retried or degraded)"
                     ).inc(self._handoff_retries)
        hreg.counter("lmrs_handoff_fallbacks_total",
                     "handoff flows degraded to colocated re-prefill"
                     ).inc(self._handoff_fallbacks)
        hreg.counter("lmrs_router_jobs_forwarded_total",
                     "durable-job API calls forwarded to backends"
                     ).inc(self._jobs_forwarded)
        hreg.counter("lmrs_router_sessions_forwarded_total",
                     "live-session API calls forwarded to backends "
                     "(sticky by session id)").inc(self._sessions_forwarded)
        hreg.counter("lmrs_router_prefix_routed_total",
                     "requests placed sticky-by-prefix (summary-predicted "
                     "or rendezvous)").inc(self._prefix_routed)
        hreg.counter("lmrs_router_prefix_hit_predicted_total",
                     "prefix placements backed by a fresh radix summary "
                     "predicting a hit").inc(self._prefix_predicted)
        hreg.counter("lmrs_router_prefix_fallback_total",
                     "prefix-eligible requests that degraded to plain "
                     "load/health ordering").inc(self._prefix_fallback)
        hreg.counter("lmrs_router_hedges_total",
                     "straggling requests duplicated to a sibling host "
                     "(LMRS_HEDGE_MS tail hedging)").inc(self._hedges)
        hreg.counter("lmrs_router_hedge_wins_total",
                     "hedged requests whose DUPLICATE leg answered first "
                     "(the loser was hung up)").inc(self._hedge_wins)
        hreg.counter("lmrs_router_slo_penalized_total",
                     "dispatch orders whose first choice was demoted by a "
                     "published SLO state (LMRS_SLO_ROUTE)"
                     ).inc(self._slo_penalized)
        hreg.counter("lmrs_router_tenant_routed_total",
                     "requests placed sticky on the tenant's last-served "
                     "host (LMRS_TENANT_ROUTE chargeback affinity)"
                     ).inc(self._tenant_routed)
        if self.kv_migrate:
            # emitted only when armed (LMRS_KV_MIGRATE=0 exposition
            # parity — same rule as the engine_metrics block)
            hreg.counter("lmrs_kv_migrate_moves_total",
                         "KV page sets moved off draining hosts over "
                         "the /v1/kv export/import wire"
                         ).inc(self._kv_moves)
            hreg.counter("lmrs_kv_migrate_prefetches_total",
                         "predicted prefixes prefetched into spread "
                         "siblings ahead of wave traffic"
                         ).inc(self._kv_prefetches)
            hreg.counter("lmrs_kv_migrate_failures_total",
                         "KV migration legs (move or prefetch) that "
                         "failed; the preamble re-prefills cold"
                         ).inc(self._kv_failures)
        pages.append(hreg.render_prometheus())
        return merge_expositions(pages)

    # ------------------------------------------------------ fleet usage

    def usage_report(self) -> dict:
        """Fleet-wide ``GET /v1/usage``: every backend's per-tenant
        rollups pulled concurrently (control-plane: bare connections,
        short timeout, dispatch pool) and merged through the ONE merge
        rule (obs.merge_usage) — per-tenant fleet rollups sum to the
        fleet totals by construction.  Hosts that are down or ledger-less
        stay visible in ``unreachable``."""
        from lmrs_tpu.obs.ledger import merge_usage, totals_from_tenants

        def fetch(h: _Host):
            conn = None
            try:
                conn = http.client.HTTPConnection(h.netloc, timeout=5.0)
                conn.request("GET", "/v1/usage")
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return json.loads(resp.read())
            except Exception as e:  # noqa: BLE001 - best-effort per host
                logger.debug("usage fetch failed for %s: %s: %s",
                             h.netloc, type(e).__name__, e)
                return None
            finally:
                if conn is not None:
                    conn.close()

        futures = [(h, self._pool.submit(fetch, h)) for h in self.hosts]
        tenants: dict[str, dict] = {}
        per_host: list[dict] = []
        unreachable: list[str] = []
        enabled = False
        # fleet fair-share rollup: windowed device-seconds SUM across
        # hosts per tenant; weights are config (identical fleet-wide by
        # contract, max tolerates skew during a rolling knob change)
        qos_burn: dict[str, float] = {}
        qos_weight: dict[str, float] = {}
        qos_window = 0.0
        qos_on = False
        for h, fut in futures:
            try:
                doc = fut.result(timeout=10.0)
            except Exception:  # noqa: BLE001 - pool saturation/timeout
                doc = None
            if not isinstance(doc, dict):
                unreachable.append(h.netloc)
                continue
            enabled = enabled or bool(doc.get("enabled"))
            per_host.append({"host": h.netloc,
                             "totals": doc.get("totals") or {}})
            for t, roll in (doc.get("tenants") or {}).items():
                merge_usage(tenants.setdefault(t, {}), roll)
            q = doc.get("qos")
            if isinstance(q, dict) and q.get("enabled"):
                qos_on = True
                qos_window = max(qos_window, float(q.get("window_s") or 0.0))
                for t, row in (q.get("tenants") or {}).items():
                    qos_burn[t] = (qos_burn.get(t, 0.0)
                                   + float(row.get("window_device_seconds")
                                           or 0.0))
                    qos_weight[t] = max(qos_weight.get(t, 0.0),
                                        float(row.get("weight") or 1.0))
        totals = totals_from_tenants(tenants)
        with self._stats_lock:
            router = {"hedges": self._hedges,
                      "hedge_wins": self._hedge_wins,
                      "handoff_retries": self._handoff_retries,
                      "slo_penalized": self._slo_penalized,
                      "tenant_routed": self._tenant_routed}
        doc = {"object": "usage", "enabled": enabled, "fleet": True,
               "tenants": tenants, "totals": totals,
               "per_host": per_host, "unreachable": unreachable,
               "router": router}
        if qos_on:
            # recompute shares over the FLEET sums — per-host shares do
            # not average into a fleet share; the block is omitted
            # entirely when every host is disarmed (LMRS_QOS=0 wire
            # parity, same rule as the backend's /v1/usage)
            total = sum(qos_burn.values())
            wsum = sum(qos_weight.get(t, 1.0) for t in qos_burn) or 1.0
            qt = {}
            for t, s in sorted(qos_burn.items()):
                w = qos_weight.get(t, 1.0)
                fair = total * w / wsum
                qt[t] = {"weight": w,
                         "window_device_seconds": round(s, 6),
                         "share": round(s / total, 4) if total > 0 else 0.0,
                         "fair_share": round(w / wsum, 4),
                         "over_quota": bool(len(qos_burn) > 1 and s > fair)}
            doc["qos"] = {"object": "qos", "enabled": True, "fleet": True,
                          "window_s": qos_window,
                          "window_device_seconds": round(total, 6),
                          "tenants": qt}
        return doc

    def anatomy_report(self) -> dict:
        """Fleet-wide ``GET /v1/anatomy``: every backend's step-anatomy
        document pulled concurrently (same control-plane discipline as
        ``usage_report``) and merged through ``obs.merge_anatomy`` —
        additive totals sum exactly; per-class percentiles are
        iteration-weighted estimates, so each host's raw document rides
        along in ``per_host``.  Hosts that are down or anatomy-less
        (LMRS_ANATOMY=0 there) stay visible in ``unreachable``."""
        from lmrs_tpu.obs.anatomy import merge_anatomy

        def fetch(h: _Host):
            conn = None
            try:
                conn = http.client.HTTPConnection(h.netloc, timeout=5.0)
                conn.request("GET", "/v1/anatomy")
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return json.loads(resp.read())
            except Exception as e:  # noqa: BLE001 - best-effort per host
                logger.debug("anatomy fetch failed for %s: %s: %s",
                             h.netloc, type(e).__name__, e)
                return None
            finally:
                if conn is not None:
                    conn.close()

        futures = [(h, self._pool.submit(fetch, h)) for h in self.hosts]
        docs: list[dict] = []
        per_host: list[dict] = []
        unreachable: list[str] = []
        for h, fut in futures:
            try:
                doc = fut.result(timeout=10.0)
            except Exception:  # noqa: BLE001 - pool saturation/timeout
                doc = None
            if not isinstance(doc, dict):
                unreachable.append(h.netloc)
                continue
            docs.append(doc)
            per_host.append({"host": h.netloc, **doc})
        merged = merge_anatomy(docs)
        merged.update({"fleet": True, "per_host": per_host,
                       "unreachable": unreachable})
        return merged

    # ---------------------------------------------------- fleet elasticity

    def add_host(self, url: str, role: str = "both") -> "_Host":
        """Admit a new backend into the fleet (autoscaler scale-up, or
        an operator joining capacity to a live router).  Idempotent by
        netloc: re-adding an existing host just clears its drain flag
        and returns it.  The new host enters healthy — the first failed
        request demotes it through the normal breaker machinery, so a
        pod that never came up costs one failover leg, not an outage."""
        h = _Host(url, role, clock=self._clock)
        for existing in self.hosts:
            if existing.netloc == h.netloc:
                existing.draining = False
                return existing
        # append order: list mutation is GIL-atomic and dispatch only
        # ever iterates, so a concurrent wave sees the fleet before or
        # after the join — never a torn list
        self.hosts.append(h)
        self.pools.setdefault(h.role, []).append(h)
        logger.info("fleet: host %s joined (role %s, %d hosts)",
                    h.netloc, h.role, len(self.hosts))
        return h

    def drain_host(self, netloc: str) -> bool:
        """Begin a graceful exit: the host leaves the dispatch order
        (``healthy`` goes False) but keeps its in-flight requests; the
        recovery probes skip it so nothing re-admits it.  Returns False
        for an unknown netloc.

        Sticky affinity is purged HERE, not at remove: a draining host
        must stop attracting placement immediately — stale tenant pins
        and summary rows would keep steering warm traffic at a host on
        its way out, and session/job pins would hold sticky clients
        there until the pod dies under them.  The pinned ids are
        collected before the purge so the KV migration (LMRS_KV_MIGRATE)
        can re-pin them onto the sibling that inherits the warm pages;
        disarmed, follow-up traffic just pays one fleet re-scan."""
        for h in self.hosts:
            if h.netloc != netloc:
                continue
            h.draining = True
            with self._summary_lock:
                self._summaries.pop(netloc, None)
                self._summary_inflight.discard(netloc)
            with self._stats_lock:
                for t, n in list(self._tenant_hosts.items()):
                    if n == netloc:
                        del self._tenant_hosts[t]
            with self._job_lock:
                pinned = [j for j, n in self._job_hosts.items()
                          if n == netloc]
                for j in pinned:
                    del self._job_hosts[j]
            logger.info("fleet: host %s draining (%d legs in flight, "
                        "%d pins released)", netloc, h.inflight,
                        len(pinned))
            if self.kv_migrate:
                self._start_kv_migration(h, pinned)
            return True
        return False

    def host_idle(self, netloc: str) -> bool:
        """True when the host has no request legs in flight (the
        drain-complete signal the autoscaler polls)."""
        for h in self.hosts:
            if h.netloc == netloc:
                return h.inflight == 0
        return True

    def remove_host(self, netloc: str, force: bool = False) -> bool:
        """Complete a drain: drop the host from the fleet and every
        routing structure.  Refuses (returns False) while request legs
        are still in flight unless ``force`` — the last host in the
        fleet can never be removed (the router's own invariant)."""
        for h in list(self.hosts):
            if h.netloc != netloc:
                continue
            if h.inflight and not force:
                return False
            if len(self.hosts) <= 1:
                logger.warning("fleet: refusing to remove last host %s",
                               netloc)
                return False
            self.hosts.remove(h)
            for pool in self.pools.values():
                if h in pool:
                    pool.remove(h)
            with self._summary_lock:
                self._summaries.pop(netloc, None)
                self._summary_inflight.discard(netloc)
            with self._stats_lock:
                for t, n in list(self._tenant_hosts.items()):
                    if n == netloc:
                        del self._tenant_hosts[t]
            # job/session pins too (a drain purges them already, but a
            # FORCED remove — breaker-dead pod, no drain — must not
            # leave sticky clients routed at a host that is gone)
            with self._job_lock:
                for j, n in list(self._job_hosts.items()):
                    if n == netloc:
                        del self._job_hosts[j]
            with self._kv_lock:
                for key in [k for k in self._kv_prefetched
                            if k[0] == netloc]:
                    del self._kv_prefetched[key]
            logger.info("fleet: host %s removed (%d hosts remain)",
                        netloc, len(self.hosts))
            return True
        return False

    # ------------------------------------------------------ KV-fabric moves

    def migrations_pending(self, netloc: str) -> bool:
        """True while a drain-triggered KV migration off ``netloc`` is
        still in flight — the autoscaler holds its force-remove until
        this clears (or its drain timeout fires), so warm pages are not
        torn off a pod mid-copy."""
        with self._kv_lock:
            return netloc in self._kv_migrating

    def _start_kv_migration(self, src: _Host, pinned: list[str]) -> None:
        """Queue the background migration of ``src``'s warm KV (one per
        netloc at a time — a double drain call must not race two copies
        of the same page sets)."""
        with self._kv_lock:
            if src.netloc in self._kv_migrating:
                return
            self._kv_migrating.add(src.netloc)
        self._pool.submit(self._migrate_host_kv, src, pinned)

    def _migrate_host_kv(self, src: _Host, pinned: list[str]) -> None:
        """Move the draining host's hottest preambles to one healthy
        sibling over the /v1/kv wire (pool thread, best-effort): export
        mints a page-set ticket on ``src``, import makes the sibling
        PULL the blob and ack it.  The drained host's sticky session/
        job pins re-pin onto the sibling afterwards — its journals
        replay anywhere (shared live-dir) or one fleet re-scan finds
        them, and now the warm radix pages travel too.  Every failure
        degrades to cold re-prefill on whatever host wins placement;
        nothing here can wedge a drain."""
        moved = 0
        try:
            dst = self._kv_sibling(src)
            if dst is None:
                logger.info("fleet: no healthy sibling for %s; KV stays "
                            "(re-prefill on demand)", src.netloc)
                return
            rows = self._fetch_kv_rows(src)
            rows.sort(key=lambda e: -(2 * int(e.get("resident_tokens") or 0)
                                      + int(e.get("spilled_tokens") or 0)))
            for ent in rows[:8]:
                if self._kv_move(src, dst, str(ent["hash"])):
                    moved += 1
            with self._job_lock:
                for jid in pinned:
                    self._job_hosts[jid] = dst.netloc
                while len(self._job_hosts) > self._job_hosts_max:
                    self._job_hosts.pop(next(iter(self._job_hosts)))
            logger.info("fleet: migrated %d KV page sets %s -> %s "
                        "(%d pins re-homed)", moved, src.netloc,
                        dst.netloc, len(pinned))
        except Exception:  # noqa: BLE001 - migration is best-effort
            logger.warning("fleet: KV migration off %s failed after %d "
                           "moves", src.netloc, moved, exc_info=True)
            self._count("_kv_failures")
        finally:
            with self._kv_lock:
                self._kv_migrating.discard(src.netloc)

    def _kv_sibling(self, src: _Host) -> _Host | None:
        """Where the drained host's KV should land: the least-loaded
        healthy host outside ``src`` (same optimism as dispatch — role
        membership is policy, and a migrated preamble is useful wherever
        follow-up traffic can be steered)."""
        healthy = [h for h in self.hosts if h is not src and h.healthy]
        if not healthy:
            return None
        return sorted(healthy, key=lambda h: (h.served, h.netloc))[0]

    def _fetch_kv_rows(self, src: _Host) -> list[dict]:
        """The draining host's CURRENT prefix summary, fetched directly
        (the cached copy was purged at drain, and the refresh loop skips
        unhealthy hosts).  An unreachable host returns no rows — there
        is nothing to migrate off a pod that is already dark."""
        conn = None
        try:
            conn = http.client.HTTPConnection(src.netloc, timeout=5.0)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            if resp.status != 200:
                return []
            doc = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - best-effort control plane
            logger.debug("KV summary fetch failed for %s: %s: %s",
                         src.netloc, type(e).__name__, e)
            return []
        finally:
            if conn is not None:
                conn.close()
        return [ent for ent in (doc.get("prefix_summary") or ())
                if isinstance(ent, dict) and ent.get("hash")]

    def _kv_move(self, src: _Host, dst: _Host, preamble: str) -> bool:
        """One page-set move: export on ``src`` (404 = cold or engine
        busy — not an error, the preamble just re-prefills), then a
        pull-import on ``dst`` (which fetches the blob and acks the
        ticket; an unacked ticket is reclaimed by src's orphan sweep)."""
        status, doc = self._job_call_safe(
            src, "POST", "/v1/kv/export", {"preamble": preamble})
        if status != 200 or not isinstance(doc, dict) \
                or not doc.get("ticket"):
            if status not in (404, 501):
                self._count("_kv_failures")
            return False
        status, _ = self._job_call_safe(
            dst, "POST", "/v1/kv/import",
            {"ticket": doc["ticket"], "source": src.netloc})
        if status == 200:
            self._count("_kv_moves")
            return True
        self._count("_kv_failures")
        return False

    def _kv_prefetch_spread(self, warm: _Host, key: str,
                            role: str) -> None:
        """Predicted-prefix prefetch: a wave's preamble group is about
        to SPREAD past its warm host (fair-share placement), so the
        siblings that will serve the remainder pull the predicted
        prefix from the warm host now instead of re-prefilling it.
        Deduped per (target, preamble) within a summary TTL; queued on
        the dispatch pool so placement never blocks on a copy."""
        now = self._clock()
        targets: list[_Host] = []
        with self._kv_lock:
            for h in self._role_pool(role):
                if h is warm or not h.healthy:
                    continue
                k = (h.netloc, key)
                if now - self._kv_prefetched.get(k, -1e9) \
                        < self.summary_ttl_s:
                    continue
                self._kv_prefetched[k] = now
                targets.append(h)
            while len(self._kv_prefetched) > self._kv_prefetched_max:
                self._kv_prefetched.pop(next(iter(self._kv_prefetched)))
        for h in targets:
            self._pool.submit(self._kv_prefetch_one, warm, h, key)

    def _kv_prefetch_one(self, src: _Host, dst: _Host, key: str) -> None:
        """One prefetch leg (pool thread): same export→pull-import flow
        as a drain move, but failures stay silent — a prefetch that
        does not land just leaves the sibling cold, which is exactly
        where it started."""
        status, doc = self._job_call_safe(
            src, "POST", "/v1/kv/export", {"preamble": key})
        if status != 200 or not isinstance(doc, dict) \
                or not doc.get("ticket"):
            return
        status, _ = self._job_call_safe(
            dst, "POST", "/v1/kv/import",
            {"ticket": doc["ticket"], "source": src.netloc})
        if status == 200:
            self._count("_kv_prefetches")
        else:
            self._count("_kv_failures")

    # ------------------------------------------------------ trace stitching

    def stitched_trace(self) -> dict:
        """Pull every backend's ``GET /v1/trace`` page, clock-align, and
        merge into ONE Perfetto document (obs.stitch_traces): per-host
        tracks under remapped pids plus a synthesized per-trace-id track
        where a disaggregated request reads as a single causal chain.
        Hosts that are down or not tracing stay visible in the returned
        ``stitch.unreachable`` list instead of silently vanishing.
        Served by a fronting EngineHTTPServer as its own ``/v1/trace``.

        Control-plane like ``_job_call``: bare connections, short
        timeout, concurrent on the dispatch pool — a serial pull would
        stack connect timeouts across a partitioned fleet."""
        def fetch(h: _Host):
            conn = None
            try:
                conn = http.client.HTTPConnection(h.netloc, timeout=5.0)
                conn.request("GET", "/v1/trace")
                resp = conn.getresponse()
                if resp.status != 200:
                    logger.debug("trace fetch from %s: HTTP %d",
                                 h.netloc, resp.status)
                    return None
                return json.loads(resp.read())
            except Exception as e:  # noqa: BLE001 - best-effort per host
                logger.debug("trace fetch failed for %s: %s: %s",
                             h.netloc, type(e).__name__, e)
                return None
            finally:
                if conn is not None:
                    conn.close()

        futures = [(h, self._pool.submit(fetch, h)) for h in self.hosts]
        pages: list[tuple[str, dict]] = []
        unreachable: list[str] = []
        for h, fut in futures:
            try:
                doc = fut.result(timeout=10.0)
            except Exception:  # noqa: BLE001 - pool saturation/timeout
                doc = None
            if doc is None:
                unreachable.append(h.netloc)
            else:
                pages.append((h.netloc, doc))
        merged = stitch_traces(pages)
        merged["stitch"]["unreachable"] = unreachable
        return merged

    # ------------------------------------------------------- job forwarding

    def job_request(self, method: str, path: str, body: dict | None,
                    trace_id: str | None = None,
                    tenant: str | None = None) -> tuple[int, dict]:
        """Forward one /v1/jobs call to the backend fleet (the front
        server's ``_job_http`` delegates here when it has no local
        JobManager).  Placement is STICKY: a submit hashes its transcript
        onto the host ring — so a duplicate POST (client retry after a
        crash) lands on the same backend and converges on the same
        content-addressed journal — and the returned job id pins follow-up
        GET/DELETE traffic to that host.  Unknown ids scan the fleet
        (rebuilding the stickiness cache after a router restart: the
        journals live with the backends, not here)."""
        with self._stats_lock:
            self._jobs_forwarded += 1
        if method == "POST":
            digest = int(hashlib.sha256(
                json.dumps(body or {}, sort_keys=True).encode()
            ).hexdigest(), 16)
            ring = sorted(self.hosts, key=lambda h: h.netloc)
            start = digest % len(ring)
            last: tuple[int, dict] = (503, {"error": {
                "message": "no backend accepted the job",
                "type": "job_error"}})
            for k in range(len(ring)):
                host = ring[(start + k) % len(ring)]
                if not host.healthy and k < len(ring) - 1:
                    continue  # same optimism as _targets: try someone
                try:
                    status, payload = self._job_call(host, method, path,
                                                     body, trace_id,
                                                     tenant=tenant)
                except Exception as e:  # noqa: BLE001 - next host
                    host.note_failed()
                    last = (502, {"error": {
                        "message": f"{host.netloc}: {type(e).__name__}: {e}",
                        "type": "job_error"}})
                    continue
                if status == 501:  # backend has no jobs_dir: keep looking
                    last = (status, payload)
                    continue
                jid = payload.get("id") if isinstance(payload, dict) else None
                if jid:
                    self._pin_job(jid, host.netloc)
                return status, payload
            return last
        # Fleet scans run CONCURRENTLY on the dispatch pool: sequential
        # probing would hold the HTTP handler thread one connect timeout
        # per partitioned host (connect HANGS rather than refuses there);
        # gathered, the whole scan is bounded by the slowest single host.
        if method == "GET" and path.rstrip("/") == "/v1/jobs":
            futures = [self._pool.submit(self._job_call_safe, h, method,
                                         path, None)
                       for h in self.hosts]
            data: list = []
            errors = 0
            for host, fut in zip(self.hosts, futures):
                status, payload = fut.result()
                if status == 200:
                    for doc in payload.get("data", []):
                        if doc.get("id"):
                            self._pin_job(doc["id"], host.netloc)
                        data.append(doc)
                elif status == 502:
                    errors += 1
            return 200, {"object": "list", "data": data,
                         "hosts_unreachable": errors}
        # GET/DELETE /v1/jobs/<id>: sticky host alone first (the common
        # case pays no fleet cost), then a concurrent fleet scan —
        # rebuilding stickiness after a router restart
        jid = path.split("/v1/jobs/", 1)[-1].strip("/")
        with self._job_lock:
            pinned = self._job_hosts.get(jid)
        if pinned is not None:
            host = next((h for h in self.hosts if h.netloc == pinned), None)
            if host is not None:
                status, payload = self._job_call_safe(host, method, path,
                                                      None)
                if status not in (404, 501, 502):
                    return status, payload
        ordered = sorted(self.hosts,
                         key=lambda h: (not h.healthy, h.netloc))
        futures = [self._pool.submit(self._job_call_safe, h, method, path,
                                     None)
                   for h in ordered]
        results = [f.result() for f in futures]
        last = (404, {"error": {"message": f"no job {jid} on any backend",
                                "type": "job_error"}})
        for host, (status, payload) in zip(ordered, results):
            if status in (404, 501):
                continue
            if status == 502:
                last = (status, payload)
                continue
            self._pin_job(jid, host.netloc)
            return status, payload
        return last

    # -------------------------------------------- live-session forwarding

    def session_request(self, method: str, path: str, body: dict | None,
                        trace_id: str | None = None,
                        tenant: str | None = None) -> tuple[int, dict]:
        """Forward one /v1/sessions call (the front server's
        ``_session_http`` delegates here when it has no local
        SessionManager).  Placement is STICKY BY SESSION ID — stronger
        than load balancing wants, and on purpose: a session's journal
        lives on one backend, and so does the warm radix prefix tree its
        refresh traffic keeps hitting (the shared map/reduce preambles +
        the transcript prefix).  Bouncing a session between hosts would
        both orphan its journal and cold-start its cache on every hop.

        Creates rendezvous-hash onto the host ring (a client-supplied
        session_id lands deterministically, so a duplicate create
        converges host-side); follow-up traffic routes to the pinned
        host, and an unknown id fleet-scans to rebuild stickiness after
        a router restart — the journals live with the backends, not
        here."""
        with self._stats_lock:
            self._sessions_forwarded += 1
        if method == "POST" and path.rstrip("/") == "/v1/sessions":
            key = (body or {}).get("session_id")
            # a client-supplied id may already live somewhere (create
            # retry, router restart): the existing backend must win, or a
            # fleet-membership change would fork the session onto a
            # second journal that silently misses the earlier segments
            ring: list[_Host] = []
            if key:
                existing = self._locate_session(key)
                if existing is not None:
                    ring = [existing]
                if not ring:
                    # TRUE rendezvous (highest-random-weight over (key,
                    # host)): membership changes move only ~1/N of ids,
                    # unlike modulo-on-the-sorted-list which reshuffles
                    # every placement
                    ring = sorted(
                        self.hosts,
                        key=lambda h: hashlib.sha256(
                            f"{key}|{h.netloc}".encode()).hexdigest(),
                        reverse=True)
            if not ring:
                # anonymous create (server mints the id): nothing stable
                # to hash — hashing the (constant) body would pile every
                # default-params session onto one backend, so place by
                # load/health instead; the returned id pins follow-ups
                ring = sorted(self.hosts,
                              key=lambda h: (not h.healthy, h.served,
                                             h.netloc))
            last: tuple[int, dict] = (503, {"error": {
                "message": "no backend accepted the session",
                "type": "session_error"}})
            for k, host in enumerate(ring):
                if not host.healthy and k < len(ring) - 1:
                    continue
                try:
                    status, payload = self._job_call(host, method, path,
                                                     body, trace_id,
                                                     tenant=tenant)
                except Exception as e:  # noqa: BLE001 - next host
                    host.note_failed()
                    last = (502, {"error": {
                        "message": f"{host.netloc}: {type(e).__name__}: {e}",
                        "type": "session_error"}})
                    continue
                if status == 501:  # backend has no live_dir: keep looking
                    last = (status, payload)
                    continue
                sid = (payload.get("id")
                       if isinstance(payload, dict) else None)
                if sid:
                    self._pin_job(sid, host.netloc)
                return status, payload
            return last
        if method == "GET" and path.split("?", 1)[0].rstrip("/") \
                == "/v1/sessions":
            futures = [self._pool.submit(self._job_call_safe, h, method,
                                         path, None)
                       for h in self.hosts]
            data: list = []
            errors = 0
            for host, fut in zip(self.hosts, futures):
                status, payload = fut.result()
                if status == 200:
                    for doc in payload.get("data", []):
                        if doc.get("id"):
                            self._pin_job(doc["id"], host.netloc)
                        data.append(doc)
                elif status == 502:
                    errors += 1
            return 200, {"object": "list", "data": data,
                         "hosts_unreachable": errors}
        # /v1/sessions/<id>[/sub]: the REAL call goes to the pinned host
        # directly (the jobs pattern — no validation pre-flight doubling
        # every hot-path append's round trips); only a MISS there (404 =
        # session not on that backend, 501 = API off) falls back to a
        # concurrent fleet scan by session STATUS and re-forwards.  A 502
        # (timeout, connection fault) on a MUTATING call is surfaced, not
        # retried: the backend may well have journaled the append before
        # the fault, and a blind re-forward would duplicate segments in
        # the transcript forever.  Refresh-bearing calls run real engine
        # work, so they get the router's generation timeout, not the 10 s
        # control-plane one.
        from urllib.parse import parse_qs, urlsplit

        sid = path.split("/v1/sessions/", 1)[-1].split("?", 1)[0] \
                  .strip("/").split("/")[0]
        # "does this call run engine work?": appends/refreshes/deletes,
        # plus a summary GET whose refresh param the BACKEND would treat
        # as true (same truthiness rule as server._session_http — the
        # two sides must agree or a ?refresh=true would run minutes of
        # refresh under the 10 s control-plane timeout)
        q = parse_qs(urlsplit(path).query)
        wants_refresh = q.get("refresh", ["0"])[-1] not in ("0", "false", "")
        heavy = method in ("POST", "DELETE") or wants_refresh
        tmo = self.timeout_s if heavy else 10.0
        # a 502 on a HEAVY call is surfaced, never blindly re-forwarded:
        # the backend may have journaled the append / started the refresh
        # before the fault, and a retry would duplicate the work (or the
        # transcript)
        rescan_on = (404, 501) if heavy else (404, 501, 502)
        with self._job_lock:
            pinned = self._job_hosts.get(sid)
        if pinned is not None:
            host = next((h for h in self.hosts if h.netloc == pinned), None)
            if host is not None:
                status, payload = self._job_call_safe(host, method, path,
                                                      body, trace_id,
                                                      timeout=tmo)
                if status == 502:
                    # the health signal must degrade whether or not we
                    # rescan — these ARE request-path failures
                    host.note_failed()
                if status not in rescan_on:
                    return status, payload
        host = self._locate_session(sid)
        if host is None:
            return 404, {"error": {
                "message": f"no session {sid} on any backend",
                "type": "session_error"}}
        status, payload = self._job_call_safe(host, method, path, body,
                                              trace_id, timeout=tmo)
        if status == 502:
            host.note_failed()
        return status, payload

    def _locate_session(self, sid: str) -> _Host | None:
        """The backend holding ``sid``: a concurrent fleet scan (GET
        status) that re-pins on a hit — how stickiness survives a router
        restart (callers try the pinned host's real call first)."""
        ordered = sorted(self.hosts,
                         key=lambda h: (not h.healthy, h.netloc))
        futures = [self._pool.submit(self._job_call_safe, h, "GET",
                                     f"/v1/sessions/{sid}", None)
                   for h in ordered]
        for host, fut in zip(ordered, futures):
            status, _payload = fut.result()
            if status == 200:
                self._pin_job(sid, host.netloc)
                return host
        return None

    def _job_call_safe(self, host: _Host, method: str, path: str,
                       body: dict | None,
                       trace_id: str | None = None,
                       timeout: float = 10.0) -> tuple[int, dict]:
        """_job_call with exceptions folded into a 502 tuple (scan legs
        run on the pool; a raise there would surface at .result())."""
        try:
            return self._job_call(host, method, path, body, trace_id,
                                  timeout=timeout)
        except Exception as e:  # noqa: BLE001 - aggregate what answers
            return 502, {"error": {
                "message": f"{host.netloc}: {type(e).__name__}: {e}",
                "type": "job_error"}}

    def _pin_job(self, jid: str, netloc: str) -> None:
        """Record job->host stickiness, bounded: oldest pins evict past
        ``_job_hosts_max`` (an evicted id just pays one fleet re-scan)."""
        with self._job_lock:
            self._job_hosts[jid] = netloc
            while len(self._job_hosts) > self._job_hosts_max:
                self._job_hosts.pop(next(iter(self._job_hosts)))

    def _job_call(self, host: _Host, method: str, path: str,
                  body: dict | None,
                  trace_id: str | None = None,
                  timeout: float = 10.0,
                  tenant: str | None = None) -> tuple[int, dict]:
        """One forwarded job/session call.  A bare connection on purpose
        (like probes): the control plane must not consume the request
        path's ``router.connect`` fault occurrences — chaos plans stay
        replayable.  The default timeout is short because job calls are
        control-plane (submit returns immediately, GET is a status read)
        and a sequential fleet scan must not hold an HTTP handler thread
        30 s per partitioned host; session calls that run ENGINE work
        (appends with refresh, explicit refreshes) pass the router's
        generation timeout instead."""
        conn = http.client.HTTPConnection(host.netloc, timeout=timeout)
        headers = {"Content-Type": "application/json"}
        if trace_id:
            headers["X-LMRS-Trace"] = trace_id
        if tenant:
            headers["X-LMRS-Tenant"] = tenant
        try:
            conn.request(method, path,
                         body=None if body is None else json.dumps(body),
                         headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                payload = json.loads(raw) if raw else {}
            except ValueError:
                payload = {"error": {"message": raw.decode("utf-8",
                                                           "replace")[:200]}}
            return resp.status, payload
        finally:
            conn.close()

    # ------------------------------------------------------------ internals

    def _wave(self, requests: list[GenerationRequest],
              on_tokens) -> list[GenerationResult]:
        cancelled: set[int] = set()
        with self._stats_lock:
            self._wave_cancel_sets.append(cancelled)
            base = self._rr_base
            self._rr_base += len(requests)
        # recovery probes run CONCURRENTLY with the wave, on unhealthy
        # hosts only — a restarted worker re-admits without waiting for
        # total fleet failure (ReplicatedEngine's probe loop, ported);
        # paced per host so heavy traffic cannot turn a dead host into a
        # probe storm (_launch_probes)
        self._launch_probes()
        # radix-summary refresh rides the same wave cadence (prefix-aware
        # placement reads whatever is fresh; never blocks), and placement
        # is PLANNED per wave so same-preamble fan-outs split fairly
        self._refresh_summaries()
        prefers = self._plan_prefix_placement(
            requests, "prefill" if self._disagg_ready() else "full")
        try:
            futures = [
                self._pool.submit(self._one, base + i, req, on_tokens,
                                  cancelled, prefers[i])
                for i, req in enumerate(requests)
            ]
            return [f.result() for f in futures]
        finally:
            with self._stats_lock:
                self._wave_cancel_sets.remove(cancelled)

    def _launch_probes(self) -> list[_Host]:
        """Submit a recovery attempt for each unavailable host whose
        pacing window has elapsed; returns the hosts probed (test hook).
        Recovery is two-stage (_recover_host): the /healthz probe clears
        a connect-phase condemnation; an OPEN breaker past its cooldown
        additionally runs the half-open golden canary, the only thing
        that may close it.  An open breaker still inside its cooldown is
        not touched at all.  The next-probe stamp is claimed under a lock
        BEFORE submission, so concurrent waves racing this method cannot
        double-probe a host — the loser of the race just skips, covered
        by the winner's probe."""
        now = self._clock()
        probed: list[_Host] = []
        with self._probe_lock:
            for host in self.hosts:
                if host.healthy or now < host.next_probe_t:
                    continue
                if host.draining:
                    # draining is deliberate: recovery must not re-admit
                    continue
                if (not host._down and host.breaker_state == "open"
                        and not host.breaker_due()):
                    continue  # cooldown running: no canary yet
                if host.breaker_state == "half_open":
                    continue  # a canary is already in flight
                host.next_probe_t = (now + self.probe_floor_s
                                     + self._probe_rng.random()
                                     * self.probe_jitter_s)
                probed.append(host)
        for host in probed:
            self._pool.submit(self._recover_host, host)
        return probed

    def _recover_host(self, host: _Host) -> None:
        """One paced recovery attempt (pool thread): healthz first when
        the host is connect-condemned, then the breaker canary when its
        cooldown has elapsed."""
        if host._down and not host.probe():
            return
        if host.breaker_state == "open" and host.breaker_due():
            host.canary()

    def _role_pool(self, role: str) -> list[_Host]:
        if role == "full":
            return self.hosts
        return self.pools.get(role) or self.pools["both"] or self.hosts

    def _targets(self, start: int, role: str = "full",
                 prefer: _Host | None = None) -> list[_Host]:
        """Hosts eligible for ``role`` in round-robin order from
        ``start``, healthy first — every eligible host when none is
        marked healthy (a transient fault must not brick the fleet — same
        optimism as ReplicatedEngine).

        Pool-aware (disaggregated serving): role "prefill"/"decode" draws
        from that pool, falling back to the "both" pool when the role
        pool is empty; role "full" (colocated dispatch) draws from EVERY
        host — pool membership is routing policy, not capability, so a
        degraded tier still serves from whatever survives.

        ``prefer`` (prefix-aware placement, _prefix_target) moves one
        host to the FRONT of the order; failover past it is unchanged."""
        pool = self._role_pool(role)
        n = len(pool)
        order = [pool[(start + k) % n] for k in range(n)]
        healthy = [h for h in order if h.healthy]
        out = healthy or order
        if self.slo_route:
            # graded SLO demotion (docs/SERVING.md § routing policy):
            # stable sort by published burn-rate state, so an ok fleet
            # keeps today's rotation byte-for-byte and a degraded host
            # sinks in the failover order instead of vanishing — it still
            # serves when everyone is degraded (the _targets optimism)
            penalties = {h.netloc: self._slo_penalty(h) for h in out}
            if any(penalties.values()):
                first = out[0]
                out = sorted(out, key=lambda h: penalties[h.netloc])
                if out and out[0] is not first:
                    with self._stats_lock:
                        self._slo_penalized += 1
            # a critical sticky preference is NOT fronted: prefix warmth
            # never outranks a host that is actively burning its SLOs
            if prefer is not None and penalties.get(prefer.netloc, 0) >= 2:
                prefer = None
        if prefer is not None and prefer in out:
            out = [prefer] + [h for h in out if h is not prefer]
        return out

    def _tenant_pref(self, req: GenerationRequest,
                     role: str) -> "_Host | None":
        """Chargeback-aware stickiness: the host that last served this
        tenant, while it is healthy and its published SLO state has not
        degraded.  No opinion (None) otherwise — the request falls back
        to plain load/health ordering."""
        if not self.tenant_route or not req.tenant:
            return None
        with self._stats_lock:
            netloc = self._tenant_hosts.get(req.tenant)
        if netloc is None:
            return None
        for h in self._role_pool(role):
            if h.netloc == netloc:
                if h.healthy and self._slo_penalty(h) == 0:
                    with self._stats_lock:
                        self._tenant_routed += 1
                    return h
                return None
        return None

    def _note_tenant_host(self, req: GenerationRequest,
                          host: _Host) -> None:
        """Record a successful placement as the tenant's warm host
        (bounded LRU: re-insert moves to the back, oldest evicts)."""
        if not self.tenant_route or not req.tenant:
            return
        with self._stats_lock:
            self._tenant_hosts.pop(req.tenant, None)
            self._tenant_hosts[req.tenant] = host.netloc
            while len(self._tenant_hosts) > self._tenant_hosts_max:
                self._tenant_hosts.pop(next(iter(self._tenant_hosts)))

    def _slo_penalty(self, host: _Host) -> int:
        """Graded placement penalty from the host's last published SLO
        state (0 ok/unknown, 1 warn, 2 critical).  Stale summaries decay
        to 0 — a host that stopped publishing must not stay penalized
        forever on old news."""
        from lmrs_tpu.obs.slo import state_rank

        with self._summary_lock:
            s = self._summaries.get(host.netloc)
            if s is None or (self._clock() - s.get("slo_at", s["at"])
                             > self.summary_ttl_s):
                return 0
            return state_rank(s.get("slo"))

    # ------------------------------------------------- prefix-aware routing

    def _refresh_summaries(self) -> None:
        """Queue a radix-summary fetch (``GET /healthz`` — the probe-path
        control plane) for every healthy host whose cached summary is
        older than half the TTL.  Stale summaries only degrade placement
        quality; they never block a wave — fetches ride the dispatch
        pool, results land under the summary lock."""
        if not (self.prefix_route or self.slo_route):
            return
        now = self._clock()
        due: list[_Host] = []
        with self._summary_lock:
            for h in self.hosts:
                if not h.healthy or h.netloc in self._summary_inflight:
                    continue
                s = self._summaries.get(h.netloc)
                if s is None or now - s["at"] >= self.summary_ttl_s / 2:
                    self._summary_inflight.add(h.netloc)
                    due.append(h)
        for host in due:
            self._pool.submit(self._fetch_summary, host)

    def _fetch_summary(self, host: _Host) -> None:
        """One summary fetch (pool thread).  A failed fetch still stamps
        ``at`` so a dark host is re-probed at the normal cadence, not
        hammered; its empty map simply predicts no hits."""
        doc = None
        conn = None
        try:
            conn = http.client.HTTPConnection(host.netloc, timeout=2.0)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            if resp.status == 200:
                doc = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - control-plane best effort
            logger.debug("summary fetch failed for %s: %s: %s",
                         host.netloc, type(e).__name__, e)
        finally:
            if conn is not None:
                conn.close()
        smap: dict[str, dict] | None = None
        slo_state: str | None = None
        if isinstance(doc, dict):
            smap = {}
            for ent in doc.get("prefix_summary") or ():
                if isinstance(ent, dict) and ent.get("hash"):
                    smap[str(ent["hash"])] = ent
            slo = doc.get("slo")
            if isinstance(slo, dict) and slo.get("enabled"):
                slo_state = str(slo.get("state") or "ok")
        with self._summary_lock:
            now = self._clock()
            slo_at = now
            if smap is None:
                # transient fetch failure: keep the last-known-good map
                # (stale-but-recent beats empty — an empty overwrite
                # would bounce same-preamble traffic off the warm host
                # for a whole TTL) and stamp the time only, so the host
                # is re-probed at the normal cadence, not hammered.
                # The SLO state keeps its LAST-SUCCESS stamp instead:
                # the penalty must decay on a host that stopped
                # publishing (re-stamping would penalize it forever on
                # old news — the opposite of the prefix-map tradeoff,
                # where stale warmth is still the best placement guess)
                prev = self._summaries.get(host.netloc)
                smap = prev["map"] if prev else {}
                slo_state = (prev or {}).get("slo")
                slo_at = (prev or {}).get("slo_at", 0.0)
            self._summaries[host.netloc] = {"at": now, "map": smap,
                                            "slo": slo_state,
                                            "slo_at": slo_at}
            self._summary_inflight.discard(host.netloc)

    def _prefix_target(self, req: GenerationRequest, role: str = "full"
                       ) -> tuple[_Host | None, bool, bool]:
        """Sticky-by-expected-prefix-hit placement for one request:
        ``(host, predicted, eligible)``.  ``eligible`` is False when the
        request declares no shared preamble (no placement opinion at
        all).  Among healthy hosts of the role pool, the one whose FRESH
        radix summary predicts the deepest hit wins (resident coverage
        weighted over spilled — a resident hit skips even the prefetch);
        with no fresh summary predicting a hit, a deterministic
        rendezvous hash of (preamble, host) places the request so
        same-preamble traffic converges on one host from cold start.
        Host health always wins: an unhealthy pick degrades to the
        normal load/health ordering (``predicted=False, host=None``)."""
        if not self.prefix_route:
            return None, False, False
        key = preamble_key(req.system_prompt, req.prompt, req.cache_prefix)
        if key is None:
            return None, False, False
        healthy = [h for h in self._role_pool(role) if h.healthy]
        if not healthy:
            return None, False, True
        now = self._clock()
        with self._summary_lock:
            views = {h.netloc: self._summaries.get(h.netloc)
                     for h in healthy}
        best, best_score = None, 0
        for h in healthy:
            s = views[h.netloc]
            if s is None or now - s["at"] > self.summary_ttl_s:
                continue  # stale: this host predicts nothing
            ent = s["map"].get(key)
            if not ent:
                continue
            try:
                score = (2 * int(ent.get("resident_tokens") or 0)
                         + int(ent.get("spilled_tokens") or 0))
            except (TypeError, ValueError):
                continue
            if score > best_score:
                best, best_score = h, score
        if best is not None:
            return best, True, True
        best = max(healthy, key=lambda h: hashlib.sha256(
            f"{key}|{h.netloc}".encode()).digest())
        return best, False, True

    def _note_prefix_placement(self, prefer: _Host | None, predicted: bool,
                               eligible: bool) -> None:
        if not eligible:
            return
        with self._stats_lock:
            if prefer is not None:
                self._prefix_routed += 1
                if predicted:
                    self._prefix_predicted += 1
            else:
                self._prefix_fallback += 1

    def _plan_prefix_placement(self, requests: list[GenerationRequest],
                               role: str) -> list[_Host | None]:
        """Wave-scoped prefix placement: group the wave's requests by
        preamble key and give each group's sticky host only its FAIR
        SHARE — ``ceil(group / healthy_hosts)`` members; the rest spread
        through the normal rotation.  Locality for steady single-request
        streams (a group of 1 is fully sticky), parallelism for batch
        fan-outs: a 24-chunk map wave sharing one preamble must NOT
        serialize onto one backend — each host prefills the preamble
        once and the group's remainder hits intra-host, which is exactly
        what round-robin cost before, while cross-WAVE traffic still
        converges on warm hosts.  Placement metrics are counted here
        (spread members count as fallback: they deliberately degraded to
        load ordering)."""
        out: list[_Host | None] = [None] * len(requests)
        if not self.prefix_route:
            return out
        healthy_n = max(1, sum(h.healthy for h in self._role_pool(role)))
        groups: dict[str, list[int]] = {}
        for idx, req in enumerate(requests):
            key = preamble_key(req.system_prompt, req.prompt,
                               req.cache_prefix)
            if key is not None:
                groups.setdefault(key, []).append(idx)
        for key, members in groups.items():
            prefer, predicted, eligible = self._prefix_target(
                requests[members[0]], role)
            share = -(-len(members) // healthy_n)
            if (self.kv_migrate and predicted and prefer is not None
                    and share < len(members)):
                # the group spreads past its warm host: move the
                # predicted prefix to the siblings ahead of the traffic
                self._kv_prefetch_spread(prefer, key, role)
            for k, idx in enumerate(members):
                sticky = prefer if k < share else None
                out[idx] = sticky
                self._note_prefix_placement(
                    sticky, predicted and sticky is not None, eligible)
        return out

    def _disagg_ready(self) -> bool:
        """True while the two-tier handoff path is viable: both role
        pools have members AND at least one healthy host each.  Anything
        less falls the whole tier back to colocated operation (the
        graceful-degradation contract, docs/SERVING.md)."""
        if not (self.pools["prefill"] and self.pools["decode"]):
            # no explicit split: nothing to disaggregate
            return False
        return (any(h.healthy for h in self.pools["prefill"])
                and any(h.healthy for h in self.pools["decode"]))

    def _one(self, i: int, req: GenerationRequest, on_tokens,
             cancelled: set[int],
             prefer: _Host | None = None) -> GenerationResult:
        # trace ingress for engine-protocol callers (the executor, a
        # fronting server hands requests that already carry one): every
        # forward, retry, and handoff leg re-sends the id via the
        # X-LMRS-Trace header, so one request is ONE trace fleet-wide
        if req.trace_id is None:
            req.trace_id = new_trace_id()
        if prefer is None:
            # prefix placement had no opinion: fall back to tenant
            # affinity (chargeback-aware routing, weakest preference)
            prefer = self._tenant_pref(
                req, "prefill" if self._disagg_ready() else "full")
        if self._disagg_ready():
            res = self._one_disagg(i, req, on_tokens, cancelled, prefer)
            if res is not None:
                return res
            # the two-tier flow degraded (no ticket, decode pool dark,
            # ticket expired/consumed): RE-PREFILL colocated below — any
            # full-capable host runs the whole request; the prefix cache
            # on a previously-tried host makes the retry cheap
            self._count("_handoff_fallbacks")
            logger.warning("request %d: handoff degraded; re-prefilling "
                           "colocated", req.request_id)
        # tail hedging (read per request so A/B harnesses can flip the
        # knob on a live router): non-streamed only — duplicating an SSE
        # stream would double every delta the client already holds
        hedge_ms = env_float("LMRS_HEDGE_MS", 0.0, lo=0.0)
        if hedge_ms > 0 and on_tokens is None and len(self.hosts) > 1:
            return self._one_hedged(i, req, cancelled, prefer, hedge_ms)
        return self._one_colocated(i, req, on_tokens, cancelled, prefer)

    def _one_colocated(self, i: int, req: GenerationRequest, on_tokens,
                       cancelled: set[int],
                       prefer: _Host | None = None) -> GenerationResult:
        rid = req.request_id
        last_err = "no healthy backend"
        for attempt, host in enumerate(
                self._targets(i, "full", prefer=prefer)[:2]):
            if rid in cancelled:
                return GenerationResult(request_id=rid,
                                        finish_reason="cancelled")
            rem = remaining_budget(req)
            if rem is not None and rem <= 0:
                # retry clipping: the budget is gone — a second host could
                # not answer in time, so report the deadline instead of
                # burning a backend slot on a worthless attempt
                return GenerationResult(request_id=rid,
                                        finish_reason="deadline")
            streamed = [0]  # deltas already forwarded on THIS request
            try:
                t_leg = time.time()
                res = self._post(host, req, on_tokens, streamed, cancelled)
                if on_tokens is None:
                    # the hedge-delay p99 pool holds NON-streamed
                    # completion walls only: SSE walls are client-paced
                    # and would inflate the p99 until hedging never fires
                    self._note_latency(time.time() - t_leg)
                host.note_served()
                host.healthy = True
                self._note_tenant_host(req, host)
                return res
            except Exception as e:  # noqa: BLE001 - degrade per request
                if rid in cancelled:
                    # the hangup WE caused: report the abort, not an error
                    return GenerationResult(request_id=rid,
                                            finish_reason="cancelled")
                host.note_failed()
                if isinstance(e, _HostConnectError):
                    # only a connect-phase failure condemns the host: a
                    # slow completion's socket timeout or a truncated
                    # response must not evict a live host from the fleet
                    host.healthy = False
                last_err = f"{host.netloc}: {type(e).__name__}: {e}"
                logger.warning("request %d failed on %s (attempt %d): %s",
                               rid, host.netloc, attempt + 1, last_err)
                if streamed[0]:
                    # a retry would REPLAY the already-forwarded deltas
                    # through on_tokens, breaking the Engine contract that
                    # delta concatenation equals the final text — surface
                    # the mid-stream failure instead
                    break
        return GenerationResult(request_id=rid, finish_reason="error",
                                error=last_err)

    # ------------------------------------------------------- tail hedging

    def _note_latency(self, dt: float) -> None:
        """One successful non-streamed completion wall (the hedge delay's
        p99 sample pool)."""
        with self._stats_lock:
            self._lat_s.append(dt)

    def _hedge_delay_s(self, hedge_ms: float) -> float:
        """How long the primary leg may run before the hedge launches:
        the observed p99 completion wall once enough samples exist (a
        hedge should only chase genuine TAIL stragglers), floored at the
        operator's LMRS_HEDGE_MS."""
        base = hedge_ms / 1000.0
        with self._stats_lock:
            lat = sorted(self._lat_s)
        if len(lat) >= 20:
            return max(base, lat[int(0.99 * (len(lat) - 1))])
        return base

    def _one_hedged(self, i: int, req: GenerationRequest,
                    cancelled: set[int], prefer: _Host | None,
                    hedge_ms: float) -> GenerationResult:
        """Colocated dispatch with tail hedging: the primary leg runs on
        the normal first-choice host; if it has not completed within the
        hedge delay, a DUPLICATE leg launches on the next host in the
        failover order.  First non-error result wins (results are keyed
        by request id, so fan-out callers cannot mix legs up); every
        other leg is hung up — the backend's disconnect detection cancels
        the duplicate server-side and frees its slot/pages (the existing
        cancel plumbing).  Greedy token-identity is preserved: both legs
        run the same request on identical weights.

        Failover is NOT traded away: a primary that fails FAST (before
        the hedge delay) still gets the sibling attempt — as a plain
        failover leg, not a hedge (no hedge counters, no duplicate) —
        so arming LMRS_HEDGE_MS can never degrade availability below
        the _one_colocated targets[:2] contract."""
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as _fwait

        rid = req.request_id
        rem = remaining_budget(req)
        if rem is not None and rem <= 0:
            return GenerationResult(request_id=rid,
                                    finish_reason="deadline")
        targets = self._targets(i, "full", prefer=prefer)
        primary, sibling = targets[0], (targets[1] if len(targets) > 1
                                        else None)
        # loser-abort marker: added to before the hangup so a loser still
        # PRE-connect (its _inflight target is a socketless
        # HTTPConnection whose close() no-ops) aborts itself at _post's
        # post-request re-check instead of running a full duplicate
        # generation nobody consumes.  Union-viewed with the wave's
        # cancel set — _post/_read_sse only do membership tests.
        aborted: set[int] = set()

        class _Either:
            __slots__ = ()

            def __contains__(_self, x) -> bool:
                return x in cancelled or x in aborted

        leg_cancel = _Either()

        def leg(host: _Host, key) -> GenerationResult:
            t0 = time.time()
            res = self._post(host, req, None, [0], leg_cancel,
                             inflight_key=key)
            self._note_latency(time.time() - t0)
            return res

        def spawn(host: _Host, key) -> "Future":
            # a fresh daemon thread per leg, NOT the dispatch pool: _one
            # already runs on a pool thread, and legs queued behind a
            # saturated wave's _one tasks would deadlock the pool
            # (every runner waiting on a leg that can never start)
            from concurrent.futures import Future

            fut: Future = Future()

            def run_leg():
                if not fut.set_running_or_notify_cancel():
                    return
                try:
                    fut.set_result(leg(host, key))
                except BaseException as e:  # noqa: BLE001 - future carries
                    fut.set_exception(e)

            threading.Thread(target=run_leg, daemon=True,
                             name=f"lmrs-hedge-{rid}").start()
            return fut

        # future -> (host, inflight key, is_hedge)
        legs: dict = {}
        fut_p = spawn(primary, rid)
        legs[fut_p] = (primary, rid, False)
        delay_s = self._hedge_delay_s(hedge_ms)  # computed ONCE: the
        # wait and the log must agree (and the reservoir sort is paid once)
        _done, still_running = _fwait({fut_p}, timeout=delay_s)
        if still_running and sibling is not None and rid not in cancelled:
            try:
                # injection site: "raise" abandons THIS hedge (the
                # primary leg continues alone — hedging is an
                # optimization); "stall" delays its launch
                faults.fire("router.hedge")
                fut_h = spawn(sibling, ("hedge", rid))
                legs[fut_h] = (sibling, ("hedge", rid), True)
                self._count("_hedges")
                logger.info("request %d: hedged to %s after %.0f ms "
                            "straggle", rid, sibling.netloc,
                            delay_s * 1e3)
            except Exception:  # noqa: BLE001 - degrade to primary-only
                logger.warning("hedge launch for %d abandoned", rid,
                               exc_info=True)
        winner: GenerationResult | None = None
        error_res: GenerationResult | None = None
        last_err = "no healthy backend"
        pending = set(legs)
        while pending and winner is None:
            done, pending = _fwait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                host, _key, is_hedge = legs[f]
                try:
                    res = f.result()
                except Exception as e:  # noqa: BLE001 - per-leg degrade
                    if rid in cancelled:
                        winner = GenerationResult(request_id=rid,
                                                  finish_reason="cancelled")
                        break
                    host.note_failed()
                    if isinstance(e, _HostConnectError):
                        host.healthy = False
                    last_err = f"{host.netloc}: {type(e).__name__}: {e}"
                    logger.warning("hedge leg for %d failed on %s: %s",
                                   rid, host.netloc, last_err)
                    # this leg DIED: if no other leg is running and the
                    # sibling was never tried, launch it as a plain
                    # FAILOVER attempt — the targets[:2] availability
                    # contract must survive arming the hedge knob
                    if (not pending and sibling is not None
                            and not any(h is sibling
                                        for h, _k, _h2 in legs.values())
                            and rid not in cancelled):
                        fut_f = spawn(sibling, ("hedge", rid))
                        legs[fut_f] = (sibling, ("hedge", rid), False)
                        pending = {fut_f}
                    continue
                if res.finish_reason != "error":
                    host.note_served()
                    host.healthy = True
                    self._note_tenant_host(req, host)
                    if is_hedge:
                        self._count("_hedge_wins")
                    winner = res
                    break
                # a backend-ANSWERED error result: the host served it
                # (_one_colocated parity — request-level engine errors
                # must not feed the breaker or trigger failover); keep it
                # as the outcome unless a concurrent leg wins outright
                host.note_served()
                error_res = res
                last_err = res.error or "backend error"
        # hang up the loser leg(s): abort-mark first (pre-connect losers
        # self-abort at the post-request check), then FIN the socket —
        # the backend's disconnect detection cancels server-side
        if winner is not None and any(not f.done() for f in legs):
            aborted.add(rid)
        for f, (_host, key, _h) in legs.items():
            if not f.done():
                with self._inflight_lock:
                    target = self._inflight.get(key)
                self._hangup(target)
        if winner is not None:
            return winner
        if error_res is not None:
            return error_res
        return GenerationResult(request_id=rid, finish_reason="error",
                                error=last_err)

    def _one_disagg(self, i: int, req: GenerationRequest, on_tokens,
                    cancelled: set[int],
                    prefer: _Host | None = None) -> GenerationResult | None:
        """Two-tier dispatch: prefill pool mints a KV handoff ticket, the
        decode pool follows it.  Returns None to fall back to colocated
        re-prefill (no ticket obtainable, decode attempts exhausted, or
        the ticket went stale) — EXCEPT once deltas have streamed, when a
        failure must surface instead (a fallback would replay them).

        At-most-once: the ticket is consumed by the first decode host
        that acks; a failed decode attempt retries a sibling (fresh
        import of the still-pinned pages), and a dead decode pod simply
        never acks — the prefill pod's orphan sweep reclaims the pinned
        pages at the ticket deadline while we re-prefill elsewhere."""
        rid = req.request_id
        # ---- stage 1: prefill + ticket ---------------------------------
        # the radix tree lives with prefill work: prefix placement
        # (planned per wave, _plan_prefix_placement) steers the PREFILL
        # leg; the decode leg stays load/health ordered
        ticket = None
        for host in self._targets(i, "prefill", prefer=prefer)[:2]:
            if rid in cancelled:
                return GenerationResult(request_id=rid,
                                        finish_reason="cancelled")
            rem = remaining_budget(req)
            if rem is not None and rem <= 0:
                return GenerationResult(request_id=rid,
                                        finish_reason="deadline")
            try:
                kind, out = self._post_prefill(host, req, cancelled)
            except Exception as e:  # noqa: BLE001 - degrade per host
                if rid in cancelled:
                    return GenerationResult(request_id=rid,
                                            finish_reason="cancelled")
                host.note_failed()
                if isinstance(e, _HostConnectError):
                    host.healthy = False
                logger.warning("prefill leg for %d failed on %s: %s: %s",
                               rid, host.netloc, type(e).__name__, e)
                continue
            host.healthy = True
            if kind == "result":
                if out.finish_reason == "error":
                    host.note_failed()
                    continue  # next prefill host, then colocated fallback
                # first token was terminal (EOS/stop/1-token budget) or a
                # deadline outcome: the prefill response IS the completion
                host.note_served()
                if on_tokens is not None and out.text:
                    on_tokens(rid, out.text)
                return out
            ticket = out  # {"ticket", "source", "first_text", ...}
            host.note_served()  # a minted ticket IS a served prefill leg
            break
        if ticket is None:
            return None  # no prefill pod could mint a ticket: fall back
        self._count("_handoffs")
        # ---- stage 2: decode follows the ticket ------------------------
        extra = {"handoff": {"ticket": ticket["ticket"],
                             "source": ticket["source"]}}
        streamed = [0]
        for host in self._targets(i + 1, "decode")[:2]:
            if rid in cancelled:
                return GenerationResult(request_id=rid,
                                        finish_reason="cancelled")
            rem = remaining_budget(req)
            if rem is not None and rem <= 0:
                # budget gone between legs: deadline contract keeps the
                # partial text (docs/ROBUSTNESS.md) — the first token the
                # prefill pod minted is real paid-for output, same as the
                # colocated in-flight expiry path
                first = str(ticket.get("first_text") or "")
                if first and on_tokens is not None and not streamed[0]:
                    on_tokens(rid, first)
                return GenerationResult(
                    request_id=rid, text=first,
                    prompt_tokens=int(ticket.get("prompt_tokens", 0) or 0),
                    completion_tokens=int(ticket.get("completion_tokens",
                                                     0) or 0),
                    finish_reason="deadline")
            try:
                res = self._post(host, req, on_tokens, streamed, cancelled,
                                 body_extra=extra)
            except Exception as e:  # noqa: BLE001 - degrade per host
                if rid in cancelled:
                    return GenerationResult(request_id=rid,
                                            finish_reason="cancelled")
                host.note_failed()
                if isinstance(e, _HostConnectError):
                    host.healthy = False
                self._count("_handoff_retries")
                logger.warning("decode leg for %d failed on %s: %s: %s",
                               rid, host.netloc, type(e).__name__, e)
                if streamed[0]:
                    # deltas already forwarded: a retry or fallback would
                    # replay them — surface the mid-stream failure
                    return GenerationResult(
                        request_id=rid, finish_reason="error",
                        error=f"{host.netloc}: {type(e).__name__}: {e}")
                continue
            if res.finish_reason == "error":
                # marked handoff failure (410 gone, duplicate, transfer
                # fault, import failure): try a sibling decode host while
                # the ticket may still be live, then fall back
                host.note_failed()
                self._count("_handoff_retries")
                logger.warning("decode leg for %d rejected on %s: %s",
                               rid, host.netloc, res.error)
                if streamed[0]:
                    return res
                continue
            host.note_served()
            host.healthy = True
            return res
        return None if not streamed[0] else GenerationResult(
            request_id=rid, finish_reason="error",
            error="handoff decode attempts exhausted mid-stream")

    def _post_prefill(self, host: _Host, req: GenerationRequest,
                      cancelled: set[int]):
        """POST the prefill leg (``handoff: true``, never streamed) and
        parse either outcome: ``("ticket", desc)`` for a minted handoff
        ticket (source filled in with the answering host), or
        ``("result", GenerationResult)`` when the first token was already
        terminal and the prefill response is the whole completion."""
        body = _request_body(req)
        body["handoff"] = True
        rid = req.request_id
        timeout = self.timeout_s
        rem = remaining_budget(req)
        if rem is not None:
            # same clip as _post: a wedged prefill pod must not hold a
            # dispatch thread past the request's own deadline budget
            timeout = max(1.0, min(timeout, rem + 5.0))
        conn = host.connect(timeout)
        host.note_leg(+1)
        with self._inflight_lock:
            self._inflight[rid] = conn
        try:
            try:
                conn.connect()
            except OSError as e:
                raise _HostConnectError(str(e)) from e
            with self._inflight_lock:
                self._inflight[rid] = conn.sock
            headers = {"Content-Type": "application/json"}
            if req.trace_id:
                headers["X-LMRS-Trace"] = req.trace_id
            if req.tenant:
                headers["X-LMRS-Tenant"] = req.tenant
            conn.request("POST", "/v1/chat/completions",
                         body=json.dumps(body), headers=headers)
            if rid in cancelled:
                raise ConnectionAbortedError("cancelled during connect")
            resp = conn.getresponse()
            if resp.status != 200:
                return "result", GenerationResult(
                    request_id=rid, finish_reason="error",
                    error=self._error_message(resp))
            data = json.loads(resp.read())
            if "handoff" in data:
                desc = dict(data["handoff"])
                desc.setdefault("source", host.netloc)
                return "ticket", desc
            choice = data["choices"][0]
            usage = data.get("usage") or {}
            return "result", GenerationResult(
                request_id=rid,
                text=choice["message"]["content"],
                prompt_tokens=int(usage.get("prompt_tokens", 0)),
                completion_tokens=int(usage.get("completion_tokens", 0)),
                finish_reason=choice.get("finish_reason") or "stop",
                usage=usage.get("cost") or None,
            )
        finally:
            host.note_leg(-1)
            with self._inflight_lock:
                self._inflight.pop(rid, None)
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    def _post(self, host: _Host, req: GenerationRequest, on_tokens,
              streamed: list[int], cancelled: set[int],
              body_extra: dict | None = None,
              inflight_key=None) -> GenerationResult:
        body = _request_body(req)
        if body_extra:
            body.update(body_extra)
        if on_tokens is not None:
            body["stream"] = True
            body["stream_options"] = {"include_usage": True}
        timeout = self.timeout_s
        rem = remaining_budget(req)
        if rem is not None:
            # the socket wait needs no more than the remaining budget plus
            # slack for the backend's own deadline result to come back —
            # without the clip an expired request would hold a dispatch
            # thread for the full worst-case-generation timeout
            timeout = max(1.0, min(timeout, rem + 5.0))
        conn = host.connect(timeout)
        host.note_leg(+1)
        rid = req.request_id
        # hedged legs register under their own key so two concurrent legs
        # of ONE rid never clobber each other's hangup target; the plain
        # path keys by rid (what cancel() looks up)
        key = rid if inflight_key is None else inflight_key
        with self._inflight_lock:
            self._inflight[key] = conn
        try:
            try:
                conn.connect()  # explicit: connect failures mean HOST DOWN
            except OSError as e:
                raise _HostConnectError(str(e)) from e
            with self._inflight_lock:
                # re-pin to the RAW socket: getresponse() will detach it
                # from the conn for Connection:close responses (SSE), and
                # cancel() must still be able to hang up
                self._inflight[key] = conn.sock
            payload = json.dumps(body)
            headers = {"Content-Type": "application/json"}
            if req.trace_id:
                headers["X-LMRS-Trace"] = req.trace_id
            if req.tenant:
                headers["X-LMRS-Tenant"] = req.tenant
            conn.request("POST", "/v1/chat/completions", body=payload,
                         headers=headers)
            # close the cancel() race on an unconnected conn: cancel adds
            # its id BEFORE closing, and close() on a socketless
            # HTTPConnection no-ops (request() would then auto-open a
            # fresh socket and the hangup would vanish) — so re-check now
            # that the socket exists, and hang up ourselves if it fired
            # in the window
            if rid in cancelled:
                raise ConnectionAbortedError("cancelled during connect")
            resp = conn.getresponse()
            if resp.status != 200:
                # status BEFORE body parse: a proxy's HTML 502 must not be
                # misclassified as a connection failure (which would mark
                # the host unhealthy and burn the retry)
                return GenerationResult(request_id=rid, finish_reason="error",
                                        error=self._error_message(resp))
            if on_tokens is not None:
                return self._read_sse(resp, req, on_tokens, streamed,
                                      cancelled)
            data = json.loads(resp.read())
            choice = data["choices"][0]
            usage = data.get("usage") or {}
            return GenerationResult(
                request_id=rid,
                text=choice["message"]["content"],
                prompt_tokens=int(usage.get("prompt_tokens", 0)),
                completion_tokens=int(usage.get("completion_tokens", 0)),
                finish_reason=choice.get("finish_reason") or "stop",
                # the backend ledger's bill rides back through the router
                # (fronting servers re-surface it; jobs roll it up)
                usage=usage.get("cost") or None,
            )
        finally:
            host.note_leg(-1)
            with self._inflight_lock:
                self._inflight.pop(key, None)
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _error_message(resp) -> str:
        try:
            data = json.loads(resp.read())
            return (data.get("error") or {}).get(
                "message", f"HTTP {resp.status}")
        except Exception:  # noqa: BLE001 - malformed error body
            return f"HTTP {resp.status}"

    def _read_sse(self, resp, req: GenerationRequest, on_tokens,
                  streamed: list[int],
                  cancelled: set[int]) -> GenerationResult:
        """Consume a chat.completion.chunk SSE stream, forwarding content
        deltas; the terminal chunk carries finish_reason and (via
        stream_options.include_usage, which _post requests) exact usage.
        A cancel-induced hangup mid-stream keeps the deltas already
        received (the in-process engines' keep-partial-output contract,
        scheduler.cancel docstring) instead of discarding them."""
        rid = req.request_id
        text_parts: list[str] = []
        finish = "stop"
        usage: dict = {}
        done_seen = False  # the [DONE] terminator actually arrived
        try:
            for raw in resp:
                # injection site: a mid-stream fault — "raise" simulates a
                # worker dying mid-response (no retry: deltas already
                # forwarded), "stall" a backend gone slow under load
                faults.fire("router.recv", OSError)
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data:"):
                    continue
                data = line[5:].strip()
                if data == "[DONE]":
                    done_seen = True
                    break
                evt = json.loads(data)
                if "error" in evt:
                    return GenerationResult(
                        request_id=rid, finish_reason="error",
                        error=evt["error"].get("message", "?"))
                choice = evt["choices"][0]
                delta = choice.get("delta") or {}
                piece = delta.get("content")
                if piece:
                    text_parts.append(piece)
                    streamed[0] += 1
                    on_tokens(rid, piece)
                if choice.get("finish_reason"):
                    finish = choice["finish_reason"]
                if evt.get("usage"):
                    usage = evt["usage"]
        except OSError:
            if rid not in cancelled:
                raise
            finish = "cancelled"
            done_seen = True  # partial-output contract: keep the deltas
        if not done_seen:
            # The server's SSE body has NO length framing (the connection
            # closes to end it, server.py _sse_headers), so a hangup or a
            # worker crash mid-stream reads as a CLEAN EOF here — without
            # this check a cancelled or truncated stream would be reported
            # as a normal 'stop' completion.
            if rid in cancelled:
                finish = "cancelled"
            else:
                raise ConnectionResetError(
                    "SSE stream ended before [DONE] "
                    f"({len(text_parts)} deltas received)")
        return GenerationResult(
            request_id=rid, text="".join(text_parts),
            prompt_tokens=int(usage.get("prompt_tokens", 0)),
            completion_tokens=int(usage.get("completion_tokens",
                                            len(text_parts))),
            finish_reason=finish,
            usage=usage.get("cost") or None)
