"""``lmrs-serve``: stand up the OpenAI/Anthropic-compatible server.

Inverts the reference's deployment: instead of the summarizer calling out to
``api.openai.com`` (llm_executor.py:292), any OpenAI/Anthropic-format client
calls in to the TPU pod.

    lmrs-serve --backend mock --port 8000
    lmrs-serve --backend jax --model gemma-2b --mesh 2,4 --port 8000
"""

from __future__ import annotations

import argparse
import logging

from lmrs_tpu.config import EngineConfig, parse_mesh
from lmrs_tpu.engine.api import make_engine
from lmrs_tpu.utils.env import env_bool
from lmrs_tpu.utils.logging import setup_logging

logger = logging.getLogger("lmrs.serving")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lmrs-serve",
        description="OpenAI/Anthropic-wire-compatible HTTP server over the "
                    "in-tree TPU engine",
        # no prefix abbreviation: --supervise re-execs this CLI with the
        # flag stripped by EXACT match — an abbreviated "--supervis"
        # would survive the strip and fork supervisors recursively
        allow_abbrev=False,
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--backend", default="mock", choices=["mock", "jax"])
    p.add_argument("--model", default="tiny", help="model preset name")
    p.add_argument("--mesh", default=None,
                   help="device mesh axes as dp,tp[,sp[,pp]], e.g. 2,4")
    p.add_argument("--checkpoint", default=None, help="Orbax checkpoint dir")
    p.add_argument("--tokenizer", default=None,
                   help="serving tokenizer: 'byte', a *.model SentencePiece "
                        "path, or an HF tokenizer dir (the checkpoint's own "
                        "vocabulary; default: model-derived)")
    p.add_argument("--quantize", default=None, choices=["int8"])
    p.add_argument("--kv-quantize", default=None, choices=["int8"])
    p.add_argument("--batch-slots", type=int, default=8,
                   help="continuous-batching decode slots")
    p.add_argument("--max-tokens-cap", type=int, default=4096,
                   help="upper bound on any request's max_tokens")
    p.add_argument("--batch-window-ms", type=float, default=20.0,
                   help="micro-batching window for pooling concurrent requests")
    p.add_argument("--role", default="both",
                   choices=["prefill", "decode", "both"],
                   help="disaggregated serving role: 'prefill' stops "
                        "handoff-flagged requests after the first token "
                        "and publishes a KV-page ticket, 'decode' imports "
                        "tickets and continues, 'both' (default) serves "
                        "colocated (docs/SERVING.md)")
    p.add_argument("--handoff-ttl", type=float, default=None,
                   help="seconds an un-acked handoff ticket pins its KV "
                        "pages before the orphan sweep reclaims them "
                        "(default: LMRS_HANDOFF_TTL or 60)")
    p.add_argument("--jobs-dir", default=None,
                   help="enable the durable async job API (POST/GET/DELETE "
                        "/v1/jobs): write-ahead journals live here and "
                        "interrupted jobs resume on startup (default: "
                        "LMRS_JOBS_DIR; unset disables — 501)")
    p.add_argument("--live-dir", default=None,
                   help="enable the live-session API (POST/GET/DELETE "
                        "/v1/sessions*): growing transcripts summarized "
                        "incrementally, journaled here and rehydrated on "
                        "startup (default: LMRS_LIVE_DIR; unset disables "
                        "— 501)")
    p.add_argument("--supervise", action="store_true",
                   help="run the server in a supervised CHILD process: "
                        "the parent polls /healthz and SIGKILL-respawns "
                        "the child on a watchdog-declared wedge, a hang, "
                        "or a crash; jobs/sessions resume from their "
                        "journals across the bounce (docs/ROBUSTNESS.md "
                        "§ Supervised restart)")
    p.add_argument("--trace", action="store_true",
                   help="enable the in-process lifecycle tracer; GET "
                        "/v1/trace then serves this host's span ring "
                        "(Chrome-trace JSON) for the router-side fleet "
                        "stitcher (also: LMRS_TRACE=1)")
    p.add_argument("--quiet", "-q", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(quiet=args.quiet)
    if args.supervise:
        # parent mode: never builds an engine — it spawns this same CLI
        # (minus --supervise) as a child and owns only its lifecycle
        import sys as _sys

        from lmrs_tpu.serving.supervisor import Supervisor

        raw = list(argv) if argv is not None else _sys.argv[1:]
        child_argv = [a for a in raw if a != "--supervise"]
        return Supervisor(child_argv, host=args.host, port=args.port).run()
    from lmrs_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    if args.trace or env_bool("LMRS_TRACE", False):
        # before the engine builds: the scheduler captures the tracer per
        # run, and serving spans must cover the first request
        from lmrs_tpu.obs import enable_tracing

        enable_tracing()
    engine_cfg = EngineConfig(
        backend=args.backend,
        model=args.model,
        max_batch_slots=args.batch_slots,
        checkpoint_path=args.checkpoint,
        tokenizer=args.tokenizer or "",
        quantize=args.quantize,
        kv_quantize=args.kv_quantize,
        max_tokens=args.max_tokens_cap,
        # explicit flag wins over LMRS_HANDOFF_TTL; validated by the
        # config's __post_init__ (a non-positive TTL would disable the
        # orphan-sweep backstop)
        **({"handoff_ttl_s": args.handoff_ttl}
           if args.handoff_ttl is not None else {}),
    )
    mesh_cfg = parse_mesh(args.mesh) if args.mesh else None
    try:
        engine = make_engine(engine_cfg, mesh_cfg=mesh_cfg)
    except ValueError as e:
        logger.error("engine init failed: %s", e)
        return 1

    from lmrs_tpu.serving.server import EngineHTTPServer

    try:
        from lmrs_tpu.config import PipelineConfig

        server = EngineHTTPServer(
            engine, host=args.host, port=args.port, model_name=args.model,
            max_tokens_cap=args.max_tokens_cap,
            batch_window_s=args.batch_window_ms / 1000.0,
            role=args.role, handoff_ttl_s=engine_cfg.handoff_ttl_s,
            jobs_dir=args.jobs_dir,
            live_dir=args.live_dir,
            # the job/session fingerprints must reflect the SERVED
            # model/config, not PipelineConfig defaults
            pipeline_config=PipelineConfig(engine=engine_cfg),
        )
    except OSError as e:
        logger.error("cannot bind %s:%d: %s", args.host, args.port, e)
        engine.shutdown()
        return 1
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        server.shutdown()
        engine.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
