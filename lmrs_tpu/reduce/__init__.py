"""L7 reduce: single-pass + hierarchical aggregation of chunk summaries."""

from lmrs_tpu.reduce.aggregator import ResultAggregator, SimpleAggregator

__all__ = ["ResultAggregator", "SimpleAggregator"]
