"""Reduce stage: combine per-chunk summaries into one final summary.

Successor of ``ResultAggregator`` (result_aggregator.py:26-524) and
``SimpleAggregator`` (simple_aggregator.py:26-189), with the reference's
defects deliberately fixed (SURVEY.md §2.3):

* provider-agnostic — every reduce call routes through the same engine as the
  map stage instead of a hardwired OpenAI POST (quirk 5);
* the custom reduce prompt is always honored and its ``{summaries}`` /
  ``{metadata}`` / ``{num_summaries}`` placeholders are really substituted
  (quirk 6 — the reference only honors templates containing the magic string
  "TIMELINE SUMMARY" and never formats placeholders);
* the hierarchical tree recurses until the batch fits the token budget
  (bounded by ``max_levels``) instead of stopping at exactly two levels
  (quirk 11);
* reduce token usage lands in the same executor counters
  (result_aggregator.py:266-278 behavior, kept).
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Any, Sequence

from lmrs_tpu.config import ReduceConfig
from lmrs_tpu.data.chunker import Chunk
from lmrs_tpu.data.preprocessor import format_timestamp
from lmrs_tpu.data.tokenizer import Tokenizer, get_tokenizer
from lmrs_tpu.engine.api import GenerationRequest, degraded_reason
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.obs import PID_PIPELINE, get_tracer
from lmrs_tpu.prompts import (
    DEFAULT_BATCH_REDUCE_PROMPT,
    DEFAULT_FINAL_REDUCE_PROMPT,
    DEFAULT_REDUCE_PROMPT,
    safe_format,
    shared_prefix_chars,
)

logger = logging.getLogger("lmrs.reduce")


def content_node_id(display: str, summaries: Sequence[str],
                    template: str | None,
                    metadata: dict | None = None) -> str:
    """Reduce-node identity = positional display name + a hash of the
    node's ACTUAL prompt inputs (children's text, template, AND metadata
    — metadata is substituted into the prompt, so two nodes differing
    only there are different nodes).  The positional part
    (``L<level>.B<batch>``) is for humans — logs, journal records; the
    content hash is what node caches may key on: inserting a leaf shifts
    every later batch's position, and a purely positional id would
    poison each of their cached entries while a content-derived one
    keeps every unchanged subtree addressable.  Canonical-JSON payload
    (the jobs journal's node_key construction) — a delimiter join over
    raw strings would collide on summaries containing the delimiter."""
    import json

    digest = hashlib.sha256(json.dumps(
        [template or "", metadata or {}, list(summaries)],
        sort_keys=True, separators=(",", ":"), ensure_ascii=False,
        default=str,
    ).encode("utf-8", "replace")).hexdigest()[:12]
    return f"{display}@{digest}"


class ResultAggregator:
    """Single-pass or hierarchical reduce over chunk summaries."""

    def __init__(
        self,
        executor: MapExecutor,
        config: ReduceConfig | None = None,
        tokenizer: Tokenizer | str = "approx",
    ):
        self.executor = executor
        self.config = config or ReduceConfig()
        self.tokenizer = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
        self._wave_errors = 0  # error-marker nodes, reset per aggregate()

    # ------------------------------------------------------------------ API

    def aggregate(
        self,
        processed_chunks: Sequence[Chunk],
        prompt_template: str | None = None,
        metadata: dict[str, Any] | None = None,
        node_cache: Any | None = None,
    ) -> dict[str, Any]:
        """Reduce chunk summaries to one final summary.

        Mirrors ``ResultAggregator.aggregate`` (result_aggregator.py:55-109):
        time-tags each summary, then picks single-pass vs hierarchical by
        total token count against ``max_tokens_per_batch``.

        ``node_cache`` is the crash-safe resume hook (lmrs_tpu/jobs/):
        an object with ``lookup(node_id, summaries, template, metadata)
        -> str | None`` and ``record(node_id, summaries, template,
        metadata, text)``.  Every reduce-tree node gets a DETERMINISTIC
        id (``L<level>.B<batch>`` / ``final``) and is offered to the
        cache before the engine runs it; chunking and the tree shape are
        deterministic in (transcript, config), so a resumed run
        recomputes the same node inputs and lands exactly on the
        journaled nodes — a crash mid-reduce resumes at the tree node it
        died at, not at the start of the stage.
        """
        t0 = time.time()
        self._wave_errors = 0
        chunks = sorted(processed_chunks, key=lambda c: c.chunk_index)
        summaries = [
            f"[Time: {format_timestamp(c.start_time)} - {format_timestamp(c.end_time)}]\n"
            f"{c.summary or ''}"
            for c in chunks
        ]
        total_tokens = self._total_tokens(summaries)
        if self.config.stable_tree:
            # shape is a function of LEAF COUNT alone (append-stability:
            # token totals grow with every append and would reshape the
            # tree; the count only ever appends new batches at the edge)
            hierarchical = (self.config.hierarchical
                            and len(summaries) > self._stable_arity())
        else:
            hierarchical = (self.config.hierarchical
                            and total_tokens > self.config.max_tokens_per_batch)
        logger.info(
            "reduce: %d summaries, %d tokens -> %s%s",
            len(summaries), total_tokens,
            "hierarchical" if hierarchical else "single-pass",
            " (stable tree)" if self.config.stable_tree else "",
        )
        if hierarchical and self.config.stable_tree:
            summary, levels = self._hierarchical_stable(
                summaries, prompt_template, metadata, node_cache)
        elif hierarchical:
            summary, levels = self._hierarchical(summaries, prompt_template,
                                                 metadata, node_cache)
        else:
            t_level = time.time()
            summary = self._reduce_once(
                summaries, prompt_template or DEFAULT_REDUCE_PROMPT, metadata,
                node_cache, node_id="final",
            )
            self._trace_level(1, 1, t_level)
            levels = 1
        return {
            "final_summary": summary,
            "num_chunk_summaries": len(summaries),
            "hierarchical": hierarchical,
            "levels": levels,
            "aggregation_time": time.time() - t0,
            # degrade-and-continue accounting: reduce nodes that fell back
            # to error markers this call, and whether the FINAL summary is
            # itself one (this class owns the marker format — consumers
            # branch on these instead of string-matching)
            "reduce_errors": self._wave_errors,
            "final_error": summary.startswith("[Error aggregating summaries:"),
        }

    # ------------------------------------------------------------ internals

    def _build_request(
        self,
        summaries: list[str],
        template: str,
        metadata: dict[str, Any] | None,
        request_id: int = 0,
    ) -> GenerationRequest:
        """Format one reduce prompt (reference _single_aggregation,
        result_aggregator.py:111-286, minus its OpenAI hardwiring)."""
        blocks = [
            f"SUMMARY {i + 1}:\n{'=' * 20}\n{s}" for i, s in enumerate(summaries)
        ]
        meta_str = ", ".join(f"{k}: {v}" for k, v in (metadata or {}).items()) or "n/a"
        prompt = safe_format(
            template,
            summaries="\n\n".join(blocks),
            metadata=meta_str,
            num_summaries=len(summaries),
        )
        return GenerationRequest(
            prompt=prompt,
            request_id=request_id,
            max_new_tokens=self.executor.config.max_tokens,
            temperature=self.config.temperature,  # reference hardcodes 0.2
            seed=self.executor.config.seed,
            # prefix-cache hint: the reduce preamble repeats per tree node;
            # summaries/metadata/count all vary per request, so the shared
            # prefix ends at whichever placeholder the template puts first
            cache_prefix=shared_prefix_chars(
                template, "summaries", "metadata", "num_summaries"),
        )

    def _reduce_wave(
        self,
        jobs: list[tuple[str, list[str], str, dict[str, Any] | None]],
        node_cache: Any | None = None,
    ) -> list[str]:
        """Run one level's reduce calls as a SINGLE engine wave — the
        reference fans batches out concurrently (asyncio.create_task +
        gather, result_aggregator.py:326-342); here they fill the batch
        slots together instead of serializing one round trip per batch.

        ``jobs`` entries are ``(node_id, summaries, template, metadata)``.
        With a ``node_cache``, journaled nodes are answered from the cache
        and only the misses form the engine wave; freshly computed nodes
        are recorded as they land (error-marker results are NOT recorded —
        a resumed run must retry them, not rehydrate the failure)."""
        out: list[str | None] = [None] * len(jobs)
        misses: list[int] = []
        # content-derived identities (positional display kept as the
        # prefix), hashed ONCE per job and reused by the record below:
        # position-keyed identities go stale on any leaf insertion,
        # content-derived ones keep unchanged sibling subtrees addressable
        idents: list[str | None] = [None] * len(jobs)
        for i, (node_id, summaries, template, metadata) in enumerate(jobs):
            if node_cache is not None:
                idents[i] = content_node_id(node_id, summaries, template,
                                            metadata)
                text = node_cache.lookup(idents[i], summaries, template,
                                         metadata)
                if text is not None:
                    out[i] = text
                    continue
            misses.append(i)
        requests = [
            self._build_request(jobs[i][1], jobs[i][2], jobs[i][3],
                                request_id=k)
            for k, i in enumerate(misses)
        ]
        results = self.executor.run_requests(requests) if requests else []
        for i, res in zip(misses, results):
            node_id, summaries, template, metadata = jobs[i]
            reason = degraded_reason(res)
            # degrade to an error string, never raise
            # (result_aggregator.py:256-259,284-286)
            if reason is None:
                out[i] = res.text
                if node_cache is not None:
                    node_cache.record(idents[i], summaries, template,
                                      metadata, res.text)
            else:
                out[i] = f"[Error aggregating summaries: {reason}]"
                self._wave_errors += 1
        return out  # type: ignore[return-value]

    def _reduce_once(
        self,
        summaries: list[str],
        template: str,
        metadata: dict[str, Any] | None,
        node_cache: Any | None = None,
        node_id: str = "final",
    ) -> str:
        return self._reduce_wave([(node_id, summaries, template, metadata)],
                                 node_cache)[0]

    def _hierarchical(
        self,
        summaries: list[str],
        prompt_template: str | None,
        metadata: dict[str, Any] | None,
        node_cache: Any | None = None,
    ) -> tuple[str, int]:
        """Recursive batch tree (reference _hierarchical_aggregation,
        result_aggregator.py:288-355, generalized past two levels)."""
        level = 0
        current = summaries
        while (
            len(current) > 1
            and self._total_tokens(current) > self.config.max_tokens_per_batch
            and level < self.config.max_levels
        ):
            level += 1
            batch_size = self._calculate_batch_size(current)
            batches = [current[i : i + batch_size] for i in range(0, len(current), batch_size)]
            logger.info(
                "reduce level %d: %d summaries in %d batches of <=%d",
                level, len(current), len(batches), batch_size,
            )
            jobs = []
            n = len(batches)
            for i, batch in enumerate(batches):
                # Positional metadata per batch (result_aggregator.py:326-339)
                lo = 100.0 * i / n
                hi = 100.0 * (i + 1) / n
                batch_meta = dict(metadata or {})
                batch_meta.update(
                    {"batch": f"{i + 1}/{n}", "position": f"{lo:.0f}%-{hi:.0f}% of the transcript"}
                )
                jobs.append(
                    (f"L{level}.B{i}", batch,
                     prompt_template or DEFAULT_BATCH_REDUCE_PROMPT, batch_meta)
                )
            t_level = time.time()
            current = self._reduce_wave(jobs, node_cache)
            self._trace_level(level, len(batches), t_level)
        if len(current) == 1:
            return current[0], level
        t_final = time.time()
        final = self._reduce_once(
            current, prompt_template or DEFAULT_FINAL_REDUCE_PROMPT, metadata,
            node_cache, node_id=f"L{level + 1}.final",
        )
        self._trace_level(level + 1, 1, t_final)
        return final, level + 1

    def _stable_arity(self) -> int:
        return max(2, self.config.max_summaries_per_batch)

    def _hierarchical_stable(
        self,
        summaries: list[str],
        prompt_template: str | None,
        metadata: dict[str, Any] | None,
        node_cache: Any | None = None,
    ) -> tuple[str, int]:
        """Append-stable batch tree (``ReduceConfig.stable_tree``; the
        rolling-reduce substrate of lmrs_tpu/live/).

        Differences from ``_hierarchical``, each one an append-stability
        requirement:

        * **fixed arity** (``max_summaries_per_batch``), leaf-aligned:
          batch ``i`` of a level always holds children ``[i*a, (i+1)*a)``
          — appending leaves adds/extends only the LAST batch per level,
          never re-partitions the ones before it;
        * **no positional batch metadata**: "batch i/n" / "position
          lo%-hi%" substitutions bake the leaf count into every prompt, so
          one append would change every node's text.  Batch nodes carry no
          metadata; the transcript-global metadata (duration, speakers,
          num_chunks) goes to the FINAL node only — the root recomputes on
          every append anyway;
        * levels derive from the leaf count alone, so a resumed/appended
          run recomputes exactly the dirty root path and answers every
          sibling subtree from the node cache.
        """
        arity = self._stable_arity()
        level = 0
        current = summaries
        while len(current) > arity and level < self.config.max_levels:
            level += 1
            batches = [current[i: i + arity]
                       for i in range(0, len(current), arity)]
            logger.info(
                "reduce level %d (stable): %d summaries in %d batches of <=%d",
                level, len(current), len(batches), arity,
            )
            jobs = [
                (f"L{level}.B{i}", batch,
                 prompt_template or DEFAULT_BATCH_REDUCE_PROMPT, None)
                for i, batch in enumerate(batches)
            ]
            t_level = time.time()
            current = self._reduce_wave(jobs, node_cache)
            self._trace_level(level, len(batches), t_level)
        t_final = time.time()
        final = self._reduce_once(
            current, prompt_template or DEFAULT_FINAL_REDUCE_PROMPT, metadata,
            node_cache, node_id=f"L{level + 1}.final",
        )
        self._trace_level(level + 1, 1, t_final)
        return final, level + 1

    @staticmethod
    def _trace_level(level: int, batches: int, t0: float) -> None:
        """One ``reduce_level`` span per tree level on the pipeline track
        (obs/trace.py) — the per-level attribution the stage-total reduce
        timing cannot give."""
        tr = get_tracer()
        if tr:
            tr.complete("reduce_level", t0, time.time(), pid=PID_PIPELINE,
                        args={"level": level, "batches": batches})

    def _calculate_batch_size(self, summaries: list[str]) -> int:
        """Token-budgeted batch size, capped (result_aggregator.py:357-380)."""
        total = self._total_tokens(summaries)
        avg = max(total // max(len(summaries), 1), 1)
        budget = self.config.max_tokens_per_batch - self.config.reserve_tokens
        return max(1, min(self.config.max_summaries_per_batch, budget // avg))

    def _total_tokens(self, summaries: list[str]) -> int:
        return sum(self.tokenizer.count(s) for s in summaries)


class SimpleAggregator:
    """Minimal single-pass reduce (reference simple_aggregator.py:26-189).

    Kept for debugging/reliability comparisons; always single-pass, strict
    no-preamble system prompt."""

    SYSTEM = (
        "You combine partial summaries into one final summary. Respond with "
        "the summary content only: no greeting, no introduction, no closing."
    )

    def __init__(self, executor: MapExecutor):
        self.executor = executor

    def aggregate(self, summaries: list[str], metadata: dict | None = None) -> str:
        blocks = "\n\n".join(
            f"SUMMARY {i + 1}:\n{s}" for i, s in enumerate(summaries)
        )
        prompt = safe_format(
            DEFAULT_REDUCE_PROMPT,
            summaries=blocks,
            metadata=", ".join(f"{k}: {v}" for k, v in (metadata or {}).items()) or "n/a",
            num_summaries=len(summaries),
        )
        req = GenerationRequest(
            prompt=prompt, system_prompt=self.SYSTEM, temperature=0.2,
            max_new_tokens=self.executor.config.max_tokens,
        )
        res = self.executor.run_requests([req])[0]
        return (res.text if degraded_reason(res) is None
                else f"[Error aggregating summaries: {degraded_reason(res)}]")


# backwards-compat alias (tests import it from here)
_safe_format = safe_format


if __name__ == "__main__":  # stage demo (pattern: result_aggregator.py:527-583)
    from lmrs_tpu.engine.mock import MockEngine

    chunks = [
        Chunk(segments=[], text="", token_count=0, start_time=i * 600.0,
              end_time=(i + 1) * 600.0, speakers=["SPEAKER_00"], chunk_index=i,
              total_chunks=12,
              summary=f"Summary {i}: the team reviewed milestone {i} of the "
                      f"inference roadmap and assigned follow-ups.")
        for i in range(12)
    ]
    executor = MapExecutor(MockEngine())
    # small budgets so 12 summaries genuinely form a 2-level tree
    # (reserve left at default would make the batch budget negative)
    agg = ResultAggregator(
        executor, ReduceConfig(max_tokens_per_batch=250, reserve_tokens=50))
    result = agg.aggregate(chunks)
    print(f"hierarchical: {result['hierarchical']} (levels={result['levels']})")
    print(result["final_summary"][:400])
