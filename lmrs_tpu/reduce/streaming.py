"""Streamed map→reduce: the reduce tree rides the map stage's batch slots.

The reference (and the plain pipeline path here) puts a hard barrier
between map and reduce: every chunk summary must exist before the first
reduce call starts (main.py:169-236).  With a continuous-batching engine
that barrier wastes capacity twice — decode slots drain idle at the map
tail, then refill from scratch for the reduce waves.  This module feeds
level-1 reduce batches into the SAME engine stream the map requests run
in (engine/scheduler.py ``run(on_result=...)``), as soon as each batch's
member summaries complete.

Semantics vs ``ResultAggregator.aggregate``:

* the single-pass-vs-hierarchical decision is EXACT: hierarchical only
  activates once the summaries completed so far already exceed
  ``max_tokens_per_batch`` (the same total-tokens test,
  result_aggregator.py:95-100 — if the whole map finishes under budget it
  was never triggered and a single-pass reduce runs);
* level-1 batch size is estimated when hierarchical triggers (from the
  summaries completed by then) instead of from the final list — batches
  are still contiguous ordered slices, token-split at submit time so no
  batch exceeds the budget;
* levels ≥ 2 have all inputs in hand and follow the non-streaming logic
  exactly (they still ride the same stream, overlapping the map tail).

Engines without a mid-run hook (mock, static, replicated) run the same
code path via post-hoc delivery (engine/api.py:drain_with_callback) —
identical results, no overlap.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Sequence

from lmrs_tpu.data.chunker import Chunk
from lmrs_tpu.data.preprocessor import format_timestamp
from lmrs_tpu.engine.api import degraded_reason
from lmrs_tpu.prompts import (
    DEFAULT_BATCH_REDUCE_PROMPT,
    DEFAULT_FINAL_REDUCE_PROMPT,
    DEFAULT_REDUCE_PROMPT,
)

logger = logging.getLogger("lmrs.reduce.stream")


class StreamingMapReduce:
    """One-stream orchestration of the map stage + reduce tree."""

    def __init__(self, executor, aggregator):
        # the aggregator supplies prompt formatting, batch-size math, the
        # tokenizer, and ReduceConfig — one source of truth with the
        # barrier path (reduce/aggregator.py)
        self.executor = executor
        self.agg = aggregator

    # ------------------------------------------------------------------ run

    def run(
        self,
        chunks: Sequence[Chunk],
        map_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
        reduce_template: str | None = None,
        metadata: dict[str, Any] | None = None,
        on_map_complete=None,
    ) -> dict[str, Any]:
        """Map every summary-less chunk and reduce; returns the aggregator's
        result dict plus ``map_seconds``/``reduce_tail_seconds``.

        ``on_map_complete(chunks)`` fires inside the stream the moment the
        last map summary lands — the pipeline's --save-chunks dump hooks in
        here so an interrupt during the reduce tail still leaves a
        resumable artifact (same checkpoint the barrier path writes
        between stages)."""
        t0 = time.time()
        ordered = sorted(chunks, key=lambda c: c.chunk_index)
        cfg = self.agg.config

        todo = [c for c in ordered if c.summary is None]
        if not todo:
            # nothing to map (full resume): the barrier path is exact here
            out = self.agg.aggregate(ordered, reduce_template, metadata)
            out["map_seconds"] = 0.0
            out["reduce_tail_seconds"] = out["aggregation_time"]
            return out

        def tagged(c: Chunk) -> str:
            return (f"[Time: {format_timestamp(c.start_time)} - "
                    f"{format_timestamp(c.end_time)}]\n{c.summary or ''}")

        # ---- state shared by the callbacks (single-threaded)
        st = {
            "pending_map": len(todo),
            # time-tagged summary tokens so far (resumed chunks count)
            "done_tokens": sum(self.agg.tokenizer.count(tagged(c))
                               for c in ordered if c.summary is not None),
            "done_count": len(ordered) - len(todo),
            "mode": "undecided",      # undecided | hierarchical | single
            "groups": [],              # level-1 groups (built on trigger)
            "pending_level": {},       # level -> outstanding request count
            "outputs": {},             # level -> list[(ordinal, text)]
            "all_l1_submitted": False,
            "submitted_groups": set(),
            "group_of": {},            # chunk_index -> group index
            "final": None,
            "levels": 0,
            "t_map_done": None,
            "next_rid": len(todo),
            "first_reduce_t": None,
        }
        chunk_by_rid: dict[int, Chunk] = {}
        reduce_meta: dict[int, tuple] = {}  # rid -> ("batch", level, ordinal) | ("final", level)
        budget = cfg.max_tokens_per_batch

        map_requests = []
        for i, c in enumerate(todo):
            map_requests.append(self.executor.build_map_request(
                c, map_template, summary_type, system_prompt, request_id=i))
            chunk_by_rid[i] = c

        # ---- reduce submission helpers

        def submit_reduce(submit, summaries, template, meta, kind) -> int:
            rid = st["next_rid"]
            st["next_rid"] += 1
            reduce_meta[rid] = kind
            if st["first_reduce_t"] is None:
                st["first_reduce_t"] = time.time()
            req = self.agg._build_request(summaries, template, meta, request_id=rid)
            submit([req])
            return rid

        def submit_group(submit, group_idx: int) -> None:
            if group_idx in st["submitted_groups"]:
                return
            st["submitted_groups"].add(group_idx)
            group = st["groups"][group_idx]
            n_groups = len(st["groups"])
            summaries = [tagged(c) for c in group]
            # token-split: contiguous sub-batches, each within the working
            # budget (same headroom the batch-size math reserves)
            cap = max(budget - cfg.reserve_tokens, 1)
            subs: list[list[str]] = [[]]
            acc = 0
            for s in summaries:
                n = self.agg.tokenizer.count(s)
                if subs[-1] and acc + n > cap:
                    subs.append([])
                    acc = 0
                subs[-1].append(s)
                acc += n
            lo = 100.0 * group_idx / n_groups
            hi = 100.0 * (group_idx + 1) / n_groups
            meta = dict(metadata or {})
            meta.update({"batch": f"{group_idx + 1}/{n_groups}",
                         "position": f"{lo:.0f}%-{hi:.0f}% of the transcript"})
            for si, sub in enumerate(subs):
                st["pending_level"][1] = st["pending_level"].get(1, 0) + 1
                submit_reduce(submit, sub,
                              reduce_template or DEFAULT_BATCH_REDUCE_PROMPT,
                              meta, ("batch", 1, (group_idx, si)))

        def maybe_trigger_hierarchical(submit) -> None:
            # cfg.hierarchical=False pins the barrier path's single-pass
            # choice (aggregator.py: hierarchical AND over-budget)
            if (not cfg.hierarchical or st["mode"] != "undecided"
                    or st["done_tokens"] <= budget):
                return
            st["mode"] = "hierarchical"
            avg = max(st["done_tokens"] // max(st["done_count"], 1), 1)
            bs = max(1, min(cfg.max_summaries_per_batch,
                            (budget - cfg.reserve_tokens) // avg))
            st["groups"] = [ordered[i: i + bs]
                            for i in range(0, len(ordered), bs)]
            for gi, group in enumerate(st["groups"]):
                for c in group:
                    st["group_of"][c.chunk_index] = gi
            logger.info("hierarchical reduce triggered mid-map: %d groups of "
                        "<=%d (est. avg %d tok)", len(st["groups"]), bs, avg)
            for gi, group in enumerate(st["groups"]):
                if all(c.summary is not None for c in group):
                    submit_group(submit, gi)

        def advance_level(submit, level: int) -> None:
            outs = [t for _, t in sorted(st["outputs"].get(level, []))]
            st["levels"] = max(st["levels"], level)
            if len(outs) == 1:
                st["final"] = outs[0]
                return
            total = self.agg._total_tokens(outs)
            # same bound as aggregator._hierarchical's `level < max_levels`
            if total <= budget or level + 1 > cfg.max_levels:
                st["pending_level"][level + 1] = 1
                submit_reduce(submit, outs,
                              reduce_template or DEFAULT_FINAL_REDUCE_PROMPT,
                              metadata, ("final", level + 1))
                return
            bs = self.agg._calculate_batch_size(outs)
            batches = [outs[i: i + bs] for i in range(0, len(outs), bs)]
            logger.info("reduce level %d: %d summaries in %d batches",
                        level + 1, len(outs), len(batches))
            for bi, batch in enumerate(batches):
                # same positional metadata the barrier path attaches per
                # batch at every level (aggregator.py:181-188)
                lo = 100.0 * bi / len(batches)
                hi = 100.0 * (bi + 1) / len(batches)
                meta = dict(metadata or {})
                meta.update({"batch": f"{bi + 1}/{len(batches)}",
                             "position": f"{lo:.0f}%-{hi:.0f}% of the transcript"})
                st["pending_level"][level + 1] = st["pending_level"].get(level + 1, 0) + 1
                submit_reduce(submit, batch,
                              reduce_template or DEFAULT_BATCH_REDUCE_PROMPT,
                              meta, ("batch", level + 1, (bi, 0)))

        # ---- the stream callback

        def on_final(res, submit) -> None:
            rid = res.request_id
            if rid in chunk_by_rid:  # ------------------------- map result
                c = chunk_by_rid[rid]
                reason = degraded_reason(res)  # shed/deadline terminals
                if reason is not None:           # carry no error field
                    c.summary = f"[Error processing chunk: {reason}]"
                    c.error = reason
                else:
                    c.summary = res.text
                c.tokens_used = res.total_tokens
                c.device_seconds = res.device_seconds
                st["pending_map"] -= 1
                st["done_count"] += 1
                st["done_tokens"] += self.agg.tokenizer.count(tagged(c))
                maybe_trigger_hierarchical(submit)
                if st["mode"] == "hierarchical":
                    gi = st["group_of"][c.chunk_index]
                    if all(x.summary is not None for x in st["groups"][gi]):
                        submit_group(submit, gi)
                if st["pending_map"] == 0:
                    st["t_map_done"] = time.time()
                    if on_map_complete is not None:
                        try:
                            on_map_complete(ordered)
                        except Exception:
                            logger.exception("on_map_complete hook failed")
                    if st["mode"] == "undecided":
                        # never exceeded the budget: exact single-pass
                        st["mode"] = "single"
                        st["pending_level"][1] = 1
                        st["levels"] = 1
                        submit_reduce(submit, [tagged(c) for c in ordered],
                                      reduce_template or DEFAULT_REDUCE_PROMPT,
                                      metadata, ("final", 1))
                    else:
                        st["all_l1_submitted"] = True
                        if st["pending_level"].get(1, 0) == 0:
                            advance_level(submit, 1)
                return
            # ------------------------------------------------ reduce result
            kind = reduce_meta.pop(rid)
            text = (res.text if degraded_reason(res) is None
                    else f"[Error aggregating summaries: "
                         f"{degraded_reason(res)}]")
            if kind[0] == "final":
                st["final"] = text
                st["levels"] = max(st["levels"], kind[1])
                st["pending_level"][kind[1]] = 0
                return
            _, level, ordinal = kind
            st["outputs"].setdefault(level, []).append((ordinal, text))
            st["pending_level"][level] -= 1
            if st["pending_level"][level] == 0 and (
                    level > 1 or st["all_l1_submitted"]):
                advance_level(submit, level)

        self.executor.run_requests_streaming(map_requests, on_final)

        t_end = time.time()
        from lmrs_tpu.obs import PID_PIPELINE, get_tracer

        tr = get_tracer()
        if tr:
            # streaming has no barrier, so the spans OVERLAP by design:
            # map_stage ends at the last map summary, reduce_tail is the
            # stream beyond it — the overlap window is visible in Perfetto
            tr.complete("map_stage", t0, st["t_map_done"] or t_end,
                        pid=PID_PIPELINE,
                        args={"chunks": len(todo), "streaming": True})
            if st["first_reduce_t"] is not None:
                tr.complete("reduce_stream", st["first_reduce_t"], t_end,
                            pid=PID_PIPELINE, tid=1,
                            args={"levels": max(st["levels"], 1)})
        if st["final"] is None:  # defensive: stream ended without a final
            logger.error("stream ended without a final summary; falling back "
                         "to barrier reduce")
            out = self.agg.aggregate(ordered, reduce_template, metadata)
            st["final"] = out["final_summary"]
            st["levels"] = out["levels"]
            st["mode"] = "hierarchical" if out["hierarchical"] else "single"
        t_map = (st["t_map_done"] or t_end) - t0
        return {
            "final_summary": st["final"],
            "num_chunk_summaries": len(ordered),
            "hierarchical": st["mode"] == "hierarchical",
            "levels": max(st["levels"], 1),
            "aggregation_time": t_end - (st["first_reduce_t"] or t_end),
            "map_seconds": t_map,
            "reduce_tail_seconds": t_end - (st["t_map_done"] or t_end),
        }
