"""Live sessions: incremental summarization of growing transcripts.

The first streaming workload tier (ROADMAP item 5): a client opens a
session, appends transcript segments as they arrive (a meeting, a
stream), and refreshes the summary incrementally — only the dirty tail
chunks and the dirty reduce root path recompute, everything else answers
from content-addressed caches journaled through the PR 7 WAL.
"""

from lmrs_tpu.live.session import (LiveSession, SessionManager,
                                   rebuild_live_state)

__all__ = ["LiveSession", "SessionManager", "rebuild_live_state"]
