"""Live session manager: rolling map-reduce over a growing transcript.

A *session* is the live-tier unit of work, the way a *job* (lmrs_tpu/
jobs/) is the batch tier's: a client opens one, appends transcript
segments over time, and requests (or auto-triggers via
``LiveConfig.refresh_tokens``) summary refreshes that recompute ONLY
what changed since the last one.  Three caches make a refresh
incremental, every one keyed on content so appends can never poison it:

* **chunk boundaries** — the incremental chunker
  (``TranscriptChunker.incremental``) pins already-sealed chunk
  identities; appends extend only the open tail chunk or seal new ones;
* **map summaries** — keyed by ``jobs.journal.chunk_key`` (index, start,
  end): a sealed chunk's summary is reused verbatim, the extended tail's
  key changes and recomputes;
* **reduce nodes** — ``ResultAggregator`` in ``stable_tree`` mode over
  the journal's content-addressed ``node_key``s: appending leaves
  recomputes the last batch per level plus the root, sibling subtrees
  answer from cache.

Everything journals through the PR 7 WAL (``jobs.journal.Journal``) as
it completes — segment batches, chunk summaries, reduce nodes, the
summary snapshot — so a SIGKILL at any instant resumes the session with
the rolling tree intact: ``recover()`` replays the journal, re-chunks
the journaled segments (deterministic), rehydrates both caches, and the
next refresh is token-identical to an uninterrupted run.

Determinism contract (chaos-gated): live preprocessing is a STATELESS
per-segment map (same-speaker merging is disabled — merging is stateful
across append boundaries and would move sealed chunk boundaries), so the
chunk stream, the map prompts, and the stable tree shape depend only on
the concatenated segment stream — never on how appends were batched.  A
refresh after N appends is token-identical to a cold session fed the
same segments at once.

Deadline classes: an ``interactive`` refresh stamps
``LiveConfig.interactive_deadline_s`` onto its map/reduce requests and
rides the PR 5 shed/expiry lifecycle (scheduler admission sheds it ahead
of unbounded work when the budget can't cover TTFT); ``bulk`` backfill
runs unbounded.  Either way refresh requests carry the executor's
``cache_prefix`` hints, so the shared map/reduce preambles hit the radix
prefix cache and (through the router's preamble key) keep a session's
traffic on one warm host.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path

from lmrs_tpu.config import LiveConfig, PipelineConfig
from lmrs_tpu.data.chunker import Chunk, IncrementalChunking
from lmrs_tpu.data.preprocessor import preprocess_transcript
from lmrs_tpu.engine.api import degraded_reason
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.jobs import journal as jl
from lmrs_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS_S,
    RATIO_BUCKETS,
    MetricsRegistry,
    PID_PIPELINE,
    get_tracer,
)
from lmrs_tpu.pipeline import build_chunker
from lmrs_tpu.prompts import (
    resolve_map_prompt,
    resolve_reduce_prompt,
    resolve_system_prompt,
)
from lmrs_tpu.reduce.aggregator import ResultAggregator
from lmrs_tpu.utils.timing import format_duration

logger = logging.getLogger("lmrs.live")

# journal record types (jobs.journal's REC_CHUNK / REC_NODE are reused
# verbatim — same idempotent replay keys; unknown types stay ignored by
# the batch-job reader, forward compatibility both ways)
REC_SESSION = "session_header"
REC_SEGMENTS = "segments_appended"
REC_SUMMARY = "summary_done"

# params a session may carry (same fail-loudly contract as jobs)
_ALLOWED_PARAMS = ("prompt_template", "system_prompt", "aggregator_prompt",
                   "summary_type", "max_tokens_per_chunk", "class")

_CLASSES = ("interactive", "bulk")


def _text_sha(text: str) -> str:
    import hashlib

    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()[:12]


def _clean_segments(segments) -> list[dict]:
    """Validate + coerce one appended batch into the canonical journaled
    form.  Raises ValueError on anything malformed — BEFORE the batch
    reaches the WAL, so a bad append can 400 but never brick replay."""
    import math

    if not isinstance(segments, list) or not segments:
        raise ValueError("segments must be a non-empty list of "
                         "{start, end, text[, speaker]} objects")
    out = []
    for i, s in enumerate(segments):
        if not isinstance(s, dict) or not isinstance(s.get("text"), str):
            raise ValueError(f"segment {i}: want an object with string "
                             "'text' plus numeric 'start'/'end'")
        try:
            start = float(s["start"])
            end = float(s["end"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(f"segment {i}: 'start'/'end' must be "
                             "numbers") from None
        if not (math.isfinite(start) and math.isfinite(end)) or end < start:
            raise ValueError(f"segment {i}: want finite start <= end "
                             f"(got {start!r}..{end!r})")
        out.append({"start": start, "end": end, "text": s["text"],
                    "speaker": str(s.get("speaker", "UNKNOWN"))})
    return out


def rebuild_live_state(records: list[dict]) -> dict:
    """Fold replayed records into canonical session state:

    ``{"header": rec|None, "segments": {seq: [raw segments]},
    "chunks": {chunk_key: rec}, "nodes": {node_key: text},
    "summary": rec|None}``

    Idempotent like ``jobs.journal.rebuild_state``: duplicates overwrite
    their own key with identical content, so a journal replayed any
    number of times yields byte-identical state."""
    state: dict = {"header": None, "segments": {}, "chunks": {},
                   "nodes": {}, "summary": None}
    for rec in records:
        kind = rec.get("type")
        if kind == REC_SESSION:
            state["header"] = rec
        elif kind == REC_SEGMENTS:
            seq = rec.get("seq")
            if isinstance(seq, int) and seq >= 0:
                state["segments"][seq] = rec.get("segments", [])
        elif kind == jl.REC_CHUNK:
            key = jl.chunk_key(rec.get("chunk_index", -1),
                               rec.get("start_time", 0.0),
                               rec.get("end_time", 0.0))
            state["chunks"][key] = rec
        elif kind == jl.REC_NODE:
            if rec.get("key"):
                state["nodes"][rec["key"]] = rec.get("text", "")
        elif kind == REC_SUMMARY:
            state["summary"] = rec
        # unknown types: ignored (forward compatibility)
    return state


@dataclass
class LiveSession:
    """In-memory record of one live session (the journal is the truth)."""

    session_id: str
    params: dict
    fingerprint: str
    wal_path: Path
    created_t: float = field(default_factory=time.time)
    recovered: bool = False
    trace_id: str | None = None
    # cost-attribution tenant (docs/OBSERVABILITY.md § Request-cost
    # ledger): the create's X-LMRS-Tenant, defaulting to the session's
    # own id — persisted in the session header like the trace id and
    # stamped on every refresh request, so GET /v1/usage rolls up per
    # session for free
    tenant: str | None = None
    # ledger usage rolled up from this process-life's refresh waves
    usage: dict = field(default_factory=dict)
    journal: jl.Journal | None = None
    closed: bool = False
    # transcript + chunking state (all appended-so-far; serialized by the
    # per-session lock below)
    inc: IncrementalChunking | None = None
    append_seq: int = 0          # segment batches journaled
    n_raw_segments: int = 0      # segments as appended (pre-preprocess)
    n_segments: int = 0          # processed segments fed to the chunker
    speakers: dict[str, None] = field(default_factory=dict)
    end_time: float = 0.0
    # content-addressed caches rehydrated from the journal
    chunk_cache: dict[str, dict] = field(default_factory=dict)
    node_cache: dict[str, str] = field(default_factory=dict)
    # current summary snapshot (None until the first refresh lands)
    summary: dict | None = None
    stale_tokens: int = 0        # appended-but-unsummarized token estimate
    # control plane.  ``lock`` serializes appends/refreshes; ``ctl``
    # is a SHORT lock over the in-flight executor + rid set, so close()
    # can snapshot them without waiting out (or racing) a refresh —
    # iterating _live_rids while the map stream discards from it would
    # raise, and waiting on ``lock`` would defeat the cancel
    lock: threading.RLock = field(default_factory=threading.RLock)
    ctl: threading.Lock = field(default_factory=threading.Lock)
    cancel_ev: threading.Event = field(default_factory=threading.Event)
    _executor: MapExecutor | None = None  # guarded-by: ctl
    _live_rids: set = field(default_factory=set)  # guarded-by: ctl

    @property
    def stale_batches(self) -> int:
        covered = (self.summary or {}).get("seq", 0)
        return self.append_seq - covered


class _SessionNodeCache:
    """``ResultAggregator`` node_cache over the session's journaled
    reduce nodes: lookups answer from the replayed ``node_key`` map,
    fresh nodes journal as they land (error markers never recorded —
    the next refresh retries them)."""

    def __init__(self, manager: "SessionManager", session: LiveSession):
        self._manager = manager
        self._session = session
        self.reused = 0
        self.computed = 0

    def lookup(self, node_id: str, summaries: list[str],
               template: str | None, metadata: dict | None) -> str | None:
        text = self._session.node_cache.get(
            jl.node_key(summaries, template, metadata))
        if text is not None:
            self.reused += 1
        return text

    def record(self, node_id: str, summaries: list[str],
               template: str | None, metadata: dict | None,
               text: str) -> None:
        key = jl.node_key(summaries, template, metadata)
        self._session.node_cache[key] = text
        self.computed += 1
        self._manager._append(self._session, {
            "type": jl.REC_NODE, "node_id": node_id, "key": key,
            "text": text})


class SessionManager:
    """Owns the sessions directory, the journals, and refresh execution
    over ``engine`` (inside lmrs-serve the engine is the micro-batcher
    facade, so refresh waves pool with interactive HTTP traffic; raw
    engines are serialized by the manager's engine lock — raw backends
    do not accept concurrent ``generate_batch`` calls)."""

    def __init__(self, engine, live_dir: str | Path,
                 config: PipelineConfig | None = None,
                 live_config: LiveConfig | None = None):
        self.engine = engine
        self.config = config or PipelineConfig()
        self.live_cfg = live_config or self.config.live
        self.dir = Path(live_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._sessions: dict[str, LiveSession] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        # raw engines accept one generate_batch at a time; the batcher
        # facade pools anyway, so serializing refresh waves here is safe
        # for every backend and required for the raw ones
        self._engine_lock = threading.Lock()
        self._stopped = False
        # ---- lmrs_live_* metrics (merged into the server's /metrics)
        self.registry = MetricsRegistry()
        c = self.registry.counter
        self._c_opened = c("lmrs_live_sessions_opened_total",
                           "sessions created by POST /v1/sessions or "
                           "create()")
        self._c_recovered = c("lmrs_live_sessions_recovered_total",
                              "interrupted session journals rehydrated by "
                              "startup recovery")
        self._c_refreshes = c("lmrs_live_refreshes_total",
                              "summary refreshes run (requested or "
                              "auto-triggered)")
        self._c_segments = c("lmrs_live_segments_appended_total",
                             "transcript segments appended across sessions")
        self._c_nodes_reused = c("lmrs_live_reduce_nodes_reused_total",
                                 "reduce-tree nodes answered from the "
                                 "session's content-addressed cache "
                                 "instead of recomputed")
        self._c_chunks_reused = c("lmrs_live_chunk_summaries_reused_total",
                                  "map summaries reused from the session "
                                  "cache instead of recomputed")
        self._g_active = self.registry.gauge(
            "lmrs_live_sessions_active", "sessions currently open")
        self._h_dirty = self.registry.histogram(
            "lmrs_live_dirty_chunk_ratio", RATIO_BUCKETS,
            help="dirty-chunk fraction per refresh (recomputed map chunks "
                 "over total chunks — low is the incremental win)",
            unit="ratio")
        self._h_refresh = self.registry.histogram(
            "lmrs_live_refresh_seconds", DEFAULT_LATENCY_BUCKETS_S,
            help="wall-clock of one summary refresh", unit="seconds")

    # ------------------------------------------------------------- public

    def create(self, params: dict | None = None,
               session_id: str | None = None,
               trace_id: str | None = None,
               tenant: str | None = None) -> LiveSession:
        """Open a session (POST /v1/sessions).  ``session_id`` may be
        client-supplied (stable id across client retries; validated);
        otherwise one is minted.  Re-creating an existing live session
        returns it (idempotent client retry)."""
        params = self._sanitize_params(params)
        sid = self._clean_sid(session_id) or f"sess-{uuid.uuid4().hex[:12]}"
        fp = self._fingerprint(params)
        with self._lock:
            existing = self._sessions.get(sid)
            if existing is not None and not existing.closed:
                return existing
            session = self._register(sid, params, fp)
            if trace_id:
                session.trace_id = trace_id
            else:
                from lmrs_tpu.obs import new_trace_id

                session.trace_id = new_trace_id()
            session.tenant = tenant or f"session:{sid[:24]}"
            self._c_opened.inc()
            self._g_active.set(self._active_count())
        self._append(session, {
            "type": REC_SESSION, "session_id": sid, "fingerprint": fp,
            "params": params, "created_t": session.created_t,
            "trace_id": session.trace_id, "tenant": session.tenant})
        tr = get_tracer()
        if tr:
            tr.instant("session_open", pid=PID_PIPELINE,
                       args={"session": sid, "trace": session.trace_id})
        logger.info("session %s: opened (class default %s)", sid,
                    params.get("class", self.live_cfg.class_default))
        return session

    def get(self, session_id: str) -> LiveSession | None:
        with self._lock:
            return self._sessions.get(session_id)

    def sessions(self) -> list[LiveSession]:
        with self._lock:
            return sorted((s for s in self._sessions.values() if not s.closed),
                          key=lambda s: s.created_t)

    def append(self, session_id: str, segments: list[dict],
               refresh: bool | None = None,
               klass: str | None = None) -> dict:
        """Append a batch of raw segments (POST /v1/sessions/<id>/
        segments).  Journals the RAW batch first (the WAL is the only
        copy of the transcript), then extends the incremental chunker
        with the stateless-preprocessed stream.  A refresh runs inline
        when asked for — or auto-triggers once the appended-but-
        unsummarized token estimate crosses ``LiveConfig.refresh_tokens``.
        Returns the session doc (plus the refresh doc when one ran)."""
        session = self._require(session_id)
        # validate + coerce BEFORE anything journals: one malformed batch
        # persisted to the WAL would poison every future replay of the
        # session (recovery degrades per batch, but never by design)
        segments = _clean_segments(segments)
        with session.lock:
            if session.closed:
                raise KeyError(session_id)
            session.append_seq += 1
            session.n_raw_segments += len(segments)
            self._append(session, {
                "type": REC_SEGMENTS, "seq": session.append_seq,
                "segments": segments})
            self._ingest(session, segments)
            self._c_segments.inc(len(segments))
            tr = get_tracer()
            if tr:
                tr.instant("session_append", pid=PID_PIPELINE,
                           args={"session": session.session_id,
                                 "segments": len(segments),
                                 "seq": session.append_seq,
                                 "trace": session.trace_id})
            doc = self.status_doc(session)
            auto = (self.live_cfg.refresh_tokens > 0
                    and session.stale_tokens >= self.live_cfg.refresh_tokens)
            if refresh or (auto and refresh is not False):
                doc["refresh"] = self._refresh_locked(session, klass,
                                                      auto=not refresh)
                doc.update(self.status_doc(session))
        return doc

    def refresh(self, session_id: str, klass: str | None = None) -> dict:
        """Recompute the summary incrementally (POST
        /v1/sessions/<id>/refresh, or GET .../summary?refresh=1)."""
        session = self._require(session_id)
        with session.lock:
            if session.closed:
                raise KeyError(session_id)
            return self._refresh_locked(session, klass)

    def summary_doc(self, session_id: str) -> dict:
        """The GET /v1/sessions/<id>/summary body: current summary text +
        staleness watermark (how far behind the live transcript it is).

        Deliberately LOCK-FREE (GIL-snapshot reads, the repo's reader
        idiom): this endpoint exists so a client can read the stale-but-
        instant snapshot WHILE a refresh runs — taking the session lock
        would block it behind minutes of engine work.  ``session.summary``
        is rebound atomically at refresh end, never mutated in place."""
        session = self._require(session_id)
        snap = session.summary or {}
        return {
            "object": "session.summary",
            "id": session.session_id,
            "summary": snap.get("summary"),
            "watermark": {
                "seq": snap.get("seq", 0),
                "n_segments": snap.get("n_segments", 0),
                "end_time": snap.get("end_time", 0.0),
                "refreshed_t": snap.get("refreshed_t"),
                "num_chunks": snap.get("n_chunks", 0),
            },
            "staleness": {
                "pending_batches": session.stale_batches,
                "pending_tokens": session.stale_tokens,
                "stale": session.stale_batches > 0 or not snap,
            },
        }

    def close(self, session_id: str) -> LiveSession | None:
        """Close + delete a session (DELETE /v1/sessions/<id>): any
        in-flight refresh is cancelled, the journal is removed — a closed
        session is gone, not resumable."""
        with self._lock:
            session = self._sessions.get(session_id)
        if session is None:
            return None
        session.cancel_ev.set()
        with session.ctl:
            ex = session._executor
            rids = list(session._live_rids)
        if ex is not None:
            ex.interrupt()
            for rid in rids:
                ex.cancel(rid)
        with session.lock:  # waits out an in-flight refresh
            session.closed = True
            if session.journal is not None:
                session.journal.close()
            try:
                os.unlink(session.wal_path)
            except OSError:
                pass
        with self._lock:
            self._sessions.pop(session_id, None)
            self._g_active.set(self._active_count())
        logger.info("session %s: closed", session_id)
        return session

    def recover(self) -> int:
        """Scan the sessions directory at startup and rehydrate every
        journal: segments re-chunk deterministically, map summaries and
        reduce nodes answer from their content-addressed records, the
        last summary snapshot serves immediately — no engine work.  A
        journal whose config fingerprint no longer matches keeps its
        TRANSCRIPT (the segments are the part a restart must never lose)
        but drops the stale summaries: the old WAL is set aside and a
        fresh one re-journals header + segments."""
        recovered = 0
        for wal in sorted(self.dir.glob("*.wal")):
            if self._recover_wal(wal):
                recovered += 1
        return recovered

    def recover_one(self, session_id: str) -> LiveSession | None:
        """On-demand single-journal recovery (the cross-host resume path,
        docs/SERVING.md "KV fabric"): a sibling that inherited a drained/
        killed host's traffic finds the session's journal in the SHARED
        live directory and rehydrates just that session when its first
        request arrives — startup-style recovery, at request time.
        Returns the live session, or None when no journal exists here
        (the 404 stands) or replay fails (degrade per session)."""
        try:
            sid = self._clean_sid(session_id)
        except ValueError:  # garbage sid: the caller's 404 stands
            return None
        if sid is None or self._stopped:
            return None
        with self._lock:
            existing = self._sessions.get(sid)
            if existing is not None:
                return None if existing.closed else existing
        wal = self.dir / f"{sid}.wal"
        if not wal.is_file():
            return None
        if self._recover_wal(wal):
            return self.get(sid)
        return None

    def _recover_wal(self, wal: Path) -> bool:
        """Rehydrate one journal file (shared body of recover() and
        recover_one()).  False when the session already exists, the
        journal is headerless, or replay fails — recovery degrades per
        session, never raises."""
        sid = wal.stem
        with self._lock:
            if sid in self._sessions:
                return False
        try:
            records, _meta = jl.replay(wal)
            state = rebuild_live_state(records)
            if state["header"] is None:
                logger.warning("session %s: journal has no header; "
                               "skipped", sid)
                return False
            params = self._sanitize_params(
                state["header"].get("params") or {})
            fp = self._fingerprint(params)
            stale = state["header"].get("fingerprint") != fp
            with self._lock:
                if sid in self._sessions:  # raced a concurrent recover
                    return False
                session = self._register(sid, params, fp)
                session.recovered = True
                session.created_t = state["header"].get(
                    "created_t", session.created_t)
                header_trace = state["header"].get("trace_id")
                if isinstance(header_trace, str) and header_trace:
                    session.trace_id = header_trace
                header_tenant = state["header"].get("tenant")
                session.tenant = (header_tenant
                                  if isinstance(header_tenant, str)
                                  and header_tenant
                                  else f"session:{sid[:24]}")
                self._g_active.set(self._active_count())
            self._rehydrate(session, state, wal, stale=stale)
        except Exception as e:  # noqa: BLE001 - degrade per session
            logger.warning("session %s: recovery failed: %s: %s",
                           sid, type(e).__name__, e)
            with self._lock:
                self._sessions.pop(sid, None)
                self._g_active.set(self._active_count())
            return False
        self._c_recovered.inc()
        tr = get_tracer()
        if tr:
            tr.instant("session_resume", pid=PID_PIPELINE,
                       args={"session": sid,
                             "segments": session.n_segments,
                             "chunk_records": len(state["chunks"]),
                             "node_records": len(state["nodes"]),
                             "trace": session.trace_id})
        logger.info(
            "session %s: recovered (%d segment batch(es), %d chunk "
            "record(s), %d reduce node(s)%s)", sid, session.append_seq,
            len(state["chunks"]), len(state["nodes"]),
            "; STALE fingerprint — summaries dropped" if stale else "")
        return True

    def status_doc(self, session: LiveSession) -> dict:
        """The GET /v1/sessions/<id> response body."""
        chunks = session.inc.chunk_count if session.inc else 0
        doc = {
            "object": "session",
            "id": session.session_id,
            "created_t": session.created_t,
            "recovered": session.recovered,
            "trace_id": session.trace_id,
            "tenant": session.tenant,
            "params": session.params,
            "append_seq": session.append_seq,
            "num_segments": session.n_raw_segments,
            "num_chunks": chunks,
            "end_time": session.end_time,
            "summarized": session.summary is not None,
            "staleness": {
                "pending_batches": session.stale_batches,
                "pending_tokens": session.stale_tokens,
            },
        }
        if session.usage:
            # ledger rollup over THIS process life's refresh engine work
            # (journal/cache-answered nodes cost nothing — the point)
            doc["usage"] = session.usage
        if session.journal is not None:
            doc["journal"] = session.journal.stats()
        return doc

    def stats(self) -> dict:
        with self._lock:
            n = len([s for s in self._sessions.values() if not s.closed])
        return {"sessions": n, "live_dir": str(self.dir)}

    def shutdown(self) -> None:
        self._stopped = True
        with self._lock:
            sessions = list(self._sessions.values())
        for s in sessions:
            s.cancel_ev.set()
            with s.ctl:
                ex = s._executor
            if ex is not None:
                ex.interrupt()
        for s in sessions:
            with s.lock:
                if s.journal is not None:
                    s.journal.close()

    # ---------------------------------------------------------- internals

    def _register(self, sid: str, params: dict,
                  fingerprint: str) -> LiveSession:  # holds-lock: _lock
        session = LiveSession(session_id=sid, params=params,
                              fingerprint=fingerprint,
                              wal_path=self.dir / f"{sid}.wal")
        session.inc = self._build_inc(params)
        # the journal handle exists BEFORE the session is visible: an
        # append racing create()/recover() must never find journal=None
        # and silently skip the WAL (Journal.__init__ is I/O-free)
        session.journal = jl.Journal(session.wal_path)
        self._sessions[sid] = session
        return session

    def _active_count(self) -> int:  # holds-lock: _lock
        return sum(1 for s in self._sessions.values() if not s.closed)

    def _require(self, session_id: str) -> LiveSession:
        session = self.get(session_id)
        if session is None or session.closed:
            raise KeyError(session_id)
        return session

    @staticmethod
    def _clean_sid(raw: str | None) -> str | None:
        if not isinstance(raw, str):
            return None
        raw = raw.strip()
        if raw and len(raw) <= 64 and all(
                ch.isalnum() or ch in "-_." for ch in raw):
            return raw
        if raw:
            raise ValueError(f"invalid session_id {raw!r} (want <=64 chars "
                             "of [A-Za-z0-9._-])")
        return None

    def _sanitize_params(self, params: dict | None) -> dict:
        p = dict(params or {})
        unknown = sorted(set(p) - set(_ALLOWED_PARAMS))
        if unknown:
            raise ValueError(f"unknown session param(s) {unknown}; "
                             f"supported: {sorted(_ALLOWED_PARAMS)}")
        if "max_tokens_per_chunk" in p:
            try:
                p["max_tokens_per_chunk"] = int(p["max_tokens_per_chunk"])
            except (TypeError, ValueError):
                raise ValueError(
                    "max_tokens_per_chunk must be an integer "
                    f"(got {p['max_tokens_per_chunk']!r})") from None
        if "class" in p and p["class"] not in _CLASSES:
            raise ValueError(f"unknown deadline class {p['class']!r}; "
                             f"want one of {_CLASSES}")
        return p

    def _fingerprint(self, params: dict) -> str:
        """The (prompt, model, chunking, tree) surface that determines
        what the journaled summaries MEAN — same gate as jobs: a journal
        written under a different surface must not rehydrate summaries
        into this run (the transcript itself always survives)."""
        e, c, r = self.config.engine, self.config.chunk, self.config.reduce
        return jl.config_fingerprint(
            live=True,  # live trees are stable-arity; never share a batch
                        # job's fingerprint space
            map_prompt=resolve_map_prompt(params.get("prompt_template"),
                                          None),
            system_prompt=resolve_system_prompt(
                params.get("system_prompt"), None) or "",
            reduce_prompt=resolve_reduce_prompt(
                params.get("aggregator_prompt"), None) or "",
            summary_type=params.get("summary_type", "summary"),
            backend=e.backend, model=e.model, temperature=e.temperature,
            max_tokens=e.max_tokens, seed=e.seed,
            max_tokens_per_chunk=params.get("max_tokens_per_chunk",
                                            c.max_tokens_per_chunk),
            overlap_tokens=c.overlap_tokens,
            context_tokens=c.context_tokens,
            arity=max(2, r.max_summaries_per_batch),
            max_levels=r.max_levels)

    def _build_inc(self, params: dict) -> IncrementalChunking:
        # engine=None on purpose (the jobs rule): chunk identity keys must
        # be purely (transcript, config)-deterministic
        chunker = build_chunker(self.config, engine=None,
                                max_tokens_per_chunk=params.get(
                                    "max_tokens_per_chunk"))
        return chunker.incremental()

    def _prepare(self, segments: list[dict]) -> list[dict]:
        """Live preprocessing: a STATELESS per-segment map.  Same-speaker
        merging and interval re-bucketing are disabled — both are
        stateful across the stream, so the result would depend on how
        appends were batched and a merge across an append boundary would
        rewrite a sealed chunk.  Long-segment splitting and text cleaning
        are per-segment and keep their config."""
        return preprocess_transcript(
            segments,
            merge_same_speaker=False,
            time_interval_seconds=None,
            max_segment_duration=self.config.data.max_segment_duration,
            preserve_timestamps=self.config.data.preserve_timestamps,
        )

    def _ingest(self, session: LiveSession,
                raw_segments: list[dict]) -> None:
        """Extend chunking + staleness state with one raw batch (caller
        holds the session lock; used by append and replay)."""
        processed = self._prepare(raw_segments)
        if not processed:
            return
        session.inc.append(processed)
        session.n_segments += len(processed)
        for s in processed:
            session.speakers.setdefault(s.get("speaker", "UNKNOWN"))
            session.end_time = max(session.end_time, s["end"])
        tok = session.inc.chunker.tokenizer
        batch_count = getattr(tok, "count_batch", None)
        texts = [s["text"] for s in processed]
        session.stale_tokens += (sum(batch_count(texts)) if batch_count
                                 else sum(tok.count(t) for t in texts))

    def _append(self, session: LiveSession, rec: dict) -> None:
        if session.journal is not None:
            session.journal.append(rec)

    def _rehydrate(self, session: LiveSession, state: dict, wal: Path,
                   stale: bool) -> None:
        """Rebuild a recovered session from replayed state (under the
        session lock — recovery usually runs before serving, but a
        handler racing it must see either nothing or the whole session).
        With a stale fingerprint the old WAL is set aside and a fresh
        journal re-persists header + segments; summaries/nodes drop
        (they were produced under a different surface)."""
        with session.lock:
            if stale:
                try:
                    os.replace(wal, str(wal) + ".stale")
                except OSError:
                    pass
                session.journal = jl.Journal(session.wal_path)
                self._append(session, {
                    "type": REC_SESSION, "session_id": session.session_id,
                    "fingerprint": session.fingerprint,
                    "params": session.params,
                    "created_t": session.created_t,
                    "trace_id": session.trace_id,
                    "tenant": session.tenant})
            tokens_by_seq: dict[int, int] = {}
            for seq in sorted(state["segments"]):
                raw = state["segments"][seq]
                session.append_seq = seq
                before = session.stale_tokens
                try:
                    self._ingest(session, raw)
                except Exception as e:  # noqa: BLE001 - degrade per batch
                    # a batch only a pre-validation build could have
                    # journaled: skip IT, never drop the whole session
                    logger.warning(
                        "session %s: segment batch %d unreplayable "
                        "(%s: %s); skipped", session.session_id, seq,
                        type(e).__name__, e)
                    continue
                session.n_raw_segments += len(raw)
                if stale:
                    self._append(session, {
                        "type": REC_SEGMENTS, "seq": seq, "segments": raw})
                tokens_by_seq[seq] = session.stale_tokens - before
            if stale:
                return
            for key, rec in state["chunks"].items():
                # errored records are NOT rehydrated: a restart is a
                # fresh retry chance (the jobs rule); empty-but-
                # successful summaries resume on presence, not truthiness
                if rec.get("summary") is not None and not rec.get("error"):
                    session.chunk_cache[key] = rec
            session.node_cache = dict(state["nodes"])
            snap = state["summary"]
            if snap is not None:
                session.summary = {k: snap.get(k) for k in
                                   ("summary", "seq", "n_segments",
                                    "end_time", "refreshed_t", "n_chunks",
                                    "levels", "hierarchical")}
            # staleness = tokens of the batches the recovered summary
            # does NOT cover (counting the whole transcript here would
            # both misreport pending_tokens and spuriously fire the
            # auto-refresh threshold on the next tiny append)
            covered = (session.summary or {}).get("seq", 0)
            session.stale_tokens = sum(
                t for seq, t in tokens_by_seq.items() if seq > covered)

    # ------------------------------------------------------------- refresh

    def _refresh_locked(self, session: LiveSession,
                        klass: str | None = None,
                        auto: bool = False) -> dict:
        """One incremental refresh (caller holds the session lock):
        re-run only dirty map chunks, then the reduce-tree path from each
        dirty leaf to the root through the stable tree + node cache."""
        t0 = time.time()
        if klass is not None and klass not in _CLASSES:
            raise ValueError(f"unknown deadline class {klass!r}; "
                             f"want one of {_CLASSES}")
        klass = (klass or session.params.get("class")
                 or self.live_cfg.class_default)
        params = session.params
        map_prompt = resolve_map_prompt(params.get("prompt_template"), None)
        sys_prompt = resolve_system_prompt(params.get("system_prompt"), None)
        reduce_prompt = resolve_reduce_prompt(
            params.get("aggregator_prompt"), None)
        summary_type = params.get("summary_type", "summary")

        chunks = session.inc.chunks()
        chunker = session.inc.chunker
        dirty: list[Chunk] = []
        reused = 0
        for c in chunks:
            # live map prompts use the APPEND-STABLE context header: the
            # batch header's "of N" / position% change on every append,
            # and a cached summary must mean the same thing a cold run of
            # the grown transcript would compute for this chunk
            c.text_with_context = chunker.stable_context_header(c) + c.text
            key = jl.chunk_key(c.chunk_index, c.start_time, c.end_time)
            rec = session.chunk_cache.get(key)
            # the text hash must match too: the open tail's (index,start,
            # end) can survive an append that grows its text (zero-
            # duration segments, sub-rounding end deltas) — reusing the
            # old summary there would break refresh==cold token identity
            if rec is not None and rec.get("text_sha") == _text_sha(c.text):
                c.summary = rec["summary"]
                c.tokens_used = rec.get("tokens_used", 0)
                c.error = None
                reused += 1
            else:
                c.summary = None
                c.error = None
                dirty.append(c)
        self._c_chunks_reused.inc(reused)

        # an interactive refresh carries a deadline budget end to end —
        # the executor stamps map AND reduce requests, so the scheduler
        # sheds/expires it ahead of unbounded bulk work (PR 5 lifecycle)
        engine_cfg = self.config.engine
        if klass == "interactive":
            engine_cfg = dataclasses.replace(
                engine_cfg,
                request_deadline_s=self.live_cfg.interactive_deadline_s)
        from lmrs_tpu.engine.api import TenantStampEngine

        def _publish_usage(snap: dict) -> None:
            # atomic reference swap (see jobs/manager.py): status docs
            # serialize a snapshot, never the dict a merge is resizing
            session.usage = snap

        # QoS class rides the refresh's deadline class: an interactive
        # refresh outranks batch job fan-out by policy (fleet/qos.py);
        # a bulk refresh competes as batch like any other bulk work
        # cross-refresh drafting (tree speculation): the PREVIOUS refresh's
        # summary seeds the device draft buffer for every request of this
        # refresh — a rolling summary mostly restates itself, so the prior
        # text is a near-perfect n-gram draft source.  Advisory only
        # (exact-distribution verify): outputs are unchanged either way.
        prior = session.summary or {}
        stamp = TenantStampEngine(self.engine, session.tenant,
                                  publish=_publish_usage,
                                  seed=session.usage,
                                  qos_class=("interactive"
                                             if klass == "interactive"
                                             else "batch"),
                                  draft_hint=prior.get("summary"))
        executor = MapExecutor(stamp, engine_cfg)
        with session.ctl:
            session._executor = executor

        map_failed = 0
        if dirty and not session.cancel_ev.is_set():
            map_failed = self._run_map(session, executor, dirty,
                                       map_prompt, summary_type, sys_prompt)
        if session.cancel_ev.is_set():
            with session.ctl:
                session._executor = None
            return {"cancelled": True}

        cache = _SessionNodeCache(self, session)
        reduce_cfg = dataclasses.replace(self.config.reduce,
                                         stable_tree=True)
        aggregator = ResultAggregator(executor, reduce_cfg,
                                      tokenizer=session.inc.chunker.tokenizer)
        metadata = {
            "duration": format_duration(session.end_time),
            "speakers": ", ".join(session.speakers),
            "num_chunks": len(chunks),
        }
        with self._engine_lock:
            agg = aggregator.aggregate(chunks, reduce_prompt, metadata,
                                       node_cache=cache)
        with session.ctl:
            session._executor = None
        if session.cancel_ev.is_set():
            return {"cancelled": True}
        self._c_nodes_reused.inc(cache.reused)

        final_error = bool(agg.get("final_error"))
        if not final_error:
            snap = {
                "summary": agg["final_summary"],
                "seq": session.append_seq,
                "n_segments": session.n_raw_segments,
                "end_time": session.end_time,
                "refreshed_t": time.time(),
                "n_chunks": len(chunks),
                "levels": agg["levels"],
                "hierarchical": agg["hierarchical"],
            }
            session.summary = snap
            session.stale_tokens = 0
            self._append(session, {"type": REC_SUMMARY, **snap})
        else:
            # the deliverable itself is an error marker (the final reduce
            # degraded — same rule as the jobs tier's failed status):
            # installing it would overwrite the last GOOD summary, journal
            # the marker as the session's truth, and zero the staleness
            # that should keep the auto-refresh threshold armed
            logger.warning(
                "session %s: refresh produced an error-marker final "
                "summary; previous summary retained, staleness kept",
                session.session_id)
        wall = time.time() - t0
        self._c_refreshes.inc()
        if chunks:
            self._h_dirty.observe(len(dirty) / len(chunks))
        self._h_refresh.observe(wall)
        tr = get_tracer()
        if tr:
            tr.instant("session_refresh", pid=PID_PIPELINE,
                       args={"session": session.session_id,
                             "dirty_chunks": len(dirty),
                             "total_chunks": len(chunks),
                             "nodes_reused": cache.reused,
                             "nodes_computed": cache.computed,
                             "class": klass,
                             "trace": session.trace_id})
        logger.info(
            "session %s: refresh (%s%s) %d/%d dirty chunks, %d/%d reduce "
            "nodes reused, %.2fs", session.session_id, klass,
            ", auto" if auto else "", len(dirty), len(chunks),
            cache.reused, cache.reused + cache.computed, wall)
        return {
            "object": "session.refresh",
            "class": klass,
            "auto": auto,
            "num_chunks": len(chunks),
            "dirty_chunks": len(dirty),
            "chunk_summaries_reused": reused,
            "map_failed": map_failed,
            "reduce_nodes_reused": cache.reused,
            "reduce_nodes_computed": cache.computed,
            "levels": agg["levels"],
            "hierarchical": agg["hierarchical"],
            "reduce_errors": agg.get("reduce_errors", 0),
            "final_error": final_error,
            "refresh_seconds": round(wall, 4),
            "summary": agg["final_summary"],
        }

    def _run_map(self, session: LiveSession, executor: MapExecutor,
                 dirty: list[Chunk], map_prompt: str, summary_type: str,
                 sys_prompt: str | None) -> int:
        """Map the dirty chunks, journaling each summary AS IT COMPLETES
        (the WAL advances inside the stream — the SIGKILL contract).
        Returns the failed-chunk count.  Successful summaries enter the
        session's chunk cache; failures keep their error marker for THIS
        refresh but are not cached, so the next refresh retries them."""
        chunk_by_rid = {i: c for i, c in enumerate(dirty)}
        requests = [executor.build_map_request(
            c, map_prompt, summary_type, sys_prompt, request_id=i)
            for i, c in enumerate(dirty)]
        with session.ctl:
            session._live_rids = set(chunk_by_rid)
        failed = [0]

        def on_final(res, submit) -> None:
            c = chunk_by_rid[res.request_id]
            with session.ctl:
                session._live_rids.discard(res.request_id)
            reason = degraded_reason(res)
            if reason is not None:
                c.summary = f"[Error processing chunk: {reason}]"
                c.error = reason
                failed[0] += 1
            else:
                c.summary = res.text
            c.tokens_used = res.total_tokens
            key = jl.chunk_key(c.chunk_index, c.start_time, c.end_time)
            if res.finish_reason != "cancelled":
                rec = {"type": jl.REC_CHUNK, "chunk_index": c.chunk_index,
                       "start_time": c.start_time, "end_time": c.end_time,
                       # tail-chunk guard: (index,start,end) alone is not
                       # enough identity for the OPEN chunk — a zero-
                       # duration (or sub-rounding) append grows its text
                       # without moving its end, and the stale summary
                       # would rehydrate over the grown content
                       "text_sha": _text_sha(c.text),
                       "summary": c.summary, "tokens_used": c.tokens_used,
                       "error": c.error}
                self._append(session, rec)
                if c.error is None:
                    session.chunk_cache[key] = rec
            if session.cancel_ev.is_set():
                executor.interrupt()
                with session.ctl:
                    rids = list(session._live_rids)
                for rid in rids:
                    executor.cancel(rid)

        with self._engine_lock:
            executor.run_requests_streaming(requests, on_final)
        with session.ctl:
            session._live_rids = set()
        return failed[0]
