"""Live per-dispatch performance attribution + on-demand profiler capture.

Every PERF.md MFU / HBM-bandwidth number so far was an offline bench
artifact (``scheduler.roofline_microbench``, RTT-amortized chains).  This
module turns the same roofline model (utils/perf_model) into a LIVE
signal on the serving path:

* ``DispatchAttribution`` — owned by the continuous scheduler, fed from
  the real dispatch loop.  Each decode block knows its model byte cost
  (weights once per step + live-KV walk) and each prefill dispatch its
  model FLOP cost; measured dispatch walls (minus the host link RTT —
  on tunneled chips the RTT dwarfs small dispatches, docs/PERF.md) turn
  those into ``lmrs_decode_hbm_util_ratio`` and
  ``lmrs_prefill_mfu_ratio`` samples, plus ``lmrs_step_gap_ms`` — the
  host-side gap between consecutive decode dispatches (the device-idle
  share the overlap levers attack).

  Attribution method (documented limits, docs/OBSERVABILITY.md):

  - decode blocks with NO prefill work threaded into them are CLEAN
    samples: util = model_bytes / (wall - rtt) / peak_bw, and they feed
    a running utilization estimate;
  - blocks that carry a same-iteration prefill dispatch (the deferred
    tok0 path sequences prefill before the decode scan on device) are
    decomposed: the decode share is estimated from the running
    utilization, the remainder is charged to prefill → an MFU sample.
    No clean decode sample yet → the mixed block only counts bytes/FLOPs;
  - FUSED mixed steps (SARATHI mixed batches) are split EXACTLY: their
    per-row token counts are known, so the wall apportions proportionally
    to each phase's roofline time — no EMA estimate involved
    (``note_mixed_step``);
  - first-run (compiling) shapes never produce samples;
  - speculative-decode blocks contribute step gaps only (their byte
    model differs; spec is off on the bench and default-off in serving).

* ``start_profile_capture`` — the ``POST /v1/debug/profile`` /
  ``LMRS_PROFILE_ON_SLOW_STEP`` hook: a bounded, one-at-a-time
  ``jax.profiler`` trace capture into a directory, stopped by a timer so
  an abandoned capture can never run forever.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from lmrs_tpu.utils.env import env_float, env_str

logger = logging.getLogger("lmrs.obs.perf")


class DispatchAttribution:
    """Roofline attribution fed from the live dispatch loop (see module
    doc).  Registers its metrics on the scheduler's registry so they ride
    the existing ``metrics_report()`` / Prometheus surfaces."""

    def __init__(self, model_cfg, engine_cfg, registry):
        from lmrs_tpu.obs.metrics import MS_LATENCY_BUCKETS, RATIO_BUCKETS

        self.model_cfg = model_cfg
        self._quantized = bool(getattr(engine_cfg, "quantize", None))
        self._kv_quantized = bool(getattr(engine_cfg, "kv_quantize", None))
        self._rtt: float | None = None
        self._rtt_t: float | None = None  # clock time of the last probe
        self._clock = time.time  # injectable (stale-RTT regression test)
        self._hbm_util_est: float | None = None  # running clean-sample EMA
        self._last_block_end: float | None = None
        h, g, c = registry.histogram, registry.gauge, registry.counter
        self.h_mfu = h("lmrs_prefill_mfu_ratio", buckets=RATIO_BUCKETS,
                       help="live prefill model-FLOPs utilization per "
                            "attributed dispatch")
        self.h_hbm = h("lmrs_decode_hbm_util_ratio", buckets=RATIO_BUCKETS,
                       help="live decode HBM-bandwidth utilization per "
                            "clean decode block")
        self.h_gap = h("lmrs_step_gap_ms", buckets=MS_LATENCY_BUCKETS,
                       help="host-side gap between consecutive decode "
                            "dispatches (end of fetch to next issue)",
                       unit="ms")
        self.g_mfu = g("lmrs_prefill_mfu_ratio_last",
                       "most recent live prefill MFU sample")
        self.g_hbm = g("lmrs_decode_hbm_util_ratio_last",
                       "most recent live decode HBM-utilization sample")
        self.g_gap = g("lmrs_step_gap_ms_last",
                       "most recent decode step gap", "ms")
        self.c_flops = c("lmrs_prefill_model_flops_total",
                         "model-accounted prefill FLOPs dispatched",
                         "flops")
        self.c_bytes = c("lmrs_decode_model_bytes_total",
                         "model-accounted decode HBM bytes dispatched",
                         "bytes")
        # host-RAM KV prefetch (engine/host_kv.py): scatter bytes issued
        # asynchronously ride into the NEXT decode block's wall, so that
        # block must not feed the clean-sample EMA — the pending flag
        # marks it dirty and the bytes are counted here
        self.c_prefetch_bytes = c("lmrs_prefix_prefetch_bytes_total",
                                  "host→HBM bytes restored by KV spill "
                                  "prefetch", "bytes")
        self._prefetch_pending = False

    # ------------------------------------------------------------ plumbing

    def _spec(self):
        from lmrs_tpu.utils.perf_model import chip_spec

        return chip_spec()

    def ensure_rtt(self) -> float:
        """Median trivial dependent-fetch round trip, measured lazily and
        RE-SAMPLED on a slow cadence (``LMRS_RTT_RESAMPLE_S``, default
        300 s): a long-lived process can see its host link degrade (VPN
        reroute, tunnel congestion) and a once-per-process sample would
        then skew every dispatch wall it is subtracted from.  A re-probe
        FAILURE keeps the previous sample (but refreshes the timestamp so
        a flaky link is not hammered every call).  Subtracted from every
        dispatch wall — on a tunneled chip the RTT is ~97% of a small
        dispatch's wall and attribution without the subtraction measures
        the link, not the chip (docs/PERF.md round 5)."""
        from lmrs_tpu.obs.anatomy import rtt_resample_s

        now = self._clock()
        if (self._rtt is not None and self._rtt_t is not None
                and now - self._rtt_t < rtt_resample_s()):
            return self._rtt
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np

            x = jnp.zeros((8,), jnp.float32)
            np.asarray(jax.device_get(x + 1))  # warm the tiny program
            rtts = []
            for _ in range(3):
                t0 = time.time()
                np.asarray(jax.device_get(x + 1))
                rtts.append(time.time() - t0)
            self._rtt = sorted(rtts)[1]
        except Exception:  # noqa: BLE001 - attribution must never kill
            if self._rtt is None:
                logger.warning("RTT probe failed; attribution walls will "
                               "include the host link RTT", exc_info=True)
                self._rtt = 0.0
            else:
                logger.warning("RTT re-probe failed; keeping the previous "
                               "sample", exc_info=True)
        self._rtt_t = now
        return self._rtt

    def rtt_sample(self) -> tuple[float | None, float | None]:
        """``(rtt_s, age_s)`` of the current sample WITHOUT probing —
        the anatomy report's stale-RTT guard reads this so a report can
        never trigger a device round trip, and a sample older than its
        staleness horizon is flagged instead of silently skewing the
        dispatch/fetch split."""
        if self._rtt is None or self._rtt_t is None:
            return None, None
        return self._rtt, max(self._clock() - self._rtt_t, 0.0)

    def prefill_flops(self, chunk_tokens: int, kv_start: int = 0) -> float:
        """Model FLOPs of one prefill row: a fresh causal chunk
        (``kv_start=0``) or a windowed continuation chunk attending
        ``kv_start`` earlier KV tokens.  LM head on the sampled row only
        (the packed-prefill gather — forward_paged last_pos)."""
        from lmrs_tpu.utils.perf_model import prefill_flops

        return prefill_flops(self.model_cfg, max(1, chunk_tokens),
                             head_tokens=1, kv_start=kv_start)

    def decode_bytes(self, steps: int, n_live: int, live_tokens: int) -> float:
        """Model HBM bytes of one decode block: every matmul weight once
        per step (batch-amortized) plus the live-KV walk, whose per-step
        total grows by one token per live row per step."""
        from lmrs_tpu.utils.perf_model import (kv_bytes_per_token,
                                               weight_bytes)

        kv_token_steps = (steps * live_tokens
                          + n_live * steps * (steps - 1) / 2.0)
        kv = kv_bytes_per_token(self.model_cfg) * kv_token_steps
        if self._kv_quantized:
            kv /= 2
        return steps * weight_bytes(self.model_cfg, self._quantized) + kv

    def note_prefetch(self, nbytes: float) -> None:
        """A KV spill prefetch was issued (async scatter): count its HBM
        bytes and mark the next decode block dirty — its wall includes
        the transfer, so it must count work but never sample utilization
        (same discipline as compiling shapes)."""
        if nbytes > 0:
            self.c_prefetch_bytes.inc(nbytes)
        self._prefetch_pending = True

    # ------------------------------------------------------------- samples

    def note_gap(self, t_start: float, t_end: float) -> None:
        """Record the host-side gap since the previous block's fetch
        completed (the device-idle window between dispatches), and mark
        this block's end.  Called by every block path — including
        speculative blocks, which contribute no byte/FLOP samples."""
        if self._last_block_end is not None:
            gap_ms = max(0.0, (t_start - self._last_block_end) * 1e3)
            self.h_gap.observe(gap_ms)
            self.g_gap.set(gap_ms)
        self._last_block_end = t_end

    def note_block(self, t_start: float, t_end: float, steps: int,
                   n_live: int, live_tokens: int, prefill_flops: float,
                   warm: bool) -> float:
        """One decode-block dispatch: wall [t_start, t_end], ``n_live``
        rows at ``live_tokens`` total context, with ``prefill_flops`` of
        same-iteration prefill work sequenced before it on device (0 for
        a clean decode block).  ``warm=False`` (a compiling shape) counts
        work but never samples.  Returns the block's model byte cost (the
        ``hbm_gb`` trace-span arg)."""
        self.note_gap(t_start, t_end)
        nbytes = self.decode_bytes(steps, n_live, live_tokens)
        self.c_bytes.inc(nbytes)
        if prefill_flops > 0:
            self.c_flops.inc(prefill_flops)
        if self._prefetch_pending:
            # the wall includes an async spill-prefetch scatter sequenced
            # before this block: count the work, skip the samples
            self._prefetch_pending = False
            warm = False
        if not warm:
            return nbytes
        spec = self._spec()
        t = (t_end - t_start) - self.ensure_rtt()
        if t <= 1e-6:
            return nbytes
        if prefill_flops <= 0:
            util = nbytes / t / spec.peak_hbm_bw
            if 0.0 < util < 4.0:  # garbage guard (clock steps, CPU fallback)
                self.h_hbm.observe(util)
                self.g_hbm.set(util)
                self._hbm_util_est = (util if self._hbm_util_est is None
                                      else 0.8 * self._hbm_util_est
                                      + 0.2 * util)
            return nbytes
        # mixed block: subtract the decode share estimated from clean
        # samples; the remainder is the prefill compute the device spent
        if self._hbm_util_est is None or self._hbm_util_est <= 0:
            return nbytes
        t_decode = nbytes / (spec.peak_hbm_bw * self._hbm_util_est)
        t_prefill = t - t_decode
        if t_prefill <= 1e-6:
            return nbytes
        mfu = prefill_flops / t_prefill / spec.peak_flops
        if 0.0 < mfu < 4.0:
            self.h_mfu.observe(mfu)
            self.g_mfu.set(mfu)
        return nbytes

    def note_mixed_step(self, t_start: float, t_end: float, n_live: int,
                        live_tokens: int, prefill_flops: float,
                        warm: bool, span_tokens: int | None = None) -> float:
        """One FUSED mixed dispatch (SARATHI mixed batches): ``n_live``
        decode rows advance one token and a prefill slice of known size
        rides the SAME program.  Unlike the sequenced-prefill decode
        blocks (``note_block``, whose decode share must be ESTIMATED from
        the clean-sample EMA), the fused step's per-row token counts are
        exact, so the split needs no estimate: the wall is apportioned
        proportionally to each phase's own roofline time
        (``bytes/peak_bw`` vs ``flops/peak_flops``), under which both
        phase samples equal the step's combined roofline utilization —
        the assumption-free number for a step whose two phases share one
        kernel launch (they cannot be timed apart host-side).  Clean
        decode samples alone keep feeding the EMA.  Returns the step's
        model byte cost (the ``hbm_gb`` trace-span arg).

        ``span_tokens`` is the SPAN-LEVEL decode token count from a
        ragged span dispatch (LMRS_RPA): total decode-side query tokens
        in the step — ``(1 + spec_k) * n_live`` when decode rows carry
        verify spans.  Defaults to ``n_live`` (one token per live row,
        the legacy fused step), under which the byte model is unchanged."""
        self.note_gap(t_start, t_end)
        if span_tokens is None or span_tokens <= n_live or n_live <= 0:
            nbytes = self.decode_bytes(1, n_live, live_tokens)
        else:
            # ragged span step: every query token in a row's span walks
            # that row's KV, so the walk term scales by the mean span
            # length instead of the legacy one-token-per-row shape
            from lmrs_tpu.utils.perf_model import (kv_bytes_per_token,
                                                   weight_bytes)
            kv = (kv_bytes_per_token(self.model_cfg)
                  * live_tokens * span_tokens / n_live)
            if self._kv_quantized:
                kv /= 2
            nbytes = weight_bytes(self.model_cfg, self._quantized) + kv
        self.c_bytes.inc(nbytes)
        if prefill_flops > 0:
            self.c_flops.inc(prefill_flops)
        if self._prefetch_pending:  # same contract as note_block
            self._prefetch_pending = False
            warm = False
        if not warm:
            return nbytes
        spec = self._spec()
        t = (t_end - t_start) - self.ensure_rtt()
        if t <= 1e-6:
            return nbytes
        t_model = (nbytes / spec.peak_hbm_bw
                   + max(prefill_flops, 0.0) / spec.peak_flops)
        util = t_model / t
        if 0.0 < util < 4.0:  # same garbage guard as note_block
            self.h_hbm.observe(util)
            self.g_hbm.set(util)
            if prefill_flops > 0:
                self.h_mfu.observe(util)
                self.g_mfu.set(util)
        return nbytes

    def note_prefill_sync(self, flops: float, t_start: float,
                          t_end: float, warm: bool) -> None:
        """A prefill wave whose first tokens were fetched SYNCHRONOUSLY
        (handoff-export slots, speculation, LMRS_DEFER_TOK0=0): the wall
        covers exactly the prefill compute + one RTT — a clean MFU sample
        (this is the prefill pod's whole serving life under
        disaggregation)."""
        if flops <= 0:
            return
        self.c_flops.inc(flops)
        if self._prefetch_pending:  # the wave's wall includes the scatter
            self._prefetch_pending = False
            warm = False
        if not warm:
            return
        t = (t_end - t_start) - self.ensure_rtt()
        if t <= 1e-6:
            return
        mfu = flops / t / self._spec().peak_flops
        if 0.0 < mfu < 4.0:
            self.h_mfu.observe(mfu)
            self.g_mfu.set(mfu)

    # -------------------------------------------------------------- report

    def report(self) -> dict:
        """The ``perf_attribution`` block of ``metrics_report()`` / bench
        detail: per-phase live roofline ratios + the model-accounted work
        totals they were computed over."""
        return {
            "prefill_mfu": self.h_mfu.percentile_report(scale=1.0,
                                                        ndigits=4),
            "prefill_mfu_last": round(self.g_mfu.value, 4),
            "decode_hbm_util": self.h_hbm.percentile_report(scale=1.0,
                                                            ndigits=4),
            "decode_hbm_util_last": round(self.g_hbm.value, 4),
            "step_gap_ms": self.h_gap.percentile_report(scale=1.0),
            # 6 decimals: tiny test models dispatch MEGA-scale work, and
            # a report that rounds real nonzero totals to 0.0 reads as
            # "attribution dead" exactly where tests check liveness
            "model_prefill_gflops": round(self.c_flops.value / 1e9, 6),
            "model_decode_gb": round(self.c_bytes.value / 1e9, 6),
            "rtt_ms": (round(self._rtt * 1e3, 2)
                       if self._rtt is not None else None),
        }


# ------------------------------------------------ on-demand profiler capture

_capture_lock = threading.Lock()
_capture_active = False


def profile_capture_active() -> bool:
    with _capture_lock:
        return _capture_active


def default_profile_dir() -> str:
    """Where captures land unless the caller says otherwise: the ONE
    implementation of the LMRS_PROFILE_DIR fallback, shared by the
    ``/v1/debug/profile`` endpoint and the slow-step trigger so the two
    capture paths can never write to different places."""
    import tempfile

    return (env_str("LMRS_PROFILE_DIR")
            or os.path.join(tempfile.gettempdir(), "lmrs_profile"))


def start_profile_capture(out_dir: str, duration_s: float = 2.0
                          ) -> tuple[bool, str]:
    """Start a bounded ``jax.profiler`` trace capture into ``out_dir``,
    auto-stopped after ``duration_s`` by a daemon timer.  One capture at a
    time per process (the profiler is process-global); returns
    ``(ok, dir_or_reason)``.  Never raises — the caller is a serving
    endpoint or the slow-step trigger, neither of which may die on a
    profiler hiccup."""
    import math

    global _capture_active
    # NaN survives min/max clamps and would kill the stop timer's
    # Event.wait, leaving _capture_active wedged True forever — the same
    # reason the deadline parser refuses non-finite budgets
    duration_s = float(duration_s)
    if not math.isfinite(duration_s):
        duration_s = 2.0
    duration_s = min(max(duration_s, 0.1), 60.0)
    with _capture_lock:
        if _capture_active:
            return False, "a profile capture is already running"
        _capture_active = True
    try:
        import pathlib

        import jax

        pathlib.Path(out_dir).mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(str(out_dir))
    except Exception as e:  # noqa: BLE001 - report, never raise
        with _capture_lock:
            _capture_active = False
        return False, f"profiler start failed: {type(e).__name__}: {e}"

    def _stop() -> None:
        global _capture_active
        try:
            import jax

            jax.profiler.stop_trace()
            logger.info("profile capture written to %s", out_dir)
        except Exception:  # noqa: BLE001 - best-effort stop
            logger.warning("profiler stop failed", exc_info=True)
        finally:
            with _capture_lock:
                _capture_active = False

    timer = threading.Timer(duration_s, _stop)
    timer.daemon = True
    timer.start()
    logger.info("profile capture started: %s (%.1fs)", out_dir, duration_s)
    return True, out_dir


def slow_step_threshold_s() -> float:
    """The ``LMRS_PROFILE_ON_SLOW_STEP`` trigger threshold (seconds);
    0 = disabled.  Read per call so tests can arm it without rebuilding
    the engine."""
    return env_float("LMRS_PROFILE_ON_SLOW_STEP", 0.0, lo=0.0)
