"""Per-request cost ledger: who consumed the device, exactly.

PR 8's roofline attribution and PR 10's exact mixed-step split tell you
how fast the hardware ran; this module extends the same apportionment
ONE level down, to the individual rows inside each dispatch, so every
request accumulates an honest device-time bill:

* each dispatch's measured wall is first split between its prefill and
  decode phases by their own roofline times (the exact-split rule
  ``note_mixed_step`` established — the two phases share one kernel
  launch and cannot be timed apart host-side), then each phase's share
  is apportioned to its participating rows by per-row work (prefill
  FLOPs / emitted decode tokens);
* per request the ledger accumulates: phase-split device-seconds,
  prompt/generated token attribution, tokens saved (prefix-cache hits,
  host-KV prefetch, accepted speculation), KV page-seconds (pages held
  x dispatch wall), host-pool byte-seconds (bytes prefetched x request
  residency), queue wait, and wedge counts;
* entries key on request id plus the ``tenant`` aggregation label
  (``X-LMRS-Tenant``, minted at ingress and propagated like the trace
  id — jobs and live sessions default it to their own identity, so
  ``GET /v1/usage`` rolls up per job/session for free).

**Conservation is an auditable invariant**, not a hope:
``audit()`` checks that the per-request device-seconds (live entries +
finished rollups) sum to the dispatch walls the ledger was fed (within
float epsilon — each wall's row shares are remainder-corrected so the
per-dispatch sum is exact) and that attributed tokens equal dispatched
tokens EXACTLY (integers are never split).  ``scheduler.audit()``
carries both checks, so every chaos/fuzz arm that audits also proves
the bill adds up.

``LMRS_COST_LEDGER=0`` disables the ledger: every note is a no-op,
results carry no ``usage`` block, and generated tokens are byte-for-byte
identical (the ledger is pure host bookkeeping — it touches no RNG and
no dispatch).
"""

from __future__ import annotations

import logging
import threading

from lmrs_tpu.utils.env import env_bool, env_int

logger = logging.getLogger("lmrs.obs.ledger")

DEFAULT_TENANT = "default"

# past LMRS_COST_TENANTS_MAX distinct labels, new tenants' rollups fold
# into this aggregate bucket (jobs/sessions mint one label each, and the
# rollup map lives as long as the scheduler — cardinality must be capped)
OVERFLOW_TENANT = "other"

_SAVED_KINDS = ("prefix_cache", "host_kv_prefetch", "speculation")

# per-request / per-tenant accumulator fields (one list so the entry,
# the rollup, and the merge can never drift apart)
_FIELDS = ("prefill_device_seconds", "decode_device_seconds",
           "queue_wait_seconds", "kv_page_seconds",
           "host_pool_byte_seconds", "prompt_tokens", "generated_tokens",
           "tokens_saved_prefix_cache", "tokens_saved_host_kv_prefetch",
           "tokens_saved_speculation", "goodput_tokens", "wasted_tokens",
           "wedges")


def _zero() -> dict:
    return {f: 0.0 if "seconds" in f else 0 for f in _FIELDS}


def totals_from_tenants(tenants: dict) -> dict:
    """Fold per-tenant rollups into one totals doc — the ONE fold shared
    by the ledger's host report, the replicated engine's replica merge,
    and the router's fleet aggregation, so totals computed at any level
    agree with the sum of their parts."""
    totals: dict = {}
    for roll in tenants.values():
        merge_usage(totals, roll)
    totals.pop("requests", None)
    totals["requests"] = sum(r.get("requests", 0) for r in tenants.values())
    return totals


def merge_usage(into: dict, usage: dict) -> dict:
    """Accumulate one usage doc (a result's ``usage`` block, or another
    rollup) into ``into`` — the ONE merge rule shared by the ledger's
    tenant rollups, the job/session rollups, and the router's fleet
    aggregation, so totals computed at any level agree."""
    for f in _FIELDS:
        v = usage.get(f, 0)
        if v:
            into[f] = into.get(f, 0) + v
    into["requests"] = into.get("requests", 0) + usage.get("requests", 1)
    into["device_seconds"] = round(
        into.get("prefill_device_seconds", 0.0)
        + into.get("decode_device_seconds", 0.0), 9)
    return into


class _Entry:
    __slots__ = ("tenant", "vals", "attr_prefill_tokens",
                 "attr_decode_tokens", "t_open", "pool_bytes")

    def __init__(self, tenant: str, t_open: float):
        self.tenant = tenant
        self.vals = _zero()
        # token-conservation counters: tokens attributed to this entry by
        # note_step (compared exactly against the ledger's dispatch total)
        self.attr_prefill_tokens = 0
        self.attr_decode_tokens = 0
        self.t_open = t_open
        # host-pool meter: bytes prefetched for this request (charged as
        # byte-seconds at finish, bytes x residency)
        self.pool_bytes = 0.0


class CostLedger:
    """Request-cost accounting on the continuous scheduler (module doc).

    Thread contract: the scheduler thread feeds dispatch notes; HTTP
    handler threads read ``usage_report()``; the watchdog's wedge sweep
    finishes entries from the caller thread while the scheduler thread
    is stuck — ONE lock covers all ledger state (pure in-memory math,
    nothing blocking runs under it)."""

    def __init__(self, registry=None, enabled: bool | None = None,
                 clock=None):
        import time

        self.enabled = (env_bool("LMRS_COST_LEDGER", True)
                        if enabled is None else bool(enabled))
        self.max_tenants = env_int("LMRS_COST_TENANTS_MAX", 512, lo=1)
        self.clock = clock or time.time
        # usage observer (fleet/qos.py fair-share window): called with the
        # (tenant, device_seconds) pairs of each apportioned dispatch,
        # AFTER _lock is released — the two locks never nest, so the
        # policy may read the ledger from its own callers freely
        self.observer = None
        self._lock = threading.Lock()
        self._entries: dict[int, _Entry] = {}   # guarded-by: _lock
        self._tenants: dict[str, dict] = {}     # guarded-by: _lock
        # conservation totals (guarded-by: _lock)
        self._wall_seconds = 0.0
        self._step_tokens = 0
        self._finished = 0
        self._c = {}
        if registry is not None and self.enabled:
            c = registry.counter
            self._c = {
                "prefill_s": c("lmrs_cost_prefill_device_seconds_total",
                               "device seconds attributed to prefill rows",
                               "seconds"),
                "decode_s": c("lmrs_cost_decode_device_seconds_total",
                              "device seconds attributed to decode rows",
                              "seconds"),
                "queue_s": c("lmrs_cost_queue_wait_seconds_total",
                             "queue wait attributed across requests",
                             "seconds"),
                "page_s": c("lmrs_cost_kv_page_seconds_total",
                            "KV page-seconds (pages held x dispatch wall)",
                            "page-seconds"),
                "pool_bs": c("lmrs_cost_host_pool_byte_seconds_total",
                             "host-pool byte-seconds (prefetched bytes x "
                             "request residency)", "byte-seconds"),
                "saved": c("lmrs_cost_tokens_saved_total",
                           "prompt/draft tokens saved across all sources",
                           "tokens"),
                "finished": c("lmrs_cost_requests_finished_total",
                              "requests whose cost entry was finalized"),
                "goodput": c("lmrs_cost_goodput_tokens_total",
                             "completion tokens of usable outcomes",
                             "tokens"),
                "wasted": c("lmrs_cost_wasted_tokens_total",
                            "completion tokens of failed/cancelled/wedged "
                            "outcomes", "tokens"),
                "overflow": c("lmrs_cost_tenants_overflow_total",
                              "finished requests whose tenant rollup "
                              "folded into the aggregate bucket past "
                              "LMRS_COST_TENANTS_MAX"),
            }

    # ----------------------------------------------------------- entry feed

    def _entry_locked(self, req) -> _Entry:  # holds-lock: _lock
        """Caller holds self._lock."""
        rid = req.request_id
        e = self._entries.get(rid)
        if e is None:
            tenant = getattr(req, "tenant", None) or DEFAULT_TENANT
            e = self._entries[rid] = _Entry(tenant, self.clock())
        return e

    def note_queue_wait(self, req, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            e = self._entry_locked(req)
            e.vals["queue_wait_seconds"] += max(0.0, seconds)
        c = self._c.get("queue_s")
        if c is not None:
            c.inc(max(0.0, seconds))

    def note_saved(self, req, prefix_tokens: int = 0,
                   prefetched_tokens: int = 0, spec_tokens: int = 0,
                   prefetched_bytes: float = 0.0) -> None:
        """Tokens this request never had to pay device time for: prefix
        cache hits (resident), host-KV prefetch restores, accepted
        speculation drafts.  ``prefetched_bytes`` opens the host-pool
        byte-seconds meter (charged at finish, bytes x residency)."""
        if not self.enabled:
            return
        with self._lock:
            e = self._entry_locked(req)
            e.vals["tokens_saved_prefix_cache"] += max(0, int(prefix_tokens))
            e.vals["tokens_saved_host_kv_prefetch"] += max(
                0, int(prefetched_tokens))
            e.vals["tokens_saved_speculation"] += max(0, int(spec_tokens))
            if prefetched_bytes > 0:
                e.pool_bytes += prefetched_bytes
        c = self._c.get("saved")
        if c is not None:
            saved = (max(0, int(prefix_tokens))
                     + max(0, int(prefetched_tokens))
                     + max(0, int(spec_tokens)))
            if saved:
                c.inc(saved)

    def note_step(self, wall_s: float, decode_rows=(), prefill_rows=(),
                  decode_cost_s: float = 0.0,
                  prefill_cost_s: float = 0.0) -> None:
        """Apportion ONE dispatch wall to its rows.

        ``decode_rows``: ``(req, tokens_emitted, pages_held)`` per live
        decode row; ``prefill_rows``: ``(req, tokens, flops)`` per
        prefill row in the fused/sequenced wave.  The wall splits between
        the phases proportionally to their roofline times
        (``decode_cost_s`` = model bytes / peak bw, ``prefill_cost_s`` =
        model FLOPs / peak FLOPs — the PR 10 exact-split rule); with no
        roofline estimate the split degrades to per-row token counts
        across both phases.  Within a phase, rows share by their own work
        (emitted tokens / per-row FLOPs), remainder-corrected so the
        per-dispatch sum is EXACT."""
        if not self.enabled or wall_s <= 0:
            return
        decode_rows = [r for r in decode_rows if r[0] is not None]
        prefill_rows = [r for r in prefill_rows if r[0] is not None]
        if not decode_rows and not prefill_rows:
            return
        # ---- phase split -------------------------------------------------
        if decode_rows and prefill_rows:
            dc, pc = max(decode_cost_s, 0.0), max(prefill_cost_s, 0.0)
            if dc + pc > 0:
                decode_wall = wall_s * dc / (dc + pc)
            else:  # no roofline estimate: split by token counts
                dtok = sum(max(1, int(t)) for _, t, _ in decode_rows)
                ptok = sum(max(1, int(t)) for _, t, _ in prefill_rows)
                decode_wall = wall_s * dtok / (dtok + ptok)
            prefill_wall = wall_s - decode_wall
        elif decode_rows:
            decode_wall, prefill_wall = wall_s, 0.0
        else:
            decode_wall, prefill_wall = 0.0, wall_s
        page_s = 0.0
        tenant_s: dict[str, float] = {}  # this dispatch's per-tenant bill
        with self._lock:
            self._wall_seconds += wall_s
            self._apportion_locked(decode_wall, decode_rows, "decode",
                                   tenant_s)
            self._apportion_locked(prefill_wall, prefill_rows, "prefill",
                                   tenant_s)
            # KV page-seconds bill on the FULL dispatch wall: the pages
            # are resident for the whole kernel launch, including a fused
            # step's prefill share (the module-doc / metrics-catalog
            # definition — NOT the phase-split share billed above)
            for req, _tok, pages in decode_rows:
                pages = max(0, int(pages))
                if pages:
                    charge = pages * wall_s
                    self._entry_locked(req).vals["kv_page_seconds"] += charge
                    page_s += charge
        if self._c:
            self._c["decode_s"].inc(decode_wall)
            self._c["prefill_s"].inc(prefill_wall)
            if page_s:
                self._c["page_s"].inc(page_s)
        obs = self.observer
        if obs is not None and tenant_s:
            obs(tenant_s.items())

    def _apportion_locked(self, wall: float, rows, phase: str,
                          tenant_s: dict | None = None) -> None:
        """Caller holds self._lock."""  # holds-lock: _lock
        if not rows:
            return
        field = f"{phase}_device_seconds"
        # weights: per-row work; an all-zero dispatch (every row emitted
        # nothing) splits evenly so the wall is still conserved
        weights = [max(0.0, float(r[2] if phase == "prefill" else r[1]))
                   for r in rows]
        total_w = sum(weights)
        if total_w <= 0:
            weights = [1.0] * len(rows)
            total_w = float(len(rows))
        spent = 0.0
        for i, row in enumerate(rows):
            req, tokens = row[0], max(0, int(row[1]))
            share = (wall - spent if i == len(rows) - 1
                     else wall * weights[i] / total_w)
            spent += share
            e = self._entry_locked(req)
            e.vals[field] += share
            if tenant_s is not None and share > 0:
                tenant_s[e.tenant] = tenant_s.get(e.tenant, 0.0) + share
            self._step_tokens += tokens
            if phase == "decode":
                e.attr_decode_tokens += tokens
            else:
                e.attr_prefill_tokens += tokens

    # ----------------------------------------------------------- lifecycle

    def finish(self, req, res) -> dict | None:
        """Finalize a request's entry against its terminal result:
        returns the ``usage`` doc (attached to ``GenerationResult.usage``
        and surfaced on the wire) and rolls the entry into its tenant's
        cumulative totals.  Requests that never touched a dispatch (shed,
        cancelled-in-queue) finalize a zero-cost entry — every outcome is
        billed to someone.  None when the ledger is disabled."""
        if not self.enabled:
            return None
        # goodput = tokens of outcomes the caller ASKED to end this way
        # (stop/length/handoff, no error); everything else — cancelled,
        # deadline, shed, wedged, errors — is wasted device work even
        # when partial text was kept (the docs' wasted definition, and
        # the same classification the SLO goodput numerator uses, so the
        # two surfaces can never disagree about the same traffic)
        usable = (res.error is None
                  and res.finish_reason in ("stop", "length", "handoff"))
        overflowed = False
        with self._lock:
            e = self._entries.pop(res.request_id, None)
            if e is None:
                e = _Entry(getattr(req, "tenant", None) or DEFAULT_TENANT,
                           self.clock())
            v = e.vals
            v["prompt_tokens"] = int(res.prompt_tokens)
            v["generated_tokens"] = int(res.completion_tokens)
            if e.pool_bytes:
                v["host_pool_byte_seconds"] += e.pool_bytes * max(
                    0.0, self.clock() - e.t_open)
            if usable:
                v["goodput_tokens"] = int(res.completion_tokens)
            else:
                v["wasted_tokens"] = int(res.completion_tokens)
            if res.finish_reason == "wedged":
                v["wedges"] = 1
            self._finished += 1
            # conservation: the attributed tokens leave with the entry,
            # so park them in the tenant rollup's hidden counters
            roll = self._tenants.get(e.tenant)
            if roll is None:
                if len(self._tenants) >= self.max_tenants \
                        and e.tenant != OVERFLOW_TENANT:
                    # cardinality cap: fold into the aggregate bucket —
                    # conservation keeps holding because the hidden token
                    # counters travel with whichever rollup is billed
                    if OVERFLOW_TENANT not in self._tenants:
                        logger.warning(
                            "cost ledger tenant cardinality cap (%d) "
                            "reached; new tenants roll up under %r "
                            "(raise LMRS_COST_TENANTS_MAX to widen)",
                            self.max_tenants, OVERFLOW_TENANT)
                    roll = self._tenants.setdefault(OVERFLOW_TENANT,
                                                    _zero())
                    overflowed = True
                else:
                    roll = self._tenants[e.tenant] = _zero()
            roll.setdefault("_attr_prefill_tokens", 0)
            roll.setdefault("_attr_decode_tokens", 0)
            roll["_attr_prefill_tokens"] += e.attr_prefill_tokens
            roll["_attr_decode_tokens"] += e.attr_decode_tokens
            # roll up the UNROUNDED values (rounding per request would
            # drift the conservation audit past its epsilon); the wire
            # usage doc is rounded for presentation only
            merge_usage(roll, {f: v[f] for f in _FIELDS})
            usage = {
                "tenant": e.tenant,
                **{f: (round(v[f], 6) if isinstance(v[f], float) else v[f])
                   for f in _FIELDS},
                "device_seconds": round(v["prefill_device_seconds"]
                                        + v["decode_device_seconds"], 6),
            }
        if self._c:
            self._c["finished"].inc()
            if overflowed:
                self._c["overflow"].inc()
            if usage["goodput_tokens"]:
                self._c["goodput"].inc(usage["goodput_tokens"])
            if usage["wasted_tokens"]:
                self._c["wasted"].inc(usage["wasted_tokens"])
            if usage["host_pool_byte_seconds"]:
                self._c["pool_bs"].inc(usage["host_pool_byte_seconds"])
        return usage

    @property
    def finished_count(self) -> int:
        with self._lock:
            return self._finished

    # -------------------------------------------------------------- reports

    def usage_report(self) -> dict:
        """The ``GET /v1/usage`` document: per-tenant cumulative rollups
        plus host totals (internal conservation counters stripped)."""
        if not self.enabled:
            return {"object": "usage", "enabled": False, "tenants": {},
                    "totals": {}}
        with self._lock:
            tenants = {
                t: {k: v for k, v in roll.items() if not k.startswith("_")}
                for t, roll in self._tenants.items()}
            live = len(self._entries)
        return {"object": "usage", "enabled": True, "tenants": tenants,
                "totals": totals_from_tenants(tenants),
                "live_requests": live}

    def report(self, before: dict | None = None) -> dict:
        """The ``cost`` block of ``metrics_report()`` / bench detail.
        With ``before`` (a prior ``report()``), the work fields window to
        the delta — same convention as ``_mixed_report``."""
        if not self.enabled:
            return {"enabled": False}
        with self._lock:
            wall = self._wall_seconds
            finished = self._finished
            tenants = len(self._tenants)
        doc = self.usage_report()
        tot = doc["totals"]
        b = (before or {})
        bt = b.get("totals", {})
        out = {
            "enabled": True,
            "requests_finished": finished - b.get("requests_finished", 0),
            "tenants": tenants,
            "attributed_wall_seconds": round(
                wall - b.get("attributed_wall_seconds_raw", 0.0), 6),
            "attributed_wall_seconds_raw": wall,
            "totals": {
                k: (round(tot.get(k, 0) - bt.get(k, 0), 6)
                    if isinstance(tot.get(k, 0), float)
                    else tot.get(k, 0) - bt.get(k, 0))
                for k in ("device_seconds", "prefill_device_seconds",
                          "decode_device_seconds", "goodput_tokens",
                          "wasted_tokens", "queue_wait_seconds",
                          "kv_page_seconds")},
            "totals_raw": tot,
        }
        return out

    # ---------------------------------------------------------------- audit

    def audit(self) -> list[str]:
        """Conservation invariants (joins ``scheduler.audit()``):

        * Σ per-request device-seconds (live entries + finished tenant
          rollups) == Σ dispatch walls fed to ``note_step`` within ε;
        * Σ attributed tokens == Σ dispatched tokens EXACTLY.
        """
        if not self.enabled:
            return []
        with self._lock:
            attr_s = sum(e.vals["prefill_device_seconds"]
                         + e.vals["decode_device_seconds"]
                         for e in self._entries.values())
            attr_tok = sum(e.attr_prefill_tokens + e.attr_decode_tokens
                           for e in self._entries.values())
            for roll in self._tenants.values():
                attr_s += (roll.get("prefill_device_seconds", 0.0)
                           + roll.get("decode_device_seconds", 0.0))
                attr_tok += (roll.get("_attr_prefill_tokens", 0)
                             + roll.get("_attr_decode_tokens", 0))
            wall, toks = self._wall_seconds, self._step_tokens
        out: list[str] = []
        eps = 1e-6 + 1e-9 * max(wall, 1.0)
        if abs(attr_s - wall) > eps:
            out.append(f"cost ledger device-seconds not conserved: "
                       f"attributed {attr_s:.9f}s != dispatched "
                       f"{wall:.9f}s (eps {eps:.2e})")
        if attr_tok != toks:
            out.append(f"cost ledger token attribution not conserved: "
                       f"attributed {attr_tok} != dispatched {toks}")
        return out
