"""Typed metric primitives + registry with Prometheus exposition.

Replaces the scheduler's ad-hoc cumulative dict and unbounded-ish latency
sample lists (SURVEY.md §5.5 grew into a grab-bag): Counter/Gauge/Histogram
objects live in one ``MetricsRegistry`` per engine, the scheduler's
``metrics_report()`` becomes a derived view over them (exact pre-registry
key names and shapes kept — bench windowing deltas those keys), and
``render_prometheus()`` emits the standard text exposition for scraping.

Histograms carry BOTH fixed log-spaced bucket counts (the Prometheus/
aggregation representation — mergeable across hosts, constant memory) and a
bounded reservoir of raw samples (the percentile representation — p50/p90/
p99 computed exactly as the old ``_latency_pct`` did, so latency reporting
does not quantize to bucket edges just because a registry arrived).

Dependency-free by design: stdlib + numpy only, importable from the
scheduler hot path, the HTTP server, and the router without pulling in a
metrics client library this image doesn't have.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left

import numpy as np

# one-two-five per decade, 1 ms .. 100 s: the span from a single decode
# step to a wedged-link dispatch, ~3 buckets per decade
DEFAULT_LATENCY_BUCKETS_S: tuple[float, ...] = tuple(
    round(m * 10.0 ** e, 6) for e in range(-3, 2) for m in (1.0, 2.5, 5.0)
) + (100.0,)

# pow2 token-count buckets: prefill dispatches range from one decode-block
# tail chunk to a full packed max_len row
POW2_TOKEN_BUCKETS: tuple[float, ...] = tuple(float(2 ** i) for i in range(4, 17))

# occupancy/utilization ratios are bounded [0, 1]: linear tenths, not log
RATIO_BUCKETS: tuple[float, ...] = tuple(round(i / 10.0, 1) for i in range(1, 11))

# millisecond-valued histograms (lmrs_step_gap_ms): 0.1 ms (a warm host
# turnaround) .. 50 s (a wedged dispatch), one-two-five per decade.  The
# values are OBSERVED in ms, so the Prometheus _sum stays in the unit the
# metric name promises.
MS_LATENCY_BUCKETS: tuple[float, ...] = tuple(
    round(m * 10.0 ** e, 6) for e in range(-1, 4) for m in (1.0, 2.5, 5.0)
) + (50000.0,)

_SAMPLE_CAP = 200_000  # same bound (drop oldest half) as the old raw lists


def log_buckets(lo: float, hi: float, per_decade: int = 3) -> tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds covering [lo, hi]."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi (got {lo}, {hi})")
    n = max(2, int(round(per_decade * math.log10(hi / lo))) + 1)
    ratio = (hi / lo) ** (1.0 / (n - 1))
    return tuple(round(lo * ratio ** i, 9) for i in range(n))


class Counter:
    """Monotonic cumulative value (float; token counts stay integral)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", unit: str = ""):
        self.name = name
        self.help = help
        self.unit = unit
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self.value += amount


class Gauge:
    """Point-in-time value; ``track_max`` keeps a running peak."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", unit: str = ""):
        self.name = name
        self.help = help
        self.unit = unit
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value

    def track_max(self, value: float) -> None:
        if value > self.value:
            self.value = value


class Histogram:
    """Fixed-bucket histogram + bounded raw-sample reservoir.

    Buckets are upper bounds (le), strictly increasing; +Inf is implicit.
    ``percentile_report()`` reproduces the old scheduler ``_latency_pct``
    exactly (np.percentile over the retained samples, ms, 0.1 precision,
    None when empty) so ``metrics_report()`` consumers see identical
    values; the bucket counts serve Prometheus exposition and cross-host
    aggregation, where raw samples cannot travel.
    """

    kind = "histogram"

    def __init__(self, name: str, buckets: tuple[float, ...],
                 help: str = "", unit: str = ""):
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"histogram {name}: buckets must be strictly "
                             f"increasing and non-empty (got {buckets})")
        self.name = name
        self.help = help
        self.unit = unit
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.samples: list[float] = []

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        self.samples.append(v)
        if len(self.samples) > _SAMPLE_CAP:  # drop the oldest half;
            del self.samples[: _SAMPLE_CAP // 2]  # percentiles stay recent

    def reset(self) -> None:
        """Drop everything (bench warmup isolation — compile-time gaps are
        orders of magnitude over steady state and must not pollute either
        the percentiles or the scrape)."""
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        self.samples.clear()

    def cumulative_counts(self) -> list[int]:
        """Prometheus-style cumulative per-le counts, +Inf last."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def percentile_report(self, scale: float = 1e3,
                          ndigits: int = 1) -> dict | None:
        """p50/p90/p99 over retained samples (default: seconds -> ms), or
        None when nothing was measured — metrics consumers then omit the
        block instead of reporting zeros (old ``_latency_pct`` contract)."""
        if not self.samples:
            return None
        p50, p90, p99 = np.percentile(np.asarray(self.samples), [50, 90, 99])
        return {"p50": round(float(p50) * scale, ndigits),
                "p90": round(float(p90) * scale, ndigits),
                "p99": round(float(p99) * scale, ndigits),
                "n": len(self.samples)}


class MetricsRegistry:
    """Name-keyed metric store; get-or-create so wiring sites stay terse."""

    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "", unit: str = "") -> Counter:
        return self._register(name, lambda: Counter(name, help, unit), Counter)

    def gauge(self, name: str, help: str = "", unit: str = "") -> Gauge:
        return self._register(name, lambda: Gauge(name, help, unit), Gauge)

    def histogram(self, name: str,
                  buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_S,
                  help: str = "", unit: str = "") -> Histogram:
        return self._register(
            name, lambda: Histogram(name, buckets, help, unit), Histogram)

    def _register(self, name: str, make, want_type):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = make()
            elif not isinstance(m, want_type):
                raise ValueError(f"metric {name} already registered as "
                                 f"{m.kind}")
            return m

    def get(self, name: str):
        return self._metrics.get(name)

    def metrics(self) -> list:
        return list(self._metrics.values())

    # ------------------------------------------------------------ exposition

    def render_prometheus(self, labels: dict[str, str] | None = None) -> str:
        """Prometheus text exposition (format 0.0.4) of every metric."""
        lines: list[str] = []
        for m in self._metrics.values():
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                cum = m.cumulative_counts()
                for le, c in zip(m.buckets, cum[:-1]):
                    lines.append(_sample(f"{m.name}_bucket",
                                         {**(labels or {}), "le": _fmt(le)}, c))
                lines.append(_sample(f"{m.name}_bucket",
                                     {**(labels or {}), "le": "+Inf"}, cum[-1]))
                lines.append(_sample(f"{m.name}_sum", labels, m.sum))
                lines.append(_sample(f"{m.name}_count", labels, m.count))
            else:
                lines.append(_sample(m.name, labels, m.value))
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Canonical number formatting: integral values render without the
    trailing .0 (token counts and bucket counts read as ints)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _sample(name: str, labels: dict[str, str] | None, value) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape_label(str(v))}"'
                        for k, v in labels.items())
        return f"{name}{{{body}}} {_fmt(float(value))}"
    return f"{name} {_fmt(float(value))}"


# ---------------------------------------------------- cross-host aggregation

_COMMENT = ("# HELP", "# TYPE")


def add_label_to_exposition(text: str, label: str, value: str) -> str:
    """Inject ``label="value"`` into every sample line of a Prometheus text
    page (the router's per-host relabeling: backend registries know nothing
    of the fleet, the router adds ``host=...`` so aggregated series never
    collide).  Comment and blank lines pass through untouched."""
    out: list[str] = []
    esc = _escape_label(value)
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("#"):
            out.append(line)
            continue
        if "{" in s:  # labeled sample: name{...} value — splice in front
            name, _, tail = s.partition("{")
            out.append(f'{name}{{{label}="{esc}",{tail}')
        else:  # bare sample: name value
            name_part, _, rest = s.partition(" ")
            out.append(f'{name_part}{{{label}="{esc}"}} {rest}')
    return "\n".join(out) + "\n"


def merge_expositions(pages: list[str]) -> str:
    """Merge relabeled per-host pages into one valid exposition: the text
    format requires all lines of a metric to form ONE contiguous group
    with a single # HELP/# TYPE header, so samples are regrouped by metric
    family (histogram ``_bucket``/``_sum``/``_count`` children fold into
    their parent) in first-appearance order."""
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    samples: dict[str, list[str]] = {}

    def family(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if base in types:
                    return base
        return sample_name

    for page in pages:
        for line in page.splitlines():
            s = line.strip()
            if not s:
                continue
            if s.startswith(_COMMENT):
                parts = s.split()
                kind, name = parts[1], parts[2]
                (helps if kind == "HELP" else types).setdefault(name, s)
                samples.setdefault(name, [])
            elif not s.startswith("#"):
                name = s.split("{", 1)[0].split(" ", 1)[0]
                samples.setdefault(family(name), []).append(line)
    out: list[str] = []
    for name, lines in samples.items():
        if name in helps:
            out.append(helps[name])
        if name in types:
            out.append(types[name])
        out.extend(lines)
    return "\n".join(out) + "\n"
