"""Step-anatomy profiler + ragged-span bucket economics.

The obs stack measures dispatch WALLS (obs/perf.py roofline attribution,
the PR 14 cost ledger) but nothing decomposes the host side of a
scheduler iteration — and both remaining perf mysteries are host-side:
the spec-verify step costs ~3x a plain step while the verify kernel is
only 1.09x, and the 1B prefill MFU gap is "launch/tail overhead at small
shapes".  This module names every microsecond between two dispatches:

* ``StepAnatomy.seg(name)`` is a nestable context timer.  Entering an
  inner segment PAUSES the outer one (elapsed time is attributed to the
  outer segment first), so segments never overlap and their per-iteration
  sum can never exceed the iteration wall.  The difference is tracked as
  an explicit ``residual`` — the anatomy is conservation-audited like the
  ledger: ``wall == seg_sum + residual`` must reconcile within eps in
  ``scheduler.audit()``.
* ``iter_begin()`` / ``iter_end(cls)`` / ``iter_abort()`` bound one
  scheduler iteration.  ``iter_end`` folds the iteration's record into
  the cumulative totals (and the per-class reservoir for p50/p95);
  ``iter_abort`` DISCARDS the open record — an iteration killed by a
  dispatch fault contributes nothing, so the audit identity survives
  chaos arms by construction rather than by luck.
* Bucket economics for the PR 16 ragged-span family: per
  (pow2 query-token bucket, pow2 page-window) key the profiler counts
  dispatches, real vs padded span tokens (padding-waste ratio), and
  cumulative compile seconds — the pow2 family's padding-vs-compile
  trade becomes a number per bucket instead of a guess.

Always-on by default; ``LMRS_ANATOMY=0`` swaps in ``NULL_ANATOMY``, which
registers NO metrics and no-ops every call — output, wire format, and the
pre-existing metrics shape are byte-identical to a build without this
module.  Overhead when on is a handful of ``time.time()`` calls and dict
adds per iteration; trace spans are only formatted when a tracer is
armed (same ≤2% budget discipline as obs/trace.py).
"""

from __future__ import annotations

import time
from collections import deque

from lmrs_tpu.obs.flight import dump_postmortem
from lmrs_tpu.obs.metrics import MetricsRegistry, log_buckets
from lmrs_tpu.obs.trace import get_tracer
from lmrs_tpu.utils.env import env_bool, env_float, env_int

# the named host segments of one scheduler iteration, in loop order:
#   admit    — fault/heartbeat/sweep bookkeeping + admission & QoS pick
#   plan     — span/operand/page-table build (host-side numpy plumbing)
#   draft    — spec draft+reseed plumbing (seed_history, stale reseeds)
#   dispatch — the jitted device call (compile time lands here, cold keys)
#   fetch    — result transfer (device_get / _timed_get)
#   finish   — emitted-token sweep + perf/ledger/SLO notes + slot finish
#   io       — journal/session delivery (on_result callbacks)
SEGMENTS: tuple[str, ...] = ("admit", "plan", "draft", "dispatch",
                             "fetch", "finish", "io")
_SEG_SET = frozenset(SEGMENTS)

# iteration step classes (the decode_split/serving_latency split axis)
CLASSES: tuple[str, ...] = ("plain", "mixed", "spec", "prefill")

# host-overhead histogram: 1 µs (an idle-ish pass) .. 10 s (a compile)
_HOST_US_BUCKETS = log_buckets(1.0, 1e7, per_decade=3)


def anatomy_enabled() -> bool:
    """The ``LMRS_ANATOMY`` kill switch (default on)."""
    return env_bool("LMRS_ANATOMY", True)


def slow_step_ms() -> float:
    """Slow-step postmortem threshold in ms; 0 disables.  Read per
    iteration (not cached) so tests can arm it without rebuilding the
    engine — same convention as ``perf.slow_step_threshold_s``."""
    return env_float("LMRS_ANATOMY_SLOW_MS", 0.0, lo=0.0)


def reservoir_size() -> int:
    """Per-class percentile reservoir depth (``LMRS_ANATOMY_RESERVOIR``)."""
    return env_int("LMRS_ANATOMY_RESERVOIR", 512, lo=16)


class _Seg:
    """One ``with anatomy.seg(name):`` activation.  Stack-based with
    pause semantics: entering attributes the elapsed slice to the
    enclosing segment, exiting resumes it — re-entrant on the same name
    and exception-safe (an unwind closes every frame on the way out)."""

    __slots__ = ("a", "name")

    def __init__(self, a: "StepAnatomy", name: str):
        self.a = a
        self.name = name

    def __enter__(self):
        a = self.a
        if not a._open:
            return self
        t = a._clock()
        st = a._stack
        if st:
            p = st[-1]
            a._cur[p[0]] += t - p[2]  # pause the enclosing segment
        st.append([self.name, t, t])  # [name, t_enter, t_resume]
        return self

    def __exit__(self, exc_type, exc, tb):
        a = self.a
        st = a._stack
        if not a._open or not st:
            return False
        t = a._clock()
        e = st.pop()
        a._cur[e[0]] += t - e[2]
        if st:
            st[-1][2] = t  # resume the enclosing segment
        if a._tr is not None:
            a._tr.complete("anatomy." + e[0], e[1], t)
        return False


class _NullSeg:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SEG = _NullSeg()


class StepAnatomy:
    """Conservation-audited per-iteration host-segment profiler + ragged
    bucket economics (module docstring).  One instance per scheduler run
    context; NOT thread-safe by design — only the scheduler loop thread
    touches the iteration lifecycle, matching every other per-run
    accumulator in the scheduler."""

    enabled = True

    def __init__(self, registry: MetricsRegistry, *, metrics_cb=None,
                 clock=time.time):
        self._clock = clock
        self._metrics_cb = metrics_cb
        self._tr = None
        # iteration lifecycle state
        self._open = False
        self._stack: list[list] = []
        self._cur: dict[str, float] = {}
        self._t_iter = 0.0
        # cumulative totals (floats keep sign for the audit identity;
        # counter incs are clamped at 0 because Counter refuses decrements)
        self._iters = 0
        self._aborted = 0
        self._wall = 0.0
        self._residual = 0.0
        self._segs = {s: 0.0 for s in SEGMENTS}
        self._host_us = 0.0  # sum of (wall - dispatch - fetch) in µs
        # per-class percentile reservoirs: cls -> deque[(wall, segs tuple)]
        cap = reservoir_size()
        self._res: dict[str, deque] = {c: deque(maxlen=cap) for c in CLASSES}
        self._cls_iters = {c: 0 for c in CLASSES}
        # bucket economics: (tpb, w) -> {dispatches, real, padded, compile_s}
        self._buckets: dict[tuple[int, int], dict] = {}

        c, g, h = (registry.counter, registry.gauge, registry.histogram)
        self._c_iters = c("lmrs_anatomy_iterations_total",
                          "scheduler iterations profiled by the anatomy")
        self._c_aborted = c("lmrs_anatomy_aborted_iterations_total",
                            "iterations discarded mid-flight (fault unwind)")
        self._c_wall = c("lmrs_anatomy_wall_seconds_total",
                         "summed iteration wall time", unit="s")
        self._c_residual = c("lmrs_anatomy_residual_seconds_total",
                             "iteration wall not covered by any segment",
                             unit="s")
        self._c_slow = c("lmrs_anatomy_slow_steps_total",
                         "iterations over LMRS_ANATOMY_SLOW_MS")
        self._seg_c = {
            "admit": c("lmrs_anatomy_admit_seconds_total",
                       "admission/QoS-pick + sweep host time", unit="s"),
            "plan": c("lmrs_anatomy_plan_seconds_total",
                      "span/operand/plan build host time", unit="s"),
            "draft": c("lmrs_anatomy_draft_seconds_total",
                       "spec draft+reseed plumbing host time", unit="s"),
            "dispatch": c("lmrs_anatomy_dispatch_seconds_total",
                          "jitted device dispatch call time", unit="s"),
            "fetch": c("lmrs_anatomy_fetch_seconds_total",
                       "device result fetch time", unit="s"),
            "finish": c("lmrs_anatomy_finish_seconds_total",
                        "finish sweep + ledger/SLO note host time",
                        unit="s"),
            "io": c("lmrs_anatomy_io_seconds_total",
                    "journal/session delivery host time", unit="s"),
        }
        self._h_host_us = h("lmrs_anatomy_host_us_step", _HOST_US_BUCKETS,
                            "per-iteration host overhead (wall - dispatch "
                            "- fetch)", unit="us")
        self._c_b_disp = c("lmrs_rpa_bucket_dispatches_total",
                           "ragged-span dispatches across all buckets")
        self._c_b_real = c("lmrs_rpa_bucket_real_tokens_total",
                           "real span tokens dispatched (pre-padding)")
        self._c_b_pad = c("lmrs_rpa_bucket_padded_tokens_total",
                          "padding tokens added by pow2 bucketing")
        self._c_b_compile = c("lmrs_rpa_bucket_compile_seconds_total",
                              "cold-key dispatch wall (compile) time",
                              unit="s")

    # ------------------------------------------------------------- lifecycle

    def iter_begin(self) -> None:
        if self._open:  # defensive: a lost iter_end must not leak forever
            self.iter_abort()
        self._tr = get_tracer()
        self._stack = []
        self._cur = {s: 0.0 for s in SEGMENTS}
        self._t_iter = self._clock()
        self._open = True

    def seg(self, name: str):
        """Context timer for one named segment (see ``SEGMENTS``)."""
        if name not in _SEG_SET:
            raise ValueError(f"unknown anatomy segment {name!r} "
                             f"(want one of {SEGMENTS})")
        return _Seg(self, name)

    def iter_end(self, cls: str) -> None:
        """Fold the open iteration into the totals under step class
        ``cls`` — the only place cumulative state advances, so a caller
        that aborts instead contributes exactly nothing."""
        if not self._open:
            return
        # defensively close dangling frames (a seg left open by a caller
        # bug still participates in conservation rather than vanishing)
        t = self._clock()
        while self._stack:
            e = self._stack.pop()
            self._cur[e[0]] += t - e[2]
            if self._stack:
                self._stack[-1][2] = t
        wall = t - self._t_iter
        seg_sum = sum(self._cur.values())
        residual = wall - seg_sum
        self._open = False

        self._iters += 1
        self._wall += wall
        self._residual += residual
        self._c_iters.inc()
        self._c_wall.inc(max(wall, 0.0))
        self._c_residual.inc(max(residual, 0.0))
        host_us = max(wall - self._cur["dispatch"] - self._cur["fetch"],
                      0.0) * 1e6
        self._host_us += host_us
        self._h_host_us.observe(host_us)
        for s in SEGMENTS:
            self._segs[s] += self._cur[s]
            self._seg_c[s].inc(max(self._cur[s], 0.0))
        if cls not in self._res:  # unknown class: fold under "plain"
            cls = "plain"
        self._cls_iters[cls] += 1
        self._res[cls].append(
            (wall, tuple(self._cur[s] for s in SEGMENTS), residual))

        thresh = slow_step_ms()
        if thresh > 0.0 and wall * 1e3 > thresh:
            self._c_slow.inc()
            dump_postmortem("slow_step", metrics=(
                self._metrics_cb() if self._metrics_cb else None),
                extra={"anatomy": {
                    "class": cls,
                    "wall_ms": round(wall * 1e3, 3),
                    "threshold_ms": thresh,
                    "segments_ms": {s: round(self._cur[s] * 1e3, 3)
                                    for s in SEGMENTS},
                    "residual_ms": round(residual * 1e3, 3)}})

    def iter_abort(self) -> None:
        """Discard the open iteration (fault unwind / stop request).
        Idempotent — the scheduler calls it from ``finally``."""
        if not self._open:
            return
        self._open = False
        self._stack = []
        self._aborted += 1
        self._c_aborted.inc()

    def iter_discard(self) -> None:
        """Close the open iteration WITHOUT counting it anywhere — the
        run-exit pass (the loop's "all work done" break) is bookkeeping,
        not a step, and must pollute neither the totals nor the aborted
        count chaos arms assert on."""
        self._open = False
        self._stack = []

    # ------------------------------------------------------ bucket economics

    def note_bucket(self, tpb: int, w: int, real_tokens: int) -> None:
        """One ragged-span dispatch on bucket (``tpb`` pow2 query tokens,
        ``w`` pow2 page window) that carried ``real_tokens`` real span
        tokens — the rest of the bucket is padding."""
        rec = self._buckets.setdefault((int(tpb), int(w)), {
            "dispatches": 0, "real": 0, "padded": 0, "compile_s": 0.0})
        pad = max(int(tpb) - int(real_tokens), 0)
        rec["dispatches"] += 1
        rec["real"] += int(real_tokens)
        rec["padded"] += pad
        self._c_b_disp.inc()
        self._c_b_real.inc(max(int(real_tokens), 0))
        self._c_b_pad.inc(pad)

    def note_compile(self, tpb: int, w: int, seconds: float) -> None:
        """Cold-key dispatch wall for a bucket — the compile cost the pow2
        family pays to keep the bucket count finite."""
        rec = self._buckets.setdefault((int(tpb), int(w)), {
            "dispatches": 0, "real": 0, "padded": 0, "compile_s": 0.0})
        rec["compile_s"] += max(float(seconds), 0.0)
        self._c_b_compile.inc(max(float(seconds), 0.0))

    # --------------------------------------------------------------- reading

    def snapshot(self) -> dict:
        """Window anchor for ``report(before=...)`` (bench/serving_latency
        delta their measurement window off this, same convention as the
        scheduler's raw ``metrics`` snapshot)."""
        return {"iters": self._iters, "aborted": self._aborted,
                "wall": self._wall, "residual": self._residual,
                "host_us": self._host_us,
                "segs": dict(self._segs)}

    def audit(self) -> list[str]:
        """Conservation check over the CUMULATIVE totals (safe to call
        mid-iteration: totals only advance at ``iter_end``).  Violations
        are returned as strings for ``scheduler.audit()`` to aggregate."""
        violations: list[str] = []
        seg_sum = sum(self._segs.values())
        eps = 1e-6 * max(1, self._iters) + 1e-9
        drift = abs(self._wall - (seg_sum + self._residual))
        if drift > eps:
            violations.append(
                f"anatomy conservation: |wall - (segments + residual)| = "
                f"{drift:.3e}s over {self._iters} iterations (eps {eps:.3e})")
        if self._residual < -eps:
            violations.append(
                f"anatomy residual is negative: {self._residual:.3e}s "
                f"(segments overlap — pause bookkeeping broken)")
        for s, v in self._segs.items():
            if v < -eps:
                violations.append(f"anatomy segment {s} went negative: {v}")
        for key, rec in self._buckets.items():
            if rec["real"] + rec["padded"] != rec["dispatches"] * key[0]:
                violations.append(
                    f"anatomy bucket {key[0]}x{key[1]}: real+padded "
                    f"({rec['real']}+{rec['padded']}) != dispatches*bucket "
                    f"({rec['dispatches']}*{key[0]})")
        return violations

    def report(self, before: dict | None = None, *,
               rtt: tuple | None = None) -> dict:
        """The ``anatomy`` block (``metrics_report()`` / ``/v1/anatomy`` /
        bench detail).  Top-level totals window off ``before`` (a
        ``snapshot()``); per-class percentiles and bucket economics stay
        cumulative, like the rpa block's compile shapes.  ``rtt`` is
        ``(rtt_s | None, age_s | None)`` from ``DispatchAttribution.
        rtt_sample()`` — a STALE sample is reported but never subtracted
        from the fetch split (the satellite-3 guard)."""
        b = before or {}
        iters = self._iters - b.get("iters", 0)
        wall = self._wall - b.get("wall", 0.0)
        residual = self._residual - b.get("residual", 0.0)
        host_us = self._host_us - b.get("host_us", 0.0)
        b_segs = b.get("segs", {})
        segs_ms = {s: round((self._segs[s] - b_segs.get(s, 0.0)) * 1e3, 3)
                   for s in SEGMENTS}

        classes: dict[str, dict] = {}
        for cls in CLASSES:
            rs = self._res[cls]
            if not rs:
                continue
            walls = sorted(r[0] for r in rs)
            p50: dict[str, float] = {}
            p95: dict[str, float] = {}
            for i, s in enumerate(SEGMENTS):
                vals = sorted(r[1][i] for r in rs)
                p50[s] = round(_pct(vals, 50) * 1e6, 1)
                p95[s] = round(_pct(vals, 95) * 1e6, 1)
            p50["wall"] = round(_pct(walls, 50) * 1e6, 1)
            p95["wall"] = round(_pct(walls, 95) * 1e6, 1)
            classes[cls] = {"iterations": self._cls_iters[cls],
                            "p50_us": p50, "p95_us": p95}

        buckets: dict[str, dict] = {}
        tot_real = tot_pad = 0
        for (tpb, w), rec in sorted(self._buckets.items()):
            span = rec["real"] + rec["padded"]
            buckets[f"{tpb}x{w}"] = {
                "dispatches": rec["dispatches"],
                "real_tokens": rec["real"],
                "padded_tokens": rec["padded"],
                "pad_waste": round(rec["padded"] / span, 4) if span else 0.0,
                "compile_ms": round(rec["compile_s"] * 1e3, 1),
            }
            tot_real += rec["real"]
            tot_pad += rec["padded"]

        rtt_s, rtt_age = (rtt if rtt is not None else (None, None))
        out = {
            "object": "anatomy",
            "enabled": True,
            "iterations": iters,
            "aborted_iterations": self._aborted - b.get("aborted", 0),
            "wall_ms": round(wall * 1e3, 3),
            "residual_ms": round(residual * 1e3, 3),
            "segments_ms": segs_ms,
            "host_overhead_us_step": (round(host_us / iters, 1)
                                      if iters > 0 else None),
            "classes": classes,
            "buckets": buckets,
            "rpa_pad_waste_ratio": (
                round(tot_pad / (tot_real + tot_pad), 4)
                if (tot_real + tot_pad) else None),
        }
        if rtt_s is not None:
            stale = rtt_age is None or rtt_age > 2.0 * rtt_resample_s()
            out["rtt_ms"] = round(rtt_s * 1e3, 3)
            out["rtt_stale"] = stale
            if not stale and iters > 0:
                # pure device-wait estimate: fetch minus one host RTT per
                # iteration, floored at 0 — only derived from a FRESH rtt
                fetch_s = (self._segs["fetch"]
                           - b_segs.get("fetch", 0.0))
                out["device_wait_us_step"] = round(
                    max(fetch_s / iters - rtt_s, 0.0) * 1e6, 1)
        return out


def merge_anatomy(docs: list[dict]) -> dict:
    """Merge per-engine ``anatomy`` documents into one fleet view (the
    router's ``GET /v1/anatomy`` and the replicated engine's metrics
    block).  Additive totals sum exactly (iterations, walls, segments,
    bucket token counts — the same one-merge-rule discipline as
    ``merge_usage``); per-class percentiles cannot be merged exactly, so
    they are iteration-weighted means — close under balanced load and
    explicitly an estimate, which is why per-host raw docs travel next to
    the merged view on the router surface."""
    live = [d for d in docs if d and d.get("enabled")]
    if not live:
        return {"object": "anatomy", "enabled": False}
    iters = sum(int(d.get("iterations") or 0) for d in live)
    segs_ms = {s: round(sum(float((d.get("segments_ms") or {}).get(s, 0.0))
                            for d in live), 3) for s in SEGMENTS}
    hosts_us = [(float(d["host_overhead_us_step"]),
                 int(d.get("iterations") or 0)) for d in live
                if d.get("host_overhead_us_step") is not None]
    w_iters = sum(n for _, n in hosts_us)
    classes: dict[str, dict] = {}
    for cls in CLASSES:
        per = [(d["classes"][cls], int(d["classes"][cls]["iterations"]))
               for d in live if cls in (d.get("classes") or {})]
        n_cls = sum(n for _, n in per)
        if not n_cls:
            continue
        keys = (*SEGMENTS, "wall")
        classes[cls] = {
            "iterations": n_cls,
            "p50_us": {k: round(sum(c["p50_us"].get(k, 0.0) * n
                                    for c, n in per) / n_cls, 1)
                       for k in keys},
            "p95_us": {k: round(sum(c["p95_us"].get(k, 0.0) * n
                                    for c, n in per) / n_cls, 1)
                       for k in keys},
        }
    buckets: dict[str, dict] = {}
    tot_real = tot_pad = 0
    for d in live:
        for key, rec in (d.get("buckets") or {}).items():
            m = buckets.setdefault(key, {
                "dispatches": 0, "real_tokens": 0, "padded_tokens": 0,
                "pad_waste": 0.0, "compile_ms": 0.0})
            m["dispatches"] += int(rec.get("dispatches") or 0)
            m["real_tokens"] += int(rec.get("real_tokens") or 0)
            m["padded_tokens"] += int(rec.get("padded_tokens") or 0)
            m["compile_ms"] = round(
                m["compile_ms"] + float(rec.get("compile_ms") or 0.0), 1)
    for m in buckets.values():
        span = m["real_tokens"] + m["padded_tokens"]
        m["pad_waste"] = round(m["padded_tokens"] / span, 4) if span else 0.0
        tot_real += m["real_tokens"]
        tot_pad += m["padded_tokens"]
    return {
        "object": "anatomy",
        "enabled": True,
        "iterations": iters,
        "aborted_iterations": sum(int(d.get("aborted_iterations") or 0)
                                  for d in live),
        "wall_ms": round(sum(float(d.get("wall_ms") or 0.0)
                             for d in live), 3),
        "residual_ms": round(sum(float(d.get("residual_ms") or 0.0)
                                 for d in live), 3),
        "segments_ms": segs_ms,
        "host_overhead_us_step": (
            round(sum(v * n for v, n in hosts_us) / w_iters, 1)
            if w_iters else None),
        "classes": classes,
        "buckets": dict(sorted(buckets.items())),
        "rpa_pad_waste_ratio": (
            round(tot_pad / (tot_real + tot_pad), 4)
            if (tot_real + tot_pad) else None),
    }


def rtt_resample_s() -> float:
    """RTT re-sample cadence (``LMRS_RTT_RESAMPLE_S``, satellite 3) — also
    the staleness horizon the anatomy report guards with (2x cadence)."""
    return env_float("LMRS_RTT_RESAMPLE_S", 300.0, lo=1.0)


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (no numpy on the
    report path — /v1/anatomy serves from the HTTP thread)."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class NullAnatomy:
    """The ``LMRS_ANATOMY=0`` object: registers no metrics, every call is
    a no-op, ``seg`` hands back one shared null context — the scheduler
    keeps one unconditional code path while the kill switch restores the
    exact pre-anatomy metrics shape and wire format."""

    enabled = False

    def iter_begin(self) -> None:
        pass

    def seg(self, name: str):
        return _NULL_SEG

    def iter_end(self, cls: str) -> None:
        pass

    def iter_abort(self) -> None:
        pass

    def iter_discard(self) -> None:
        pass

    def note_bucket(self, tpb: int, w: int, real_tokens: int) -> None:
        pass

    def note_compile(self, tpb: int, w: int, seconds: float) -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    def audit(self) -> list[str]:
        return []

    def report(self, before: dict | None = None, *,
               rtt: tuple | None = None) -> dict:
        return {"object": "anatomy", "enabled": False}


NULL_ANATOMY = NullAnatomy()


def maybe_anatomy(registry: MetricsRegistry, *, metrics_cb=None,
                  clock=time.time):
    """``StepAnatomy`` when armed, the shared ``NULL_ANATOMY`` otherwise
    (so the disabled path allocates nothing per engine)."""
    if not anatomy_enabled():
        return NULL_ANATOMY
    return StepAnatomy(registry, metrics_cb=metrics_cb, clock=clock)
