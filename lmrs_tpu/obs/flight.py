"""Flight recorder: crash-adjacent postmortem dumps.

When the serving path hits one of the "what just happened" events — a
dispatch fault killing a scheduler run, a deadline-expiry storm, an
invariant-auditor failure — the in-memory trace ring still holds the
last N spans and the metric registry the counters that led up to it.
``dump_postmortem`` freezes both into one atomically-written JSON file
so the evidence survives the process (the same motivation as the jobs
WAL, applied to telemetry).

Disabled unless ``LMRS_POSTMORTEM_DIR`` points at a directory (the chaos
suite arms it per scenario); dumps are throttled per reason
(``LMRS_POSTMORTEM_MIN_S``, default 5 s) so a fault storm cannot turn
the recorder itself into a disk-filling failure mode.  Never raises —
a postmortem writer that can crash the process it is documenting would
be worse than no recorder.

Schema (``validate_postmortem_file``)::

    {"schema": "lmrs-postmortem-v1", "reason": str, "ts": float,
     "host": str, "pid": int, "spans": [trace events...],
     "metrics": {...}, "extra": {...}}
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from pathlib import Path

from lmrs_tpu.obs.trace import get_tracer, validate_trace_events
from lmrs_tpu.utils.env import env_float, env_str

logger = logging.getLogger("lmrs.obs.flight")

POSTMORTEM_SCHEMA = "lmrs-postmortem-v1"
DEFAULT_LAST_N_SPANS = 2048

_throttle_lock = threading.Lock()
_last_dump: dict[str, float] = {}  # reason -> monotonic time of last dump


def postmortem_dir() -> Path | None:
    """The armed dump directory, or None when the recorder is disabled."""
    d = env_str("LMRS_POSTMORTEM_DIR")
    return Path(d) if d else None


def _min_interval_s() -> float:
    # the shared parser owns the hard cases: "" means the documented 5 s
    # default, and a NaN can never reach the throttle comparison (NaN
    # compares False against the elapsed time, i.e. an unthrottled storm)
    return env_float("LMRS_POSTMORTEM_MIN_S", 5.0, lo=0.0)


def dump_postmortem(reason: str, *, metrics: dict | None = None,
                    extra: dict | None = None,
                    last_n: int = DEFAULT_LAST_N_SPANS,
                    out_dir: str | Path | None = None) -> Path | None:
    """Write one postmortem file; returns its path, or None when the
    recorder is disabled, throttled, or the write failed (logged).  The
    write is atomic (tmp + rename) so a reader — or a second crash — can
    never observe a torn dump."""
    try:
        d = Path(out_dir) if out_dir is not None else postmortem_dir()
        if d is None:
            return None
        now_mono = time.monotonic()
        with _throttle_lock:
            last = _last_dump.get(reason)
            if last is not None and now_mono - last < _min_interval_s():
                return None
            _last_dump[reason] = now_mono
        tr = get_tracer()
        spans = tr.events()[-last_n:] if tr is not None else []
        doc = {
            "schema": POSTMORTEM_SCHEMA,
            "reason": reason,
            "ts": time.time(),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "spans": spans,
            "metrics": dict(metrics or {}),
            "extra": dict(extra or {}),
        }
        d.mkdir(parents=True, exist_ok=True)
        name = f"postmortem-{reason}-{int(time.time() * 1e3)}-{os.getpid()}"
        path = d / f"{name}.json"
        tmp = d / f"{name}.tmp"
        tmp.write_text(json.dumps(doc), encoding="utf-8")
        os.replace(tmp, path)
        logger.warning("flight recorder: %s postmortem written to %s "
                       "(%d spans)", reason, path, len(spans))
        return path
    except Exception:  # noqa: BLE001 - the recorder must never crash its host
        logger.warning("flight recorder dump failed", exc_info=True)
        return None


def validate_postmortem_file(path: str | Path) -> dict:
    """Load + schema-check one postmortem dump (the chaos gate's check).
    Raises ValueError on any violation; returns the document."""
    doc = json.loads(Path(path).read_text(encoding="utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("postmortem is not a JSON object")
    if doc.get("schema") != POSTMORTEM_SCHEMA:
        raise ValueError(f"unknown postmortem schema {doc.get('schema')!r}")
    for key, typ in (("reason", str), ("ts", (int, float)), ("host", str),
                     ("pid", int), ("spans", list), ("metrics", dict),
                     ("extra", dict)):
        if not isinstance(doc.get(key), typ):
            raise ValueError(f"postmortem field {key!r} missing or wrong "
                             f"type: {doc.get(key)!r}")
    if not doc["reason"]:
        raise ValueError("postmortem reason is empty")
    if doc["spans"]:  # an empty ring (tracing off) is a valid dump
        validate_trace_events(doc["spans"])
    return doc
