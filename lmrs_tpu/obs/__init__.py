"""Unified telemetry: lifecycle tracing + metric registry + exposition,
cross-host trace stitching, live perf attribution, flight recorder.

Dependency-free (stdlib + numpy).  See docs/OBSERVABILITY.md for the
metric catalog, the stitching/skew-alignment method, and how to open an
exported trace in Perfetto.
"""

from lmrs_tpu.obs.anatomy import (
    CLASSES,
    NULL_ANATOMY,
    SEGMENTS,
    NullAnatomy,
    StepAnatomy,
    anatomy_enabled,
    maybe_anatomy,
    merge_anatomy,
    rtt_resample_s,
    slow_step_ms,
)
from lmrs_tpu.obs.flight import (
    POSTMORTEM_SCHEMA,
    dump_postmortem,
    postmortem_dir,
    validate_postmortem_file,
)
from lmrs_tpu.obs.ledger import DEFAULT_TENANT, CostLedger, merge_usage
from lmrs_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    MS_LATENCY_BUCKETS,
    POW2_TOKEN_BUCKETS,
    RATIO_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    add_label_to_exposition,
    log_buckets,
    merge_expositions,
)
from lmrs_tpu.obs.perf import (
    DispatchAttribution,
    profile_capture_active,
    start_profile_capture,
)
from lmrs_tpu.obs.slo import (
    DEFAULT_SPECS,
    SLOEngine,
    SLOSpec,
    specs_from_env,
    state_rank,
    worst_state,
)
from lmrs_tpu.obs.trace import (
    PID_ENGINE,
    PID_PIPELINE,
    PID_STITCH,
    TID_SCHED,
    TRACE_TRACK_PREFIX,
    Tracer,
    disable_tracing,
    enable_tracing,
    export_current,
    get_tracer,
    new_trace_id,
    req_tid,
    stitch_traces,
    stitched_chains,
    validate_trace_events,
    validate_trace_file,
)

__all__ = [
    "CLASSES", "NULL_ANATOMY", "SEGMENTS", "NullAnatomy", "StepAnatomy",
    "anatomy_enabled", "maybe_anatomy", "merge_anatomy", "rtt_resample_s",
    "slow_step_ms",
    "DEFAULT_LATENCY_BUCKETS_S", "MS_LATENCY_BUCKETS", "POW2_TOKEN_BUCKETS",
    "RATIO_BUCKETS",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "add_label_to_exposition", "log_buckets", "merge_expositions",
    "DispatchAttribution", "profile_capture_active", "start_profile_capture",
    "POSTMORTEM_SCHEMA", "dump_postmortem", "postmortem_dir",
    "validate_postmortem_file",
    "DEFAULT_TENANT", "CostLedger", "merge_usage",
    "DEFAULT_SPECS", "SLOEngine", "SLOSpec", "specs_from_env",
    "state_rank", "worst_state",
    "PID_ENGINE", "PID_PIPELINE", "PID_STITCH", "TID_SCHED",
    "TRACE_TRACK_PREFIX", "Tracer",
    "disable_tracing", "enable_tracing", "export_current", "get_tracer",
    "new_trace_id", "req_tid", "stitch_traces", "stitched_chains",
    "validate_trace_events", "validate_trace_file",
]
