"""Unified telemetry: lifecycle tracing + metric registry + exposition.

Dependency-free (stdlib + numpy).  See docs/OBSERVABILITY.md for the
metric catalog and how to open an exported trace in Perfetto.
"""

from lmrs_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    POW2_TOKEN_BUCKETS,
    RATIO_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    add_label_to_exposition,
    log_buckets,
    merge_expositions,
)
from lmrs_tpu.obs.trace import (
    PID_ENGINE,
    PID_PIPELINE,
    TID_SCHED,
    Tracer,
    disable_tracing,
    enable_tracing,
    export_current,
    get_tracer,
    req_tid,
    validate_trace_events,
    validate_trace_file,
)

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_S", "POW2_TOKEN_BUCKETS", "RATIO_BUCKETS",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "add_label_to_exposition", "log_buckets", "merge_expositions",
    "PID_ENGINE", "PID_PIPELINE", "TID_SCHED", "Tracer",
    "disable_tracing", "enable_tracing", "export_current", "get_tracer",
    "req_tid",
    "validate_trace_events", "validate_trace_file",
]
