"""Fleet SLO engine: declarative objectives -> burn-rate health states.

The serving stack's health signals were binary (breaker open/closed,
wedged 503) while its promises are statistical: TTFT p95, steady-state
block-gap p95, deadline-miss rate, error/wedge rate, goodput tokens/s.
``SLOEngine`` evaluates declarative :class:`SLOSpec` objectives over TWO
sliding windows — a fast window that reacts and a slow window that
confirms — into one graded state ``ok | warn | critical`` per spec and
for the host:

* **burn rate** = observed / target for latency percentiles and failure
  rates (target / observed for the goodput floor) — 1.0 means the
  objective is being consumed exactly at its budget;
* a spec breaches only when BOTH windows burn (the classic
  multi-window rule: the fast window catches it quickly, the slow
  window keeps a single bad second from paging);
* the host state is the worst spec state, with **flap damping**:
  upgrades (toward worse) apply immediately, downgrades must hold for
  ``hold_s`` — a host oscillating across the threshold reads as
  degraded, not as a strobe;
* a transition INTO ``critical`` fires a flight-recorder postmortem
  (reason ``"slo"``, obs/flight.py) so the window that breached is
  captured, not inferred later.

Consumers: ``/healthz`` exports the evaluation (the router's SLO-aware
placement penalty reads it — serving/router.py), ``lmrs_slo_*`` metrics
ride the engine registry, and ``metrics_report()``/bench detail carry
the same doc.  ``LMRS_SLO=0`` disables the engine entirely (every feed
is a no-op, the report pins ``ok``); router-side consumption has its own
``LMRS_SLO_ROUTE`` kill switch.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass

from lmrs_tpu.utils.env import env_bool, env_float, env_str

logger = logging.getLogger("lmrs.obs.slo")

STATES = ("ok", "warn", "critical")
_STATE_RANK = {s: i for i, s in enumerate(STATES)}


def state_rank(state: str | None) -> int:
    """Numeric severity of a state string; unknown/absent reads as ok
    (0) — a host that publishes nothing must not be penalized for it."""
    return _STATE_RANK.get(state or "ok", 0)


def worst_state(states) -> str:
    """The worst of an iterable of state strings (``ok`` when empty)."""
    best = 0
    for s in states:
        best = max(best, state_rank(s))
    return STATES[best]


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    ``kind``:
      * ``latency_p95`` — ``target`` is a p95 ceiling in ms over the
        spec's sample series;
      * ``rate`` — ``target`` is a failure-fraction ceiling over the
        window's finished requests;
      * ``throughput_min`` — ``target`` is a tokens/s floor (0 disables
        the spec: a floor only means something for a sized deployment).
    """

    name: str
    kind: str
    target: float


DEFAULT_SPECS: tuple[SLOSpec, ...] = (
    SLOSpec("ttft_p95_ms", "latency_p95", 2000.0),
    SLOSpec("block_gap_p95_ms", "latency_p95", 1500.0),
    SLOSpec("deadline_miss_rate", "rate", 0.05),
    SLOSpec("error_rate", "rate", 0.05),
    SLOSpec("goodput_tok_s", "throughput_min", 0.0),
)


def specs_from_env() -> tuple[SLOSpec, ...]:
    """DEFAULT_SPECS with ``LMRS_SLO_SPEC`` JSON overrides applied —
    ``{"ttft_p95_ms": 150, "goodput_tok_s": 40}`` retargets by spec
    name.  Unknown names and non-finite values warn and are ignored (the
    env contract: bad values keep defaults, never crash serving)."""
    raw = env_str("LMRS_SLO_SPEC")
    specs = {s.name: s for s in DEFAULT_SPECS}
    if raw:
        try:
            overrides = json.loads(raw)
            if not isinstance(overrides, dict):
                raise ValueError("want a JSON object of name -> target")
            import math

            for name, target in overrides.items():
                # per-item: one bad value must not abort the loop with
                # earlier overrides half-applied (warn-and-ignore, like
                # unknown names)
                try:
                    t = float(target)
                except (ValueError, TypeError):
                    t = float("nan")
                if name not in specs or not math.isfinite(t):
                    logger.warning("LMRS_SLO_SPEC: ignoring %r=%r "
                                   "(unknown spec or bad target)",
                                   name, target)
                    continue
                specs[name] = SLOSpec(name, specs[name].kind, t)
        except (ValueError, TypeError) as e:
            logger.warning("LMRS_SLO_SPEC unparseable (%s); using "
                           "defaults", e)
    return tuple(specs.values())


class SLOEngine:
    """Sliding-window evaluator over the serving stream's own samples.

    Fed from the measurement sites the metrics already ride (TTFT
    samples, block-gap samples, finished results); evaluation is pulled
    by the report surfaces and throttled-pushed from ``note_result`` so
    a critical breach fires its postmortem near the breach, not at the
    next scrape.  ``clock`` is injectable (tests drive window decay and
    damping deterministically)."""

    def __init__(self, registry=None, specs: tuple[SLOSpec, ...] | None = None,
                 fast_s: float | None = None, slow_s: float | None = None,
                 hold_s: float | None = None, critical_burn: float = 2.0,
                 min_events: int = 4, clock=time.monotonic,
                 enabled: bool | None = None, metrics_cb=None):
        self.enabled = (env_bool("LMRS_SLO", True) if enabled is None
                        else bool(enabled))
        self.specs = specs if specs is not None else specs_from_env()
        self.fast_s = (env_float("LMRS_SLO_FAST_S", 60.0, lo=1.0)
                       if fast_s is None else float(fast_s))
        self.slow_s = (env_float("LMRS_SLO_SLOW_S", 600.0, lo=1.0)
                       if slow_s is None else float(slow_s))
        self.slow_s = max(self.slow_s, self.fast_s)
        # downgrade dwell: a state must hold this long after its last
        # trigger before it may relax (flap damping)
        self.hold_s = self.fast_s if hold_s is None else float(hold_s)
        self.critical_burn = float(critical_burn)
        self.min_events = int(min_events)
        self.clock = clock
        self._metrics_cb = metrics_cb  # postmortem metrics snapshot
        self._lock = threading.Lock()
        # serializes whole evaluations so two concurrent pulls can't
        # interleave their state-machine publishes; the sample lock
        # (self._lock) is only ever taken INSIDE it, never around it —
        # the scheduler's feed path (observe_*/note_result appends) must
        # never wait behind a health probe's window sort
        self._eval_lock = threading.Lock()
        # sample series, (t, value) pairs trimmed to the slow window
        self._ttft: deque = deque()    # guarded-by: _lock
        self._gaps: deque = deque()    # guarded-by: _lock
        # (t, miss, err, goodput_tokens) per finished request
        self._events: deque = deque()  # guarded-by: _lock
        self._state = "ok"             # guarded-by: _lock
        self._state_since = clock()    # guarded-by: _lock
        self._last_eval = 0.0          # guarded-by: _lock
        # guarded-by: _lock
        self._last_doc: dict = {"enabled": self.enabled, "state": "ok",
                                "raw_state": "ok", "specs": {}}
        self._g_state = self._g_warn = self._g_crit = None
        self._c_evals = self._c_crit = None
        # no registration when disabled: the kill switch promises NO
        # lmrs_slo_* series, not series pinned at ok (CostLedger rule)
        if registry is not None and self.enabled:
            g, c = registry.gauge, registry.counter
            self._g_state = g("lmrs_slo_state",
                              "host SLO burn-rate state "
                              "(0=ok, 1=warn, 2=critical)")
            self._g_warn = g("lmrs_slo_specs_warn",
                             "SLO specs currently in warn")
            self._g_crit = g("lmrs_slo_specs_critical",
                             "SLO specs currently in critical")
            self._c_evals = c("lmrs_slo_evaluations_total",
                              "SLO window evaluations performed")
            self._c_crit = c("lmrs_slo_critical_transitions_total",
                             "transitions into the critical state "
                             "(each fires an 'slo' postmortem)")

    # --------------------------------------------------------------- feeds

    def observe_ttft(self, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ttft.append((self.clock(), seconds * 1e3))

    def observe_gap(self, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gaps.append((self.clock(), seconds * 1e3))

    def note_result(self, finish_reason: str, tokens: int = 0,
                    error: str | None = None) -> None:
        """One finished request: deadline outcomes count against the
        miss-rate spec, errors/wedges against the error-rate spec, and
        usable completion tokens toward the goodput floor."""
        if not self.enabled:
            return
        miss = finish_reason in ("deadline", "shed")
        err = error is not None or finish_reason in ("error", "wedged")
        goodput = 0 if (miss or err) else max(0, int(tokens))
        with self._lock:
            now = self.clock()
            self._events.append((now, miss, err, goodput))
            # throttled in-line evaluation: a critical breach must fire
            # its postmortem near the breach, not at the next scrape
            due = now - self._last_eval >= max(1.0, self.fast_s / 8.0)
        if due:
            self._fire_postmortem(self._evaluate(now))

    # ---------------------------------------------------------- evaluation

    def _trim_locked(self, now: float) -> None:  # holds-lock: _lock
        """Caller holds self._lock."""
        horizon = now - self.slow_s
        for series in (self._ttft, self._gaps, self._events):
            while series and series[0][0] < horizon:
                series.popleft()

    @staticmethod
    def _p95(values: list[float]) -> float:
        if not values:
            return 0.0
        vs = sorted(values)
        return vs[min(len(vs) - 1, int(round(0.95 * (len(vs) - 1))))]

    @staticmethod
    def _window(series, now: float, span: float) -> list:
        return [row for row in series if row[0] >= now - span]

    def _spec_burn(self, spec: SLOSpec, snap: dict, now: float,
                   span: float) -> tuple:
        """(burn, observed) for one spec over one window of ``snap`` (a
        sample snapshot taken under the lock — the math runs outside
        it).  No data (or a volume below ``min_events`` — for every
        kind) burns 0 — an idle host is a healthy host, and one bad
        request out of one is a sample, not a rate."""
        if spec.kind == "latency_p95":
            series = (snap["ttft"] if spec.name.startswith("ttft")
                      else snap["gaps"])
            vals = [v for _, v in self._window(series, now, span)]
            if len(vals) < self.min_events or spec.target <= 0:
                return 0.0, 0.0
            if len(vals) < 20:
                # below 1/(1-0.95) samples the p95 order statistic IS the
                # max, so one cold-compile/GC outlier would drive the host
                # critical at startup — drop the single worst sample until
                # the window has the volume to vote it in (a genuinely
                # degraded host's samples are ALL slow, so it still burns)
                vals.remove(max(vals))
                if not vals:
                    return 0.0, 0.0
            obs = self._p95(vals)
            return obs / spec.target, obs
        events = self._window(snap["events"], now, span)
        if spec.kind == "rate":
            if len(events) < self.min_events or spec.target <= 0:
                return 0.0, 0.0
            idx = 1 if spec.name.startswith("deadline") else 2
            obs = sum(1 for e in events if e[idx]) / len(events)
            return obs / spec.target, obs
        # throughput_min: tokens/s over the TRAFFIC span, not the fixed
        # window — a freshly-started host (4 full-speed requests, 5 s of
        # life) or a bursty-but-healthy one must not read as below the
        # floor just because the 60 s window is mostly empty; target
        # 0 = off
        if spec.target <= 0 or len(events) < self.min_events:
            return 0.0, 0.0
        span_eff = max(min(span, now - events[0][0]), 1.0)
        obs = sum(e[3] for e in events) / span_eff
        return spec.target / max(obs, 1e-9), obs

    def _evaluate(self, now: float) -> dict | None:
        """One full evaluation: snapshot the sample series under the
        lock, run the window math OUTSIDE it (scans + p95 sorts over the
        slow window are O(n log n) — every /healthz probe pulls this,
        and the scheduler's feed path must never wait behind it), then
        publish the state transition under the lock again.  Whole
        evaluations serialize on ``_eval_lock`` so two concurrent pulls
        can't interleave their publishes.  Returns the postmortem
        payload when this evaluation transitioned INTO critical (the
        caller dumps it — the flight recorder writes files), else
        None."""
        with self._eval_lock:
            with self._lock:
                self._trim_locked(now)
                self._last_eval = now
                snap = {"ttft": list(self._ttft), "gaps": list(self._gaps),
                        "events": list(self._events)}
            spec_docs: dict[str, dict] = {}
            n_warn = n_crit = 0
            for spec in self.specs:
                burn_f, obs_f = self._spec_burn(spec, snap, now, self.fast_s)
                burn_s, obs_s = self._spec_burn(spec, snap, now, self.slow_s)
                eff = min(burn_f, burn_s)  # both windows must burn
                if eff >= self.critical_burn:
                    state = "critical"
                    n_crit += 1
                elif eff >= 1.0:
                    state = "warn"
                    n_warn += 1
                else:
                    state = "ok"
                spec_docs[spec.name] = {
                    "kind": spec.kind, "target": spec.target, "state": state,
                    "burn_fast": round(burn_f, 3),
                    "burn_slow": round(burn_s, 3),
                    "observed_fast": round(obs_f, 3),
                    "observed_slow": round(obs_s, 3),
                }
            raw = worst_state(d["state"] for d in spec_docs.values())
            with self._lock:
                prev = self._state
                if state_rank(raw) >= state_rank(prev):
                    # upgrades (and re-triggers at the same level) stamp
                    # the dwell clock: damping measures time since the
                    # last trigger
                    if state_rank(raw) > state_rank(prev) or raw != "ok":
                        self._state_since = now
                    self._state = raw
                elif now - self._state_since >= self.hold_s:
                    self._state = raw
                    self._state_since = now
                self._last_doc = {
                    "enabled": True, "state": self._state, "raw_state": raw,
                    "fast_window_s": self.fast_s,
                    "slow_window_s": self.slow_s,
                    "specs": spec_docs,
                }
                doc = dict(self._last_doc)
            if self._g_state is not None:
                self._g_state.set(float(state_rank(doc["state"])))
                self._g_warn.set(float(n_warn))
                self._g_crit.set(float(n_crit))
                self._c_evals.inc()
            if doc["state"] == "critical" and prev != "critical":
                if self._c_crit is not None:
                    self._c_crit.inc()
                return doc
            return None

    def _fire_postmortem(self, doc: dict | None) -> None:
        if doc is None:
            return
        from lmrs_tpu.obs.flight import dump_postmortem

        metrics = {}
        if self._metrics_cb is not None:
            try:
                metrics = self._metrics_cb()
            except Exception:  # noqa: BLE001 - the recorder is best-effort
                logger.debug("slo postmortem metrics callback failed",
                             exc_info=True)
        dump_postmortem("slo", metrics=metrics, extra=doc)

    def report(self) -> dict:
        """Evaluate now and return the SLO doc — the ``slo`` block of
        ``/healthz``, ``metrics_report()``, and bench detail."""
        if not self.enabled:
            return {"enabled": False, "state": "ok", "specs": {}}
        self._fire_postmortem(self._evaluate(self.clock()))
        with self._lock:
            return dict(self._last_doc)

    @property
    def state(self) -> str:
        return self.report()["state"]
