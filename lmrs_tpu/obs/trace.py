"""Request-lifecycle tracer: bounded ring buffer of Chrome trace events.

One process-wide tracer (enabled explicitly — ``--trace-out`` on the CLI /
bench, or ``enable_tracing()`` in tests) records span events as plain
dicts in a ``deque(maxlen=...)``: recording is an O(1) append, dropping is
oldest-first, and a disabled tracer costs one ``None`` check at each call
site — the ≤2% overhead budget is met by never formatting or allocating
when tracing is off.

Event vocabulary (the per-request chain the scheduler emits):

    enqueue → admit → [prefix_match] → prefill → first_token
        → decode_block* → finish | preempt | cancel

Deadline-lifecycle terminals add ``shed`` (rejected before prefill) and
``deadline`` (queued expiry) instants; an in-flight expiry closes the
``decode`` span and emits ``finish`` with ``reason="deadline"``
(docs/ROBUSTNESS.md).

plus scheduler-track ``decode_block``/``prefill_dispatch`` dispatch spans
and pipeline-track ``map_stage``/``reduce_level``/stage spans.  Export is
Chrome trace-event JSON (``{"traceEvents": [...]}``) loadable directly in
Perfetto / chrome://tracing; ``validate_trace_file`` checks the fields
Perfetto requires and is shared by the tests and the CI trace-export gate.

Track layout: pid 1 = engine (tid 0 the scheduler dispatch track, tid
10+request_id one track per request), pid 2 = pipeline stages.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from pathlib import Path

PID_ENGINE = 1
PID_PIPELINE = 2
PID_STITCH = 9  # stitched per-trace tracks (stitch_traces output)
TID_SCHED = 0
REQ_TID_BASE = 10  # request_id -> tid offset (tid 0..9 reserved for tracks)
# trace-id-keyed tracks allocate from a disjoint base so they can never
# collide with the int-keyed ``REQ_TID_BASE + request_id`` tracks (HTTP
# batcher rids start at 0; executor rids ride 1<<20 epoch bands)
TRACE_TID_BASE = 1 << 30
# thread_name prefix that marks a track as belonging to one distributed
# trace — the cross-host stitcher keys on it, so the trace id needs to
# ride only the track METADATA, not every event's args
TRACE_TRACK_PREFIX = "trace:"

_PHASES = {"X", "i", "I", "B", "E", "M", "C"}


def req_tid(request_id: int) -> int:
    return REQ_TID_BASE + request_id


def new_trace_id() -> str:
    """Mint a fleet-unique trace id (ingress: server or router).  Short
    enough to ride headers/tickets/journals, unique enough that two hosts
    minting concurrently can never collide in one stitched trace."""
    return uuid.uuid4().hex[:16]


class Tracer:
    """Bounded in-memory trace recorder (thread-safe: deque.append is
    atomic, and writers only append)."""

    def __init__(self, capacity: int = 262_144):
        self.capacity = capacity
        self._events: deque[dict] = deque(maxlen=capacity)
        # total ever recorded (recorded - len = dropped).  The int += is a
        # read-modify-write — scheduler, HTTP handler, and sweeper threads
        # record concurrently, so it counts under the trace lock (a bare
        # increment was measured losing updates under concurrent spans;
        # the race detector's guarded-by annotation keeps it fixed).
        self.recorded = 0  # guarded-by: _trace_lock
        self._track_names: dict[tuple[int, int], str] = {}
        self._process_names: dict[int, str] = {
            PID_ENGINE: "lmrs-engine", PID_PIPELINE: "lmrs-pipeline"}
        # trace-id -> allocated tid (track_for): the per-request track key
        # for distributed traces — stable within a process, named
        # ``trace:<id>`` so the stitcher can match tracks across hosts
        self._trace_tids: dict[str, int] = {}  # guarded-by: _trace_lock
        self._trace_lock = threading.Lock()
        self.name_track(PID_ENGINE, TID_SCHED, "scheduler dispatches")
        self.name_track(PID_PIPELINE, TID_SCHED, "stages")

    # ------------------------------------------------------------- recording

    def instant(self, name: str, ts: float | None = None, *,
                tid: int = TID_SCHED, pid: int = PID_ENGINE,
                args: dict | None = None) -> None:
        """Point event at ``ts`` (seconds, default now)."""
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": (time.time() if ts is None else ts) * 1e6,
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self._events.append(ev)
        with self._trace_lock:
            self.recorded += 1

    def complete(self, name: str, t0: float, t1: float, *,
                 tid: int = TID_SCHED, pid: int = PID_ENGINE,
                 args: dict | None = None) -> None:
        """Span [t0, t1] (seconds since epoch, same clock as instant)."""
        ev = {"name": name, "ph": "X", "ts": t0 * 1e6,
              "dur": max(t1 - t0, 0.0) * 1e6, "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self._events.append(ev)
        with self._trace_lock:
            self.recorded += 1

    def name_track(self, pid: int, tid: int, name: str) -> None:
        """Label a track (kept outside the ring so names survive overflow)."""
        self._track_names[(pid, tid)] = name

    def track_for(self, key: str | int, pid: int = PID_ENGINE) -> int:
        """Track id for a per-request span chain.  An int key is the
        legacy request-id mapping (``REQ_TID_BASE + id`` — unchanged, so
        engine-direct callers and their tests keep their track layout); a
        STRING key is a distributed trace id: the first call allocates a
        process-stable tid from ``TRACE_TID_BASE`` and names the track
        ``trace:<id>``, which is what lets the cross-host stitcher merge
        one request's spans from several hosts into one causal chain —
        and frees the per-request track from the executor's epoch-banded
        int ids (1<<20 bands made tids meaningless across runs)."""
        if isinstance(key, int):
            return req_tid(key)
        with self._trace_lock:
            tid = self._trace_tids.get(key)
            if tid is None:
                tid = TRACE_TID_BASE + len(self._trace_tids)
                self._trace_tids[key] = tid
                self.name_track(pid, tid, f"{TRACE_TRACK_PREFIX}{key}")
            return tid

    def clear(self) -> None:
        self._events.clear()
        with self._trace_lock:
            self.recorded = 0

    # --------------------------------------------------------------- reading

    def events(self) -> list[dict]:
        return list(self._events)

    def timestamps(self, name: str, tid: int | None = None,
                   ph: str | None = None) -> list[float]:
        """Start timestamps (seconds, sorted) of retained events named
        ``name``, optionally filtered by track/phase — the dispatch-gap
        analysis hook (scripts/decode_latency.py; successor of the
        LMRS_TRACE_DISPATCH list: ``timestamps("decode_block",
        tid=TID_SCHED)`` is exactly the old per-dispatch list)."""
        return sorted(e["ts"] / 1e6 for e in self._events
                      if e["name"] == name
                      and (tid is None or e["tid"] == tid)
                      and (ph is None or e["ph"] == ph))

    def spans_by_tid(self, pid: int = PID_ENGINE) -> dict[int, list[dict]]:
        """Events grouped per track, each track ts-sorted (test helper)."""
        out: dict[int, list[dict]] = {}
        for e in self._events:
            if e["pid"] == pid:
                out.setdefault(e["tid"], []).append(e)
        for evs in out.values():
            evs.sort(key=lambda e: e["ts"])
        return out

    # --------------------------------------------------------------- export

    def payload(self, host: str | None = None) -> dict:
        """The exportable Chrome-trace document (also the ``GET /v1/trace``
        response body).  Metadata (process/thread names) is regenerated on
        every call so ring overflow can never drop it; ``clock_s`` stamps
        this host's wall clock at export time (a stitcher-side sanity
        anchor — the real skew anchor is the handoff event pair)."""
        meta: list[dict] = []
        # snapshot the name dicts under the lock: /v1/trace exports the
        # LIVE tracer while scheduler/handler threads allocate new trace
        # tracks (track_for), and iterating a mutating dict raises
        with self._trace_lock:
            process_names = list(self._process_names.items())
            track_names = list(self._track_names.items())
        for pid, name in process_names:
            meta.append({"name": "process_name", "ph": "M", "ts": 0,
                         "pid": pid, "tid": 0, "args": {"name": name}})
        for (pid, tid), name in track_names:
            meta.append({"name": "thread_name", "ph": "M", "ts": 0,
                         "pid": pid, "tid": tid, "args": {"name": name}})
        doc = {"displayTimeUnit": "ms",
               "traceEvents": meta + list(self._events),
               "clock_s": time.time()}
        if host:
            doc["host"] = host
        return doc

    def export(self, path: str | Path) -> int:
        """Write Chrome trace-event JSON; returns the event count written."""
        payload = self.payload()
        Path(path).write_text(json.dumps(payload), encoding="utf-8")
        return len(payload["traceEvents"])


# ------------------------------------------------------------ global tracer

_tracer: Tracer | None = None


def get_tracer() -> Tracer | None:
    """The process tracer, or None when tracing is off (call sites guard
    with ``if tr:`` — the disabled path must stay allocation-free)."""
    return _tracer


def enable_tracing(capacity: int = 262_144) -> Tracer:
    """Install (or return the existing) process tracer."""
    global _tracer
    if _tracer is None:
        _tracer = Tracer(capacity=capacity)
    return _tracer


def disable_tracing() -> None:
    global _tracer
    _tracer = None


def export_current(path: str | Path) -> tuple[int | None, str | None]:
    """Export the process tracer (if any) to ``path`` without ever raising:
    returns (event_count, None) on success, (None, reason) otherwise.  The
    one exit-path export helper shared by the CLI and bench — both export
    in a ``finally`` where a raise would mask the run's real error."""
    tr = get_tracer()
    if tr is None:
        return None, "tracing was not enabled"
    try:
        return tr.export(path), None
    except Exception as e:  # noqa: BLE001 - includes serialization errors;
        return None, str(e)  # a raise here would mask the run's real error


# ----------------------------------------------------------------- validation

# Lifecycle instants whose args are a CONTRACT consumers parse (the
# stitcher's skew anchors, the postmortem reader, the jobs dashboard):
# a rename or dropped key here must fail the trace gate, not silently
# break a downstream reader.
_INSTANT_REQUIRED_ARGS: dict[str, tuple[str, ...]] = {
    "handoff_export": ("pages", "kv_len"),
    "handoff_import": ("pages", "kv_len"),
    "handoff_release": ("pages", "orphaned"),
    "job_submit": ("job",),
    "job_recover": ("job",),
    "job_resume": ("job", "resumed_chunks"),
    "job_done": ("job", "status"),
    "qos_reorder": ("picked",),
    "qos_preempt": ("slot",),
    "autoscale_action": ("action",),
}

# Perf-attribution (and counting) args: whenever present they must be
# finite non-negative numbers — a NaN MFU or negative byte count in a
# trace poisons every aggregation built on it.
_NONNEG_NUMERIC_ARGS = ("pages", "kv_len", "tokens", "prompt_tokens",
                        "completion_tokens", "resumed_chunks",
                        "flops_g", "hbm_gb", "mfu", "hbm_util")


def validate_trace_events(events: list) -> list[dict]:
    """Schema-check a trace-event list against what Perfetto requires:
    every event carries ``name``/``ph``/``ts``/``pid``/``tid``, ``X``
    events carry a non-negative ``dur``, ``M`` events carry ``args.name``.
    Handoff/job lifecycle instants must carry their contract args
    (``_INSTANT_REQUIRED_ARGS``) and perf-attribution args must be finite
    non-negative numbers.  Returns the events; raises ValueError with the
    first offender."""
    import math

    if not isinstance(events, list) or not events:
        raise ValueError("trace has no events")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object: {ev!r}")
        for key in ("name", "ph", "ts", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event {i} missing {key!r}: {ev}")
        if not isinstance(ev["name"], str) or not ev["name"]:
            raise ValueError(f"event {i} has a non-string name: {ev}")
        if ev["ph"] not in _PHASES:
            raise ValueError(f"event {i} has unknown phase {ev['ph']!r}")
        if not isinstance(ev["ts"], (int, float)):
            raise ValueError(f"event {i} has non-numeric ts: {ev}")
        if not isinstance(ev["pid"], int) or not isinstance(ev["tid"], int):
            raise ValueError(f"event {i} has non-int pid/tid: {ev}")
        if ev["ph"] == "X" and (not isinstance(ev.get("dur"), (int, float))
                                or ev["dur"] < 0):
            raise ValueError(f"event {i}: X event needs dur >= 0: {ev}")
        if ev["ph"] == "M" and "name" not in (ev.get("args") or {}):
            raise ValueError(f"event {i}: metadata event needs args.name")
        args = ev.get("args") or {}
        want = _INSTANT_REQUIRED_ARGS.get(ev["name"])
        if want is not None and ev["ph"] in ("i", "I"):
            for key in want:
                if key not in args:
                    raise ValueError(
                        f"event {i}: {ev['name']} instant missing "
                        f"args.{key}: {ev}")
        for key in _NONNEG_NUMERIC_ARGS:
            if key in args:
                v = args[key]
                if (isinstance(v, bool) or not isinstance(v, (int, float))
                        or not math.isfinite(v) or v < 0):
                    raise ValueError(
                        f"event {i}: args.{key} must be a finite "
                        f"non-negative number, got {v!r}: {ev}")
    return events


def validate_trace_file(path: str | Path) -> list[dict]:
    """Load + schema-check an exported trace (the CI trace-export gate)."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if isinstance(data, dict):
        events = data.get("traceEvents")
        if events is None:
            raise ValueError("trace JSON object lacks 'traceEvents'")
    else:
        events = data
    return validate_trace_events(events)


# --------------------------------------------------- cross-host stitching
#
# A disaggregated request's spans live in two (or more) hosts' ring
# buffers, each on that host's wall clock.  ``stitch_traces`` merges the
# per-host ``/v1/trace`` pages into ONE Perfetto document:
#
# * every host keeps its own tracks (pids remapped so they never collide;
#   process names prefixed with the host's netloc);
# * host clocks are aligned using the handoff ticket's export/import
#   instant pair as the skew anchor — on real wall clocks an import
#   STRICTLY follows its export (the payload crossed the wire between
#   them) and the exporter's ``handoff_release`` strictly follows the
#   import (the ack crossed back), so each matched trace id yields a
#   feasible offset interval per host;
# * every track named ``trace:<id>`` contributes its events to a
#   synthesized per-trace track under ``PID_STITCH`` — the "one causal
#   chain" view where a request reads enqueue → prefill (pod A) →
#   handoff → decode (pod B) → finish on a single timeline.


def _host_offsets(per_host: list[dict]) -> list[float]:
    """Per-host clock offsets (seconds to ADD to that host's timestamps),
    host 0 as the reference.  For each unaligned host, matched handoff
    pairs against already-aligned hosts bound a feasible interval
    [lo, hi]; clocks already consistent (0 inside the interval) are left
    untouched, otherwise the minimal shift restoring causality is
    applied.  Hosts with no anchor pairs keep offset 0."""
    def anchors(info: dict) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {
            "handoff_export": {}, "handoff_import": {}, "handoff_release": {}}
        for e in info["events"]:
            if e.get("ph") == "M" or e.get("name") not in out:
                continue
            trace = info["tidmap"].get((e.get("pid"), e.get("tid")))
            if trace is not None:
                out[e["name"]].setdefault(trace, e.get("ts", 0) / 1e6)
        return out

    anch = [anchors(info) for info in per_host]
    offsets = [0.0] * len(per_host)
    aligned = {0} if per_host else set()
    eps = 1e-6
    progress = True
    while progress:
        progress = False
        for j in range(len(per_host)):
            if j in aligned:
                continue
            lo, hi = float("-inf"), float("inf")
            found = False
            for k in aligned:
                # host j imported what host k exported: export_k < import_j
                # < release_k (on the merged clock)
                for t, imp in anch[j]["handoff_import"].items():
                    exp = anch[k]["handoff_export"].get(t)
                    if exp is not None:
                        lo = max(lo, exp + offsets[k] - imp)
                        found = True
                    rel = anch[k]["handoff_release"].get(t)
                    if rel is not None and exp is not None:
                        hi = min(hi, rel + offsets[k] - imp)
                # host j exported what host k imported: the mirror bounds
                for t, exp in anch[j]["handoff_export"].items():
                    imp = anch[k]["handoff_import"].get(t)
                    if imp is None:
                        continue
                    hi = min(hi, imp + offsets[k] - exp)
                    found = True
                    rel = anch[j]["handoff_release"].get(t)
                    if rel is not None:
                        lo = max(lo, imp + offsets[k] - rel)
            if not found:
                continue
            if lo <= 0.0 <= hi:
                offsets[j] = 0.0  # clocks already causally consistent
            elif lo > 0.0:
                offsets[j] = lo + eps  # minimal forward shift
            else:
                offsets[j] = hi - eps  # minimal backward shift
            aligned.add(j)
            progress = True
    return offsets


def stitch_traces(pages: list[tuple[str, dict]]) -> dict:
    """Merge per-host trace pages (``[(netloc, /v1/trace payload)]``) into
    one Perfetto document (see the section comment above).  The returned
    dict carries a ``stitch`` block with the hosts merged, the applied
    clock offsets (ms), and the trace ids found — extra top-level keys
    Perfetto ignores but the CI gate and dashboards read."""
    per_host: list[dict] = []
    for host, doc in pages:
        events = (doc or {}).get("traceEvents") or []
        tidmap: dict[tuple, str] = {}
        for e in events:
            if (e.get("ph") == "M" and e.get("name") == "thread_name"):
                nm = (e.get("args") or {}).get("name", "")
                if isinstance(nm, str) and nm.startswith(TRACE_TRACK_PREFIX):
                    tidmap[(e.get("pid"), e.get("tid"))] = (
                        nm[len(TRACE_TRACK_PREFIX):])
        per_host.append({"host": host, "events": events, "tidmap": tidmap})
    offsets = _host_offsets(per_host)

    out_events: list[dict] = []
    for i, info in enumerate(per_host):
        off_us = offsets[i] * 1e6
        # pid remap: host i's pid p -> 10*(i+1)+p, far from PID_STITCH and
        # collision-free for any realistic per-host pid set (1, 2)
        for e in info["events"]:
            ne = dict(e)
            ne["pid"] = 10 * (i + 1) + int(e.get("pid", 0))
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    old = (e.get("args") or {}).get("name", "")
                    ne["args"] = {"name": f"{info['host']} {old}".strip()}
                out_events.append(ne)
                continue
            ne["ts"] = e.get("ts", 0) + off_us
            out_events.append(ne)

    traces = sorted({t for info in per_host for t in info["tidmap"].values()})
    trace_tid = {t: REQ_TID_BASE + j for j, t in enumerate(traces)}
    stitched: list[dict] = []
    for i, info in enumerate(per_host):
        off_us = offsets[i] * 1e6
        for e in info["events"]:
            if e.get("ph") == "M":
                continue
            trace = info["tidmap"].get((e.get("pid"), e.get("tid")))
            if trace is None:
                continue
            se = dict(e)
            se["pid"] = PID_STITCH
            se["tid"] = trace_tid[trace]
            se["ts"] = e.get("ts", 0) + off_us
            args = dict(se.get("args") or {})
            args.setdefault("host", info["host"])
            se["args"] = args
            stitched.append(se)
    stitched.sort(key=lambda e: e["ts"])

    meta: list[dict] = [{"name": "process_name", "ph": "M", "ts": 0,
                         "pid": PID_STITCH, "tid": 0,
                         "args": {"name": "lmrs-stitched"}}]
    for t, tid in trace_tid.items():
        meta.append({"name": "thread_name", "ph": "M", "ts": 0,
                     "pid": PID_STITCH, "tid": tid,
                     "args": {"name": f"{TRACE_TRACK_PREFIX}{t}"}})
    return {
        "displayTimeUnit": "ms",
        "traceEvents": meta + out_events + stitched,
        "stitch": {
            "hosts": [info["host"] for info in per_host],
            "offsets_ms": {info["host"]: round(offsets[i] * 1e3, 3)
                           for i, info in enumerate(per_host)},
            "traces": traces,
        },
    }


def stitched_chains(events: list[dict]) -> dict[str, list[dict]]:
    """trace id -> ts-ordered events of its stitched track (``PID_STITCH``)
    from a stitched document's event list — the per-request causal chain
    the CI gate asserts on."""
    tid_trace: dict[int, str] = {}
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and e.get("pid") == PID_STITCH):
            nm = (e.get("args") or {}).get("name", "")
            if isinstance(nm, str) and nm.startswith(TRACE_TRACK_PREFIX):
                tid_trace[e["tid"]] = nm[len(TRACE_TRACK_PREFIX):]
    chains: dict[str, list[dict]] = {}
    for e in events:
        if e.get("ph") == "M" or e.get("pid") != PID_STITCH:
            continue
        trace = tid_trace.get(e.get("tid"))
        if trace is not None:
            chains.setdefault(trace, []).append(e)
    for evs in chains.values():
        evs.sort(key=lambda e: e.get("ts", 0))
    return chains
