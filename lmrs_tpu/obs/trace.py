"""Request-lifecycle tracer: bounded ring buffer of Chrome trace events.

One process-wide tracer (enabled explicitly — ``--trace-out`` on the CLI /
bench, or ``enable_tracing()`` in tests) records span events as plain
dicts in a ``deque(maxlen=...)``: recording is an O(1) append, dropping is
oldest-first, and a disabled tracer costs one ``None`` check at each call
site — the ≤2% overhead budget is met by never formatting or allocating
when tracing is off.

Event vocabulary (the per-request chain the scheduler emits):

    enqueue → admit → [prefix_match] → prefill → first_token
        → decode_block* → finish | preempt | cancel

Deadline-lifecycle terminals add ``shed`` (rejected before prefill) and
``deadline`` (queued expiry) instants; an in-flight expiry closes the
``decode`` span and emits ``finish`` with ``reason="deadline"``
(docs/ROBUSTNESS.md).

plus scheduler-track ``decode_block``/``prefill_dispatch`` dispatch spans
and pipeline-track ``map_stage``/``reduce_level``/stage spans.  Export is
Chrome trace-event JSON (``{"traceEvents": [...]}``) loadable directly in
Perfetto / chrome://tracing; ``validate_trace_file`` checks the fields
Perfetto requires and is shared by the tests and the CI trace-export gate.

Track layout: pid 1 = engine (tid 0 the scheduler dispatch track, tid
10+request_id one track per request), pid 2 = pipeline stages.
"""

from __future__ import annotations

import json
import time
from collections import deque
from pathlib import Path

PID_ENGINE = 1
PID_PIPELINE = 2
TID_SCHED = 0
REQ_TID_BASE = 10  # request_id -> tid offset (tid 0..9 reserved for tracks)

_PHASES = {"X", "i", "I", "B", "E", "M", "C"}


def req_tid(request_id: int) -> int:
    return REQ_TID_BASE + request_id


class Tracer:
    """Bounded in-memory trace recorder (thread-safe: deque.append is
    atomic, and writers only append)."""

    def __init__(self, capacity: int = 262_144):
        self.capacity = capacity
        self._events: deque[dict] = deque(maxlen=capacity)
        self.recorded = 0  # total ever recorded (recorded - len = dropped)
        self._track_names: dict[tuple[int, int], str] = {}
        self._process_names: dict[int, str] = {
            PID_ENGINE: "lmrs-engine", PID_PIPELINE: "lmrs-pipeline"}
        self.name_track(PID_ENGINE, TID_SCHED, "scheduler dispatches")
        self.name_track(PID_PIPELINE, TID_SCHED, "stages")

    # ------------------------------------------------------------- recording

    def instant(self, name: str, ts: float | None = None, *,
                tid: int = TID_SCHED, pid: int = PID_ENGINE,
                args: dict | None = None) -> None:
        """Point event at ``ts`` (seconds, default now)."""
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": (time.time() if ts is None else ts) * 1e6,
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.recorded += 1

    def complete(self, name: str, t0: float, t1: float, *,
                 tid: int = TID_SCHED, pid: int = PID_ENGINE,
                 args: dict | None = None) -> None:
        """Span [t0, t1] (seconds since epoch, same clock as instant)."""
        ev = {"name": name, "ph": "X", "ts": t0 * 1e6,
              "dur": max(t1 - t0, 0.0) * 1e6, "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.recorded += 1

    def name_track(self, pid: int, tid: int, name: str) -> None:
        """Label a track (kept outside the ring so names survive overflow)."""
        self._track_names[(pid, tid)] = name

    def clear(self) -> None:
        self._events.clear()
        self.recorded = 0

    # --------------------------------------------------------------- reading

    def events(self) -> list[dict]:
        return list(self._events)

    def timestamps(self, name: str, tid: int | None = None,
                   ph: str | None = None) -> list[float]:
        """Start timestamps (seconds, sorted) of retained events named
        ``name``, optionally filtered by track/phase — the dispatch-gap
        analysis hook (scripts/decode_latency.py; successor of the
        LMRS_TRACE_DISPATCH list: ``timestamps("decode_block",
        tid=TID_SCHED)`` is exactly the old per-dispatch list)."""
        return sorted(e["ts"] / 1e6 for e in self._events
                      if e["name"] == name
                      and (tid is None or e["tid"] == tid)
                      and (ph is None or e["ph"] == ph))

    def spans_by_tid(self, pid: int = PID_ENGINE) -> dict[int, list[dict]]:
        """Events grouped per track, each track ts-sorted (test helper)."""
        out: dict[int, list[dict]] = {}
        for e in self._events:
            if e["pid"] == pid:
                out.setdefault(e["tid"], []).append(e)
        for evs in out.values():
            evs.sort(key=lambda e: e["ts"])
        return out

    # --------------------------------------------------------------- export

    def export(self, path: str | Path) -> int:
        """Write Chrome trace-event JSON; returns the event count written.
        Metadata (process/thread names) is regenerated on every export so
        ring overflow can never drop it."""
        meta: list[dict] = []
        for pid, name in self._process_names.items():
            meta.append({"name": "process_name", "ph": "M", "ts": 0,
                         "pid": pid, "tid": 0, "args": {"name": name}})
        for (pid, tid), name in self._track_names.items():
            meta.append({"name": "thread_name", "ph": "M", "ts": 0,
                         "pid": pid, "tid": tid, "args": {"name": name}})
        events = meta + list(self._events)
        payload = {"displayTimeUnit": "ms", "traceEvents": events}
        Path(path).write_text(json.dumps(payload), encoding="utf-8")
        return len(events)


# ------------------------------------------------------------ global tracer

_tracer: Tracer | None = None


def get_tracer() -> Tracer | None:
    """The process tracer, or None when tracing is off (call sites guard
    with ``if tr:`` — the disabled path must stay allocation-free)."""
    return _tracer


def enable_tracing(capacity: int = 262_144) -> Tracer:
    """Install (or return the existing) process tracer."""
    global _tracer
    if _tracer is None:
        _tracer = Tracer(capacity=capacity)
    return _tracer


def disable_tracing() -> None:
    global _tracer
    _tracer = None


def export_current(path: str | Path) -> tuple[int | None, str | None]:
    """Export the process tracer (if any) to ``path`` without ever raising:
    returns (event_count, None) on success, (None, reason) otherwise.  The
    one exit-path export helper shared by the CLI and bench — both export
    in a ``finally`` where a raise would mask the run's real error."""
    tr = get_tracer()
    if tr is None:
        return None, "tracing was not enabled"
    try:
        return tr.export(path), None
    except Exception as e:  # noqa: BLE001 - includes serialization errors;
        return None, str(e)  # a raise here would mask the run's real error


# ----------------------------------------------------------------- validation


def validate_trace_events(events: list) -> list[dict]:
    """Schema-check a trace-event list against what Perfetto requires:
    every event carries ``name``/``ph``/``ts``/``pid``/``tid``, ``X``
    events carry a non-negative ``dur``, ``M`` events carry ``args.name``.
    Returns the events; raises ValueError with the first offender."""
    if not isinstance(events, list) or not events:
        raise ValueError("trace has no events")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object: {ev!r}")
        for key in ("name", "ph", "ts", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event {i} missing {key!r}: {ev}")
        if not isinstance(ev["name"], str) or not ev["name"]:
            raise ValueError(f"event {i} has a non-string name: {ev}")
        if ev["ph"] not in _PHASES:
            raise ValueError(f"event {i} has unknown phase {ev['ph']!r}")
        if not isinstance(ev["ts"], (int, float)):
            raise ValueError(f"event {i} has non-numeric ts: {ev}")
        if not isinstance(ev["pid"], int) or not isinstance(ev["tid"], int):
            raise ValueError(f"event {i} has non-int pid/tid: {ev}")
        if ev["ph"] == "X" and (not isinstance(ev.get("dur"), (int, float))
                                or ev["dur"] < 0):
            raise ValueError(f"event {i}: X event needs dur >= 0: {ev}")
        if ev["ph"] == "M" and "name" not in (ev.get("args") or {}):
            raise ValueError(f"event {i}: metadata event needs args.name")
    return events


def validate_trace_file(path: str | Path) -> list[dict]:
    """Load + schema-check an exported trace (the CI trace-export gate)."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if isinstance(data, dict):
        events = data.get("traceEvents")
        if events is None:
            raise ValueError("trace JSON object lacks 'traceEvents'")
    else:
        events = data
    return validate_trace_events(events)
