"""ctypes bindings for the native runtime library.

Loads ``native/build/liblmrs_runtime.so``, building it with ``g++`` on first
use if missing or stale (source newer than the .so).  All entry points have
pure-Python fallbacks at their call sites; ``LMRS_NATIVE=0`` disables the
native path entirely.

Exposed surface (mirrors of the Python implementations, parity-tested in
tests/test_native.py):

* ``clean_text_native`` / ``clean_text_batch`` — data/preprocessor.clean_text
* ``count_approx_native`` / ``count_approx_batch`` — ApproxTokenizer.count
* ``NativePageAllocator``  — engine/kv_cache.PageAllocator
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

logger = logging.getLogger("lmrs.native")

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_SRC = _NATIVE_DIR / "src" / "lmrs_runtime.cc"
_LIB = _NATIVE_DIR / "build" / "liblmrs_runtime.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _build() -> bool:
    """Compile the shared library with g++ (no cmake needed for one TU).

    Writes to a temp path and renames into place, so a concurrent process
    can never dlopen a half-written .so.
    """
    try:
        _LIB.parent.mkdir(parents=True, exist_ok=True)
        tmp = _LIB.with_suffix(f".so.tmp.{os.getpid()}")
        cmd = [
            os.environ.get("CXX", "g++"),
            "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-fvisibility=hidden",
            "-o", str(tmp), str(_SRC),
        ]
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            logger.warning("native build failed:\n%s", r.stderr[-2000:])
            return False
        os.replace(tmp, _LIB)
        logger.info("built native runtime: %s", _LIB)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build failed: %s", e)
        return False


def _load() -> ctypes.CDLL | None:
    if _load_attempted:  # lock-free fast path (GIL-safe read)
        return _lib
    with _lock:
        if _load_attempted:
            return _lib
        lib = _try_load()
        _set_loaded(lib)
        return lib


def _set_loaded(lib: ctypes.CDLL | None) -> None:
    global _lib, _load_attempted
    _lib = lib
    _load_attempted = True


def _try_load() -> ctypes.CDLL | None:
    from lmrs_tpu.utils.env import env_bool

    if not env_bool("LMRS_NATIVE", True):
        return None
    if not _SRC.exists():
        return None
    stale = not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime
    if stale and not _build():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB))
        lib.lmrs_abi_version.restype = ctypes.c_int32
        # v2: ref-counted allocator (incref/refcount entry points; free is a
        # decref that errors on double-free)
        if lib.lmrs_abi_version() != 2:
            logger.warning("native ABI mismatch; ignoring %s", _LIB)
            return None
        lib.lmrs_clean_text.restype = ctypes.c_int64
        lib.lmrs_clean_text.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        lib.lmrs_clean_text_batch.restype = ctypes.c_int64
        lib.lmrs_clean_text_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
        lib.lmrs_count_approx.restype = ctypes.c_int64
        lib.lmrs_count_approx.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.lmrs_count_approx_batch.restype = None
        lib.lmrs_count_approx_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.lmrs_palloc_create.restype = ctypes.c_void_p
        lib.lmrs_palloc_create.argtypes = [ctypes.c_int32]
        lib.lmrs_palloc_destroy.restype = None
        lib.lmrs_palloc_destroy.argtypes = [ctypes.c_void_p]
        lib.lmrs_palloc_free_count.restype = ctypes.c_int32
        lib.lmrs_palloc_free_count.argtypes = [ctypes.c_void_p]
        lib.lmrs_palloc_alloc.restype = ctypes.c_int32
        lib.lmrs_palloc_alloc.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
        lib.lmrs_palloc_free.restype = ctypes.c_int32
        lib.lmrs_palloc_free.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.lmrs_palloc_incref.restype = ctypes.c_int32
        lib.lmrs_palloc_incref.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.lmrs_palloc_refcount.restype = ctypes.c_int32
        lib.lmrs_palloc_refcount.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        return lib
    except (OSError, AttributeError) as e:
        # missing file, missing symbol (stale .so from an older source
        # revision) — degrade to the Python implementations
        logger.warning("could not load native runtime %s: %s", _LIB, e)
        return None


def native_available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------- text


def clean_text_native(text: str) -> str | None:
    """Native clean_text; returns None when the library is unavailable.

    Non-ASCII strings are routed to the pure-Python cleaner: the regex
    ``\\w`` / ``IGNORECASE`` semantics are Unicode-aware and the C++ scan
    only reproduces them exactly for ASCII, so parity is guaranteed by
    construction instead of by approximation.
    """
    lib = _load()
    if lib is None:
        return None
    if not text.isascii():
        from lmrs_tpu.data.preprocessor import clean_text_py

        return clean_text_py(text)
    raw = text.encode("utf-8")
    cap = 2 * len(raw) + 16
    buf = ctypes.create_string_buffer(cap)
    n = lib.lmrs_clean_text(raw, len(raw), buf, cap)
    if n < 0:  # buffer too small (shouldn't happen: output <= 2n)
        cap = -n
        buf = ctypes.create_string_buffer(cap)
        n = lib.lmrs_clean_text(raw, len(raw), buf, cap)
    return buf.raw[:n].decode("utf-8")


def clean_text_batch(texts: list[str]) -> list[str] | None:
    """Clean a batch of strings in one FFI crossing (the data-plane path).

    Non-ASCII entries go through the pure-Python cleaner (see
    clean_text_native); the ASCII majority is cleaned natively in one call.
    """
    lib = _load()
    if lib is None:
        return None
    if not texts:
        return []
    non_ascii = [i for i, t in enumerate(texts) if not t.isascii()]
    if non_ascii:
        from lmrs_tpu.data.preprocessor import clean_text_py

        keep = [t for t in texts if t.isascii()]
        cleaned_ascii = iter(clean_text_batch(keep) or [])
        return [clean_text_py(t) if not t.isascii() else next(cleaned_ascii)
                for t in texts]
    raws = [t.encode("utf-8") for t in texts]
    offsets = np.zeros(len(raws) + 1, np.int64)
    np.cumsum([len(r) for r in raws], out=offsets[1:])
    buf = b"".join(raws)
    cap = 2 * len(buf) + 16
    out = ctypes.create_string_buffer(cap)
    out_off = np.zeros(len(raws) + 1, np.int64)
    rc = lib.lmrs_clean_text_batch(
        buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(raws),
        out, cap, out_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc < 0:  # shouldn't happen: output <= 2x input
        cap = -rc
        out = ctypes.create_string_buffer(cap)
        lib.lmrs_clean_text_batch(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(raws), out, cap,
            out_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    raw = out.raw
    return [raw[out_off[i]:out_off[i + 1]].decode("utf-8")
            for i in range(len(raws))]


def count_approx_native(text: str) -> int | None:
    lib = _load()
    if lib is None:
        return None
    raw = text.encode("utf-8")
    return int(lib.lmrs_count_approx(raw, len(raw)))


def count_approx_batch(texts: list[str]) -> list[int] | None:
    """Batch approx counting: one FFI crossing for the whole list."""
    lib = _load()
    if lib is None:
        return None
    raws = [t.encode("utf-8") for t in texts]
    offsets = np.zeros(len(raws) + 1, np.int64)
    np.cumsum([len(r) for r in raws], out=offsets[1:])
    buf = b"".join(raws)
    out = np.zeros(len(raws), np.int64)
    lib.lmrs_count_approx_batch(
        buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(raws), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out.tolist()


# -------------------------------------------------------------- allocator


class NativePageAllocator:
    """C++ free-list page allocator; drop-in for kv_cache.PageAllocator.

    Same contract: page 0 reserved, pages handed out lowest-id-first from a
    LIFO free list, ``OutOfPages`` (raised by the caller shim) on exhaustion,
    per-page refcounts (``incref``/``refcount``; ``free`` decrefs and raises
    ``ValueError`` on a double-free).
    """

    RESERVED = 1

    def __init__(self, num_pages: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        if num_pages <= self.RESERVED:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._lib = lib
        self._h = lib.lmrs_palloc_create(num_pages)
        if not self._h:
            raise RuntimeError("lmrs_palloc_create failed")

    @property
    def free_count(self) -> int:
        return int(self._lib.lmrs_palloc_free_count(self._h))

    def alloc(self, n: int) -> list[int]:
        from lmrs_tpu.engine.kv_cache import OutOfPages

        out = np.zeros(max(n, 1), np.int32)
        rc = self._lib.lmrs_palloc_alloc(
            self._h, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise OutOfPages(f"need {n} pages, {self.free_count} free")
        return out[:n].tolist()

    def free(self, pages: list[int]) -> None:
        arr = np.asarray(pages, np.int32)
        rc = self._lib.lmrs_palloc_free(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(pages))
        if rc == -3:
            raise ValueError(f"double-free / unowned page in {pages}")
        if rc != 0:
            raise ValueError(f"bad page id in {pages}")

    def incref(self, pages: list[int]) -> None:
        arr = np.asarray(pages, np.int32)
        rc = self._lib.lmrs_palloc_incref(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(pages))
        if rc == -3:
            raise ValueError(f"incref of refcount-0 page in {pages}")
        if rc != 0:
            raise ValueError(f"bad page id in {pages}")

    def refcount(self, page: int) -> int:
        rc = int(self._lib.lmrs_palloc_refcount(self._h, page))
        if rc < 0:
            raise ValueError(f"bad page id {page}")
        return rc

    def __del__(self):  # noqa: D105
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.lmrs_palloc_destroy(h)
            except Exception:  # interpreter teardown
                pass
            self._h = None
