"""Native runtime layer (C++ via ctypes).

The reference is pure Python end to end (SURVEY.md §0); this package houses
the TPU build's native host-side runtime: data-plane hot loops and the KV
page allocator, implemented in C++ (``native/src/lmrs_runtime.cc``) and
bound with ctypes.  Everything degrades to the pure-Python implementations
when the library is unavailable (``LMRS_NATIVE=0`` forces that).
"""

from lmrs_tpu.runtime.native import (  # noqa: F401
    NativePageAllocator,
    clean_text_batch,
    clean_text_native,
    count_approx_batch,
    count_approx_native,
    native_available,
)
