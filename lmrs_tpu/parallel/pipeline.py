"""Pipeline parallelism: layer stages over the ``pp`` mesh axis.

The reference has no model, so no pipeline anything (SURVEY.md §2.2 "PP: No
— optional for the 70B tier").  This is the TPU-native implementation:
GPipe-style fill/drain microbatching expressed as one SPMD program —

* the stacked layer params [L, ...] are sharded on the leading axis over
  ``pp`` (L/pp contiguous layers per stage — spec: sharding.param_specs
  with pp=True);
* inside ``shard_map``, a ``lax.scan`` runs M + pp - 1 ticks; each tick
  every stage applies its layers to one microbatch and hands the activation
  to the next stage via ``lax.ppermute`` over ICI (one hop — neighbors on
  the mesh ring);
* stage 0 feeds fresh microbatches into the ring, the last stage computes
  head + loss for the microbatch that has finished draining; the scalar is
  ``psum``-ed so every shard returns the same loss (SPMD requires all
  stages to run the same program — non-final stages' head FLOPs are masked,
  the standard cost of homogeneous-program pipelining);
* the pipeline bubble is the usual (pp-1)/(M+pp-1) — raise ``n_micro`` to
  amortize.

v1 scope: composes with ``dp`` (microbatches shard the batch axis) but not
with tp/sp inside the pipelined program — embedding/head are replicated
across stages.  autodiff flows through ppermute, so one jax.value_and_grad
over this function is the whole pp backward pass.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from lmrs_tpu.config import ModelConfig
from lmrs_tpu.models.transformer import decoder_layer, embed_tokens, lm_head
from lmrs_tpu.ops.rope import rope_table
from lmrs_tpu.utils.jax_compat import shard_map


def _stage_scan(layers_local, cfg: ModelConfig, x, positions, sin, cos):
    """Apply this stage's L/pp layers (scan over the local leading axis).
    Returns (x, aux_sum) — the summed MoE load-balance loss of the local
    layers (0 for dense models)."""
    def body(x, lp):
        x, aux = decoder_layer(lp, cfg, x, positions, sin, cos)
        return x, aux

    x, aux = lax.scan(body, x, layers_local)
    return x, aux.sum()


def pipeline_causal_lm_loss(
    params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S] int32
    mesh: Mesh,
    n_micro: int = 4,
    pp_axis: str = "pp",
    dp_axis: str = "dp",
) -> jnp.ndarray:
    """Next-token cross-entropy computed through the pp pipeline.

    ``tokens`` batch must divide by n_micro (× dp shards).  Returns the
    token-mean loss as a replicated scalar.
    """
    pp = mesh.shape[pp_axis]
    if cfg.n_layers % pp != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")

    # layers [L,...] -> [pp, L/pp, ...] so the stage axis is shardable
    def split_stage(x):
        return x.reshape((pp, cfg.n_layers // pp) + x.shape[1:])

    staged = {
        "embed": params["embed"],
        "layers": jax.tree.map(split_stage, params["layers"]),
        "final_norm": params["final_norm"],
    }
    if "lm_head" in params:
        staged["lm_head"] = params["lm_head"]

    layer_specs = jax.tree.map(lambda _: P(pp_axis), staged["layers"])
    param_specs = {
        "embed": jax.tree.map(lambda _: P(), staged["embed"]),
        "layers": layer_specs,
        "final_norm": jax.tree.map(lambda _: P(), staged["final_norm"]),
    }
    if "lm_head" in staged:
        param_specs["lm_head"] = jax.tree.map(lambda _: P(), staged["lm_head"])

    def body(sp, tok):  # runs per (dp, pp) shard
        stage = lax.axis_index(pp_axis)
        layers_local = jax.tree.map(lambda x: x[0], sp["layers"])  # [L/pp,...]
        b, s = tok.shape
        m = n_micro
        mb = b // m
        micro = tok.reshape(m, mb, s)
        positions = jnp.broadcast_to(jnp.arange(s)[None], (mb, s))
        hd = cfg.hd
        sin, cos = rope_table(s, hd, cfg.rope_theta)

        x_in = jax.vmap(lambda t: embed_tokens(sp, cfg, t))(micro)  # [M,mb,S,D]

        def tick(carry, t):
            y_prev, loss_sum, tok_count, aux_sum, aux_count = carry
            # previous tick's output moves one stage down the ring
            recv = lax.ppermute(
                y_prev, pp_axis,
                [(i, (i + 1) % pp) for i in range(pp)])
            feed = lax.dynamic_index_in_dim(
                x_in, jnp.clip(t, 0, m - 1), keepdims=False)
            x = jnp.where(stage == 0, feed, recv)
            y, stage_aux = _stage_scan(layers_local, cfg, x, positions, sin, cos)

            # stage s processes microbatch t-s at tick t; aux only counts
            # when that's a real microbatch (not warmup/drain garbage)
            mb_idx = t - stage
            aux_valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
            aux_sum = aux_sum + jnp.where(aux_valid, stage_aux, 0.0)
            aux_count = aux_count + jnp.where(
                aux_valid, layers_local["ln_attn"]["scale"].shape[0], 0)

            # the microbatch finishing at tick t on the last stage is t-(pp-1)
            out_idx = jnp.clip(t - (pp - 1), 0, m - 1)
            tgt = lax.dynamic_index_in_dim(micro, out_idx, keepdims=False)
            logits = lm_head(sp, cfg, y)[:, :-1]  # [mb, S-1, V]
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, tgt[:, 1:, None], axis=-1)[..., 0]
            valid = jnp.logical_and(stage == pp - 1, t >= pp - 1)
            loss_sum = loss_sum + jnp.where(valid, nll.sum(), 0.0)
            tok_count = tok_count + jnp.where(valid, nll.size, 0)
            return (y, loss_sum, tok_count, aux_sum, aux_count), None

        init = (jnp.zeros((mb, s, cfg.dim), x_in.dtype),
                jnp.float32(0.0), jnp.int32(0), jnp.float32(0.0), jnp.int32(0))
        (_, loss_sum, tok_count, aux_sum, aux_count), _ = lax.scan(
            tick, init, jnp.arange(m + pp - 1))

        loss_sum = lax.psum(lax.psum(loss_sum, pp_axis), dp_axis)
        tok_count = lax.psum(lax.psum(tok_count, pp_axis), dp_axis)
        loss = loss_sum / jnp.maximum(tok_count, 1)
        if cfg.n_experts and cfg.router_aux_coef:
            aux_sum = lax.psum(lax.psum(aux_sum, pp_axis), dp_axis)
            aux_count = lax.psum(lax.psum(aux_count, pp_axis), dp_axis)
            loss = loss + cfg.router_aux_coef * aux_sum / jnp.maximum(aux_count, 1)
        return loss

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P(dp_axis)),
        out_specs=P(),
        check_vma=False,
    )
    return fn(staged, tokens)


def make_pp_train_step(cfg: ModelConfig, optimizer, mesh: Mesh,
                       n_micro: int = 4):
    """Jitted (params, opt_state, tokens) -> (params, opt_state, loss) with
    the loss computed through the pp pipeline.  Params stay in their normal
    stacked layout; the stage split happens inside the loss."""
    import optax

    def loss_fn(params, tokens):
        return pipeline_causal_lm_loss(params, cfg, tokens, mesh, n_micro)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
