"""L5 parallelism: device mesh, sharding specs, collectives, multi-host init.

The reference has NO device parallelism — its "distributed" layer is asyncio
HTTP fan-out (SURVEY.md §2.2/§5.8).  This package is the TPU-native
replacement: mesh axes (dp, tp, sp, pp), pjit/NamedSharding param layouts,
XLA collectives over ICI, and jax.distributed for multi-host DCN.
"""

from lmrs_tpu.parallel.mesh import build_mesh, local_mesh_config
from lmrs_tpu.parallel.sharding import (
    batch_spec,
    param_shardings,
    param_specs,
    shard_params,
)

__all__ = [
    "batch_spec",
    "build_mesh",
    "local_mesh_config",
    "param_shardings",
    "param_specs",
    "shard_params",
]
