"""Parameter & activation sharding layouts.

The scaling-book recipe: pick a mesh, annotate shardings on params and
activations, let XLA insert the collectives (all-gather/reduce-scatter ride
ICI on the ``tp`` axis; ``dp`` replicates params and shards the batch).

Layer params are stacked along a leading ``n_layers`` axis (scanned in the
model), so every spec below leads with None for that axis.

Layout (Megatron-style, collective-minimal for decoders):
* attention QKV projections: shard the HEAD axis over tp  → column parallel
* attention output:          shard the input-head axis    → row parallel
  (XLA inserts one psum per attention block)
* MLP gate/up: column parallel; MLP down: row parallel    → one psum per MLP
* embedding/lm_head: vocab axis over tp (logits all-gathered once per step)
* KV cache: batch over dp, kv-heads over tp
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_specs(tie_embeddings: bool = True, moe: bool = False) -> dict[str, Any]:
    """PartitionSpec pytree matching models.transformer.init_params layout."""
    if moe:
        ffn = {
            "moe": {
                "router": P(None, None, None),     # [L, D, E] replicated (tiny)
                "w_gate": P(None, "ep", None, "tp"),  # [L, E, D, F] experts over ep
                "w_up": P(None, "ep", None, "tp"),
                "w_down": P(None, "ep", "tp", None),  # [L, E, F, D]
            }
        }
    else:
        ffn = {
            "mlp": {
                "w_gate": P(None, None, "tp"),  # [L, D, F] column
                "w_up": P(None, None, "tp"),
                "w_down": P(None, "tp", None),  # [L, F, D] row
            }
        }
    specs = {
        "embed": {"weight": P("tp", None)},  # vocab sharded
        "layers": {
            "ln_attn": {"scale": P(None, None)},
            "ln_mlp": {"scale": P(None, None)},
            "attn": {
                "wq": P(None, None, "tp", None),  # [L, D, H, hd] heads sharded
                "wk": P(None, None, "tp", None),  # [L, D, K, hd]
                "wv": P(None, None, "tp", None),
                "wo": P(None, "tp", None, None),  # [L, H, hd, D] row parallel
            },
            **ffn,
        },
        "final_norm": {"scale": P(None)},
    }
    if not tie_embeddings:
        specs["lm_head"] = {"weight": P(None, "tp")}  # [D, V] vocab sharded
    return specs


def specs_to_shardings(specs: Any, mesh: Mesh) -> Any:
    """PartitionSpec pytree → NamedSharding pytree on ``mesh``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def param_shardings(mesh: Mesh, tie_embeddings: bool = True, moe: bool = False):
    """NamedSharding pytree for jit in_shardings / device_put."""
    return specs_to_shardings(param_specs(tie_embeddings, moe), mesh)


def shard_params(params: Any, mesh: Mesh, tie_embeddings: bool = True,
                 moe: bool = False) -> Any:
    """Place a host-side param pytree onto the mesh with the TP layout.
    Handles int8-quantized trees (ops/quant.py): the q tensor takes the
    weight's spec, scales replicate."""
    from lmrs_tpu.ops.quant import match_quantized_specs

    specs = match_quantized_specs(param_specs(tie_embeddings, moe), params)
    return jax.tree.map(jax.device_put, params, specs_to_shardings(specs, mesh))


def batch_spec(seq_sharded: bool = False) -> P:
    """Activation sharding for [B, S, ...] tensors: batch over dp, optionally
    sequence over sp (context parallelism)."""
    return P("dp", "sp") if seq_sharded else P("dp")


def kv_cache_spec() -> P:
    """[L, B, S, K, hd]: batch over dp, kv heads over tp."""
    return P(None, "dp", None, "tp", None)
