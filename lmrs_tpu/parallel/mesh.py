"""Device mesh construction.

Axes (SURVEY.md §2.2 "TPU-native equivalent to build"):

* ``dp`` — data parallel: independent chunk streams (the successor of the
  reference's asyncio request fan-out, llm_executor.py:133-147).  Crosses DCN
  in multi-slice deployments.
* ``tp`` — tensor parallel: attention heads + FFN sharded over ICI.
* ``sp`` — sequence/context parallel: ring attention for single chunks whose
  KV exceeds one chip (SURVEY.md §5.7 tier b).
* ``ep`` — expert parallel: MoE expert axis (ops/moe.py); dispatch einsums
  lower to an all-to-all over this axis under GSPMD.
* ``pp`` — pipeline parallel: layer stages for the 70B tier.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh

from lmrs_tpu.config import MeshConfig

logger = logging.getLogger("lmrs.mesh")


def build_mesh(cfg: MeshConfig | None = None, devices: list | None = None) -> Mesh:
    """Build a Mesh with axes (dp, tp, sp, pp) from available devices.

    With no config, all local devices land on the ``dp`` axis.  Axis sizes
    must multiply to the device count used.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if cfg is None:
        cfg = MeshConfig(dp=n)
    want = cfg.n_devices
    if want > n:
        raise ValueError(f"mesh needs {want} devices ({cfg}), only {n} available")
    arr = np.array(devices[:want]).reshape(cfg.dp, cfg.tp, cfg.sp, cfg.ep, cfg.pp)
    mesh = Mesh(arr, axis_names=cfg.axis_names)
    logger.info("mesh: dp=%d tp=%d sp=%d ep=%d pp=%d over %d %s device(s)",
                cfg.dp, cfg.tp, cfg.sp, cfg.ep, cfg.pp, want, devices[0].platform)
    return mesh


def local_mesh_config() -> MeshConfig:
    """All local devices on dp — the zero-config default."""
    return MeshConfig(dp=len(jax.devices()))


def initialize_distributed(coordinator: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host bring-up over DCN (jax.distributed).

    The reference's closest analog is... nothing: its multi-machine story is
    HTTPS to a vendor (SURVEY.md §5.8).  On TPU pods each host calls this
    before building a global mesh; with no arguments JAX infers the topology
    from the TPU environment.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info("jax.distributed initialized: process %d/%d",
                jax.process_index(), jax.process_count())
