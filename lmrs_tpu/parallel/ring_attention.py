"""Ring attention: causal context parallelism over the ``sp`` mesh axis.

The reference handles long context purely algorithmically — it splits the
transcript *before* the model and map-reduces (SURVEY.md §5.7); there is no
device-level sequence parallelism anywhere in it.  This module is the
device-level tier the TPU build adds underneath: when a single chunk's
sequence (or a fine-tuning batch) is too long for one chip's HBM/FLOPs, the
sequence axis is sharded over ``sp`` and attention runs as a ring —

* every device holds its local Q, K, V sequence block;
* K/V blocks (with their absolute positions) rotate around the ring via
  ``lax.ppermute`` over ICI, one hop per step, ``sp`` steps total;
* each device folds every visiting K/V block into a running flash-style
  online softmax (running max ``m``, running denominator ``l``, accumulator
  ``o``) — numerics identical to dense causal attention, O(S_local) memory;
* masking is positional (block positions travel with the block), so ragged /
  shifted position arrays work unchanged.

XLA overlaps the ppermute with the current block's matmuls (the permuted
block isn't needed until the next iteration), so the ring rides ICI behind
the MXU work.

Composable: the head axis stays shardable over ``tp`` (pass ``head_axis``),
batch over ``dp``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from lmrs_tpu.ops.attention import NEG_INF, _repeat_kv
from lmrs_tpu.utils.jax_compat import shard_map


def ring_attention(
    q: jnp.ndarray,       # [B, Sq_loc, H_loc, hd] local query block
    k: jnp.ndarray,       # [B, Skv_loc, K_loc, hd] local key block
    v: jnp.ndarray,       # [B, Skv_loc, K_loc, hd]
    q_pos: jnp.ndarray,   # [B, Sq_loc] absolute positions of local queries
    kv_pos: jnp.ndarray,  # [B, Skv_loc] absolute positions of local keys
    axis_name: str = "sp",
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Per-shard causal ring attention — call inside shard_map.

    Returns [B, Sq_loc, H_loc, hd] in q.dtype.  Fully-masked queries (none
    possible under causal masking with position 0 present somewhere in the
    ring) would return zeros rather than NaN.
    """
    n = lax.psum(1, axis_name)
    b, sq, h, hd = q.shape
    kh = k.shape[2]
    n_rep = h // kh
    scale = hd ** -0.5

    m = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    o = jnp.zeros((b, h, sq, hd), jnp.float32)

    def fold(m, l, o, k_blk, v_blk, pos_blk):
        kk = _repeat_kv(k_blk, n_rep)
        vv = _repeat_kv(v_blk, n_rep)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kk).astype(jnp.float32) * scale
        if logit_softcap is not None:
            logits = logit_softcap * jnp.tanh(logits / logit_softcap)
        mask = pos_blk[:, None, None, :] <= q_pos[:, None, :, None]  # [B,1,Sq,Skv]
        logits = jnp.where(mask, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # exp(NEG_INF - NEG_INF) = 1 for fully-masked rows: zero those
        # probabilities explicitly instead of trusting the subtraction.
        p = jnp.where(mask, jnp.exp(logits - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        # PV matmul in the value dtype (bf16 → MXU) with f32 accumulation
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(vv.dtype), vv,
            preferred_element_type=jnp.float32)
        return m_new, l, o

    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        m, l, o = fold(m, l, o, k, v, kv_pos)
        if step != n - 1:
            k, v, kv_pos = jax.tree.map(
                lambda x: lax.ppermute(x, axis_name, perm), (k, v, kv_pos))

    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Sq, H, hd]


def ring_attention_sharded(
    q: jnp.ndarray,      # [B, S, H, hd] global
    k: jnp.ndarray,      # [B, S, K, hd]
    v: jnp.ndarray,      # [B, S, K, hd]
    q_pos: jnp.ndarray,  # [B, S] absolute positions
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axis: str = "dp",
    head_axis: str | None = "tp",
    logit_softcap: float | None = None,
    kv_pos: jnp.ndarray | None = None,  # [B, S] key positions, default q_pos
) -> jnp.ndarray:
    """shard_map wrapper: sequence over ``seq_axis``, batch over
    ``batch_axis``, heads over ``head_axis`` (composes with tensor
    parallelism — Q heads and KV heads shard together, so GQA grouping stays
    local to each tp shard).

    ``kv_pos`` lets callers mask ragged/padded keys positionally (ring
    attention has no kv_length mask): give pad keys a position larger than
    any real query position and the causal rule excludes them — the serving
    ring-prefill path (models/transformer.forward_paged) relies on this.
    """
    qkv_spec = P(batch_axis, seq_axis, head_axis, None)
    pos_spec = P(batch_axis, seq_axis)
    fn = shard_map(
        partial(ring_attention, axis_name=seq_axis, logit_softcap=logit_softcap),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, q_pos, kv_pos if kv_pos is not None else q_pos)
