"""Speculative decoding: prompt-lookup drafting + exact-distribution verify.

Summaries quote their source, so the next tokens of a summary frequently
continue an n-gram that already occurred in the prompt (prompt-lookup /
n-gram speculation).  Drafting is FREE — no draft model: find the most
recent earlier occurrence of the last bigram in the token history and
propose the tokens that followed it.  One [B, 1+k] verify forward then
scores all k drafts at once, turning up to k+1 sequential decode steps
into one — a latency win precisely proportional to how repetitive the
decode is, with NO quality change:

Acceptance is the standard speculative-sampling rule with a deterministic
proposal q = delta(draft): accept draft_j with probability p_j(draft_j)
(p = the temperature/top-k/top-p-filtered model distribution,
ops/sampling.filtered_probs); on first rejection sample from the residual
norm(max(p - q, 0)) = p with the rejected token zeroed; if every valid
draft is accepted, sample the bonus token from the model's own p_k.  This
preserves the output distribution EXACTLY (greedy rows degenerate to
"accept while draft == argmax"), so speculation is purely a scheduling
optimization.  The reference has no model-side decoding at all — this is
serving-stack surface with no reference counterpart.

Everything here is trace-friendly (static k, where-masks, no data-dependent
shapes) so it runs inside the scheduler's on-device decode block scan.

Cost note: the verify forward runs through forward_paged(multi_decode=True)
— the ragged multi-token kernel (ops/paged_attention.paged_decode_pallas_multi)
writes all k+1 tokens' K/V and attends them with per-token causality in ONE
page walk per layer, the multi-query extension of the decode kernel.  The
round-2 measurement that made speculation a 12x loss (verify materialized
the full page window per layer per step, docs/PERF.md) is specifically what
this path removes; whether speculation WINS still depends on acceptance
rate, so ``speculate_k`` stays opt-in until the hardware ABBA lands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def draft_lookup(
    buf: jnp.ndarray,   # [B, L] int32 token history (prompt + generated)
    hist_len: jnp.ndarray,  # [B] valid tokens in buf
    k: int,
    pad_id: int = 0,
    n: int = 2,         # n-gram length to match (EngineConfig.speculate_ngram)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Propose k draft tokens per row by n-gram lookup over the history.

    Finds the most recent position i with buf[i : i+n] equal to the LAST n
    history tokens and drafts the k tokens that followed it.  Returns
    (draft [B, k], n_valid [B]) with n_valid == 0 when the row has no
    earlier occurrence (or < n tokens).  Longer n-grams collide less —
    decisive for byte-level vocabularies, where bigrams recur everywhere
    and drafts then continue the WRONG earlier occurrence (measured on the
    trained copy-task model, docs/PERF.md round 4: acceptance ~1.0/step at
    n=2 vs ~k at n=3 on verbatim-quoting decodes).
    """
    b, L = buf.shape
    w = L - (n - 1)  # candidate n-gram start positions
    idx = jnp.arange(w)[None, :]
    match = jnp.ones((b, w), bool)
    for j in range(n):
        cj = jnp.take_along_axis(
            buf, jnp.maximum(hist_len - n + j, 0)[:, None], 1)  # [B, 1]
        match &= buf[:, j: j + w] == cj
    # exclude the query n-gram itself and anything whose draft window would
    # start at/after the history end
    match &= idx + n < hist_len[:, None]
    has = jnp.any(match, axis=1) & (hist_len >= n)
    # most recent match: argmax over idx * match.  A match near the buffer
    # end (the LIVE context — exactly the occurrence we want) used to be
    # excluded because its k-token window ran past L and the slice clip
    # would slide onto unrelated tokens; pad the buffer by k instead so the
    # window always has room and n_valid clips to the real history.
    pos = jnp.max(jnp.where(match, idx, -1), axis=1)  # [B], -1 if none

    start = pos + n  # draft source window; < hist_len whenever has
    bufp = jnp.pad(buf, ((0, 0), (0, k)), constant_values=pad_id)
    draft = jax.vmap(
        lambda row, s: jax.lax.dynamic_slice_in_dim(row, s, k)
    )(bufp, jnp.maximum(start, 0))
    n_valid = jnp.where(has, jnp.clip(hist_len - start, 0, k), 0)
    draft = jnp.where(jnp.arange(k)[None, :] < n_valid[:, None], draft, pad_id)
    return draft, n_valid.astype(jnp.int32)


def draft_tree_lookup(
    buf: jnp.ndarray,       # [B, L] int32 token history (prompt + generated)
    hist_len: jnp.ndarray,  # [B] valid tokens in buf
    k: int,                 # chain depth (tokens per chain)
    width: int,             # chains per row (tree branching at the root)
    pad_id: int = 0,
    n: int = 2,
    depth: jnp.ndarray | None = None,  # [B] per-row depth clamp (adaptive)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Propose a token TREE per row: the ``width`` most recent n-gram
    matches each contribute a depth-``k`` continuation chain branching at
    the root (EAGLE-Pangu / SpecInfer shape, flattened as root-branching
    chains — parent of chain token 0 is the current token, parent of chain
    token j is chain token j-1).

    A linear draft wastes the whole chain on the first miss; when the last
    n-gram recurs at several earlier positions the continuations DIVERGE,
    and verifying the top-``width`` of them in one pass keeps the step
    alive on whichever branch the model actually takes.

    Returns (chains [B, width, k], n_valid [B, width]) ordered most recent
    match first; chains whose FIRST token duplicates a more recent chain's
    are dropped (n_valid 0) — under sequential multi-candidate rejection a
    duplicate root candidate has zero residual mass, so it could never be
    accepted anyway.
    """
    b, L = buf.shape
    w = L - (n - 1)
    idx = jnp.arange(w)[None, :]
    match = jnp.ones((b, w), bool)
    for j in range(n):
        cj = jnp.take_along_axis(
            buf, jnp.maximum(hist_len - n + j, 0)[:, None], 1)
        match &= buf[:, j: j + w] == cj
    match &= idx + n < hist_len[:, None]
    # top-`width` most recent match positions, descending (non-matches -1)
    pos, _ = jax.lax.top_k(jnp.where(match, idx, -1), width)  # [B, W]
    has = (pos >= 0) & (hist_len[:, None] >= n)

    start = pos + n
    bufp = jnp.pad(buf, ((0, 0), (0, k)), constant_values=pad_id)
    chains = jax.vmap(jax.vmap(
        lambda row, s: jax.lax.dynamic_slice_in_dim(row, s, k),
        in_axes=(None, 0),
    ))(bufp, jnp.maximum(start, 0))                 # [B, W, k]
    n_valid = jnp.where(has, jnp.clip(hist_len[:, None] - start, 0, k), 0)
    if depth is not None:
        n_valid = jnp.minimum(n_valid, depth[:, None])
    # dedup identical root candidates (keep the most recent occurrence)
    for c2 in range(1, width):
        dup = jnp.zeros((b,), bool)
        for c1 in range(c2):
            dup |= (chains[:, c2, 0] == chains[:, c1, 0]) & (n_valid[:, c1] > 0)
        n_valid = n_valid.at[:, c2].set(jnp.where(dup, 0, n_valid[:, c2]))
    chains = jnp.where(
        jnp.arange(k)[None, None, :] < n_valid[:, :, None], chains, pad_id)
    return chains, n_valid.astype(jnp.int32)


def verify_tree(
    probs: jnp.ndarray,    # [B, 1+W*k, V] filtered model dist per tree node
    chains: jnp.ndarray,   # [B, W, k] proposed chains (draft_tree_lookup)
    n_valid: jnp.ndarray,  # [B, W] usable depth per chain
    key: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Exact-distribution tree acceptance (deterministic proposals).

    Node layout matches the span the scheduler dispatches: slot 0 is the
    current token's output (the root distribution), slot ``1 + c*k + j``
    is the output AFTER chain c's token j.  Acceptance is two-stage:

    * **root**: sequential multi-candidate rejection over the chains'
      first tokens — accept candidate c with probability residual(x_c)
      under the running residual, else zero x_c and renormalize (the
      SpecInfer rule; with deterministic proposals this preserves the
      root distribution exactly, and greedy rows degenerate to "pick the
      chain whose first token is the argmax");
    * **within the winning chain**: the linear verify_tokens rule — accept
      token j with probability p_{node j-1}(token j), first rejection
      samples the residual there, full acceptance samples the bonus.

    Returns (emit [B, k+1], count [B], chain [B], depth [B]): row b's new
    tokens are emit[b, :count[b]]; ``chain`` is the winning chain index
    (-1 when every root candidate rejected) and ``depth`` the accepted
    draft-token count (count - 1) — the scheduler needs both to heal the
    KV columns of a non-first chain and to feed the acceptance EMA.
    """
    b, n_nodes, v = probs.shape
    _, W, k = chains.shape
    key_root, key_chain, key_final = jax.random.split(key, 3)
    u_root = jax.random.uniform(key_root, (b, W))
    u_chain = jax.random.uniform(key_chain, (b, k))
    rows = jnp.arange(b)

    # root: sequential rejection over candidate first-tokens
    residual = probs[:, 0]
    chosen = jnp.full((b,), -1, jnp.int32)
    for c in range(W):
        x = chains[:, c, 0]
        px = jnp.take_along_axis(residual, x[:, None], 1)[:, 0]
        live = (n_valid[:, c] > 0) & (chosen < 0)
        acc = live & (u_root[:, c] < px)
        chosen = jnp.where(acc, c, chosen)
        rej = live & ~acc
        zeroed = residual.at[rows, x].set(0.0)
        zsum = jnp.maximum(zeroed.sum(-1, keepdims=True), 1e-20)
        residual = jnp.where(rej[:, None], zeroed / zsum, residual)

    # winning chain's tokens / validity / node distributions
    cs = jnp.maximum(chosen, 0)
    ctoks = jnp.take_along_axis(chains, cs[:, None, None], 1)[:, 0]   # [B, k]
    cvalid = jnp.take_along_axis(n_valid, cs[:, None], 1)[:, 0]       # [B]
    off = 1 + cs[:, None] * k + jnp.arange(k)[None, :]                # [B, k]
    cprobs = jnp.take_along_axis(probs, off[:, :, None], 1)           # [B,k,V]

    # within-chain acceptance: token j (j >= 1) vs the node j-1 dist
    p_next = jnp.take_along_axis(
        cprobs[:, : k - 1], ctoks[:, 1:, None], 2)[:, :, 0]           # [B,k-1]
    ok = (u_chain[:, : k - 1] < p_next) \
        & (jnp.arange(1, k)[None, :] < cvalid[:, None])
    a = 1 + jnp.sum(jnp.cumprod(ok.astype(jnp.int32), 1), 1)
    a = jnp.minimum(a, jnp.maximum(cvalid, 1))  # accepted tokens, in [1,cv]

    # final token: residual at the rejection node, bonus on full accept
    p_fin = jnp.take_along_axis(cprobs, (a - 1)[:, None, None], 1)[:, 0]
    rejected = a < cvalid
    tok_a = jnp.take_along_axis(ctoks, jnp.minimum(a, k - 1)[:, None], 1)[:, 0]
    resid2 = p_fin.at[rows, tok_a].set(0.0)
    resid2 = resid2 / jnp.maximum(resid2.sum(-1, keepdims=True), 1e-20)
    dist = jnp.where(rejected[:, None], resid2, p_fin)
    none = chosen < 0  # no root candidate survived: sample the root residual
    dist = jnp.where(none[:, None], residual, dist)
    final = jax.random.categorical(
        key_final, jnp.log(jnp.maximum(dist, 1e-20)), -1)

    acc_n = jnp.where(none, 0, a)
    slots = jnp.arange(k + 1)[None, :]
    emit = jnp.where(slots < acc_n[:, None],
                     jnp.pad(ctoks, ((0, 0), (0, 1))), 0)
    emit = jnp.where(slots == acc_n[:, None], final[:, None], emit)
    return (emit.astype(jnp.int32), (acc_n + 1).astype(jnp.int32),
            chosen.astype(jnp.int32), acc_n.astype(jnp.int32))


def verify_tokens(
    probs: jnp.ndarray,   # [B, k+1, V] filtered model distribution per slot
    draft: jnp.ndarray,   # [B, k] proposed tokens
    n_valid: jnp.ndarray, # [B] usable draft prefix length
    key: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative-sampling acceptance (deterministic proposal).

    Returns (emit [B, k+1], count [B]): row b's new tokens are
    emit[b, :count[b]] — the accepted draft prefix plus one token that is
    either the residual sample at the rejection slot or the bonus sample
    when every valid draft was accepted.  1 <= count <= k+1.
    """
    b, kp1, v = probs.shape
    k = kp1 - 1
    key_u, key_s = jax.random.split(key)
    u = jax.random.uniform(key_u, (b, k))

    # p_j(draft_j) for each draft slot
    p_draft = jnp.take_along_axis(
        probs[:, :k], draft[:, :, None], axis=2
    )[:, :, 0]  # [B, k]
    ok = (u < p_draft) & (jnp.arange(k)[None, :] < n_valid[:, None])
    # accepted prefix length: first failure cuts everything after it
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)  # [B, k]
    a = jnp.sum(acc, axis=1)  # [B] in [0, n_valid]

    # distribution for the final token, taken at slot a
    p_final = jnp.take_along_axis(probs, a[:, None, None], axis=1)[:, 0]  # [B,V]
    rejected = a < n_valid  # a rejection happened at slot a
    draft_a = jnp.take_along_axis(draft, jnp.minimum(a, k - 1)[:, None], 1)[:, 0]
    residual = p_final.at[jnp.arange(b), draft_a].set(0.0)
    residual = residual / jnp.maximum(residual.sum(-1, keepdims=True), 1e-20)
    dist = jnp.where(rejected[:, None], residual, p_final)
    final = jax.random.categorical(key_s, jnp.log(jnp.maximum(dist, 1e-20)), -1)

    # emit = draft[:a] + [final]
    slots = jnp.arange(kp1)[None, :]
    emit = jnp.where(slots < a[:, None],
                     jnp.pad(draft, ((0, 0), (0, 1))),
                     0)
    emit = jnp.where(slots == a[:, None], final[:, None], emit)
    return emit.astype(jnp.int32), (a + 1).astype(jnp.int32)
