"""Speculative decoding: prompt-lookup drafting + exact-distribution verify.

Summaries quote their source, so the next tokens of a summary frequently
continue an n-gram that already occurred in the prompt (prompt-lookup /
n-gram speculation).  Drafting is FREE — no draft model: find the most
recent earlier occurrence of the last bigram in the token history and
propose the tokens that followed it.  One [B, 1+k] verify forward then
scores all k drafts at once, turning up to k+1 sequential decode steps
into one — a latency win precisely proportional to how repetitive the
decode is, with NO quality change:

Acceptance is the standard speculative-sampling rule with a deterministic
proposal q = delta(draft): accept draft_j with probability p_j(draft_j)
(p = the temperature/top-k/top-p-filtered model distribution,
ops/sampling.filtered_probs); on first rejection sample from the residual
norm(max(p - q, 0)) = p with the rejected token zeroed; if every valid
draft is accepted, sample the bonus token from the model's own p_k.  This
preserves the output distribution EXACTLY (greedy rows degenerate to
"accept while draft == argmax"), so speculation is purely a scheduling
optimization.  The reference has no model-side decoding at all — this is
serving-stack surface with no reference counterpart.

Everything here is trace-friendly (static k, where-masks, no data-dependent
shapes) so it runs inside the scheduler's on-device decode block scan.

Cost note: the verify forward runs through forward_paged(multi_decode=True)
— the ragged multi-token kernel (ops/paged_attention.paged_decode_pallas_multi)
writes all k+1 tokens' K/V and attends them with per-token causality in ONE
page walk per layer, the multi-query extension of the decode kernel.  The
round-2 measurement that made speculation a 12x loss (verify materialized
the full page window per layer per step, docs/PERF.md) is specifically what
this path removes; whether speculation WINS still depends on acceptance
rate, so ``speculate_k`` stays opt-in until the hardware ABBA lands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def draft_lookup(
    buf: jnp.ndarray,   # [B, L] int32 token history (prompt + generated)
    hist_len: jnp.ndarray,  # [B] valid tokens in buf
    k: int,
    pad_id: int = 0,
    n: int = 2,         # n-gram length to match (EngineConfig.speculate_ngram)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Propose k draft tokens per row by n-gram lookup over the history.

    Finds the most recent position i with buf[i : i+n] equal to the LAST n
    history tokens and drafts the k tokens that followed it.  Returns
    (draft [B, k], n_valid [B]) with n_valid == 0 when the row has no
    earlier occurrence (or < n tokens).  Longer n-grams collide less —
    decisive for byte-level vocabularies, where bigrams recur everywhere
    and drafts then continue the WRONG earlier occurrence (measured on the
    trained copy-task model, docs/PERF.md round 4: acceptance ~1.0/step at
    n=2 vs ~k at n=3 on verbatim-quoting decodes).
    """
    b, L = buf.shape
    w = L - (n - 1)  # candidate n-gram start positions
    idx = jnp.arange(w)[None, :]
    match = jnp.ones((b, w), bool)
    for j in range(n):
        cj = jnp.take_along_axis(
            buf, jnp.maximum(hist_len - n + j, 0)[:, None], 1)  # [B, 1]
        match &= buf[:, j: j + w] == cj
    # exclude the query n-gram itself and anything whose draft window would
    # start at/after the history end
    match &= idx + n < hist_len[:, None]
    # a match so close to the buffer end that its k-token continuation
    # window would run past L can't be drafted from (the clip below would
    # silently slide the window onto unrelated tokens) — require room
    match &= idx + n <= L - k
    has = jnp.any(match, axis=1) & (hist_len >= n)
    # most recent match: argmax over idx * match
    pos = jnp.max(jnp.where(match, idx, -1), axis=1)  # [B], -1 if none

    start = jnp.clip(pos + n, 0, L - k)  # draft source window
    draft = jax.vmap(
        lambda row, s: jax.lax.dynamic_slice_in_dim(row, s, k)
    )(buf, start)
    n_valid = jnp.where(has, jnp.minimum(k, hist_len - start), 0)
    draft = jnp.where(jnp.arange(k)[None, :] < n_valid[:, None], draft, pad_id)
    return draft, n_valid.astype(jnp.int32)


def verify_tokens(
    probs: jnp.ndarray,   # [B, k+1, V] filtered model distribution per slot
    draft: jnp.ndarray,   # [B, k] proposed tokens
    n_valid: jnp.ndarray, # [B] usable draft prefix length
    key: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative-sampling acceptance (deterministic proposal).

    Returns (emit [B, k+1], count [B]): row b's new tokens are
    emit[b, :count[b]] — the accepted draft prefix plus one token that is
    either the residual sample at the rejection slot or the bonus sample
    when every valid draft was accepted.  1 <= count <= k+1.
    """
    b, kp1, v = probs.shape
    k = kp1 - 1
    key_u, key_s = jax.random.split(key)
    u = jax.random.uniform(key_u, (b, k))

    # p_j(draft_j) for each draft slot
    p_draft = jnp.take_along_axis(
        probs[:, :k], draft[:, :, None], axis=2
    )[:, :, 0]  # [B, k]
    ok = (u < p_draft) & (jnp.arange(k)[None, :] < n_valid[:, None])
    # accepted prefix length: first failure cuts everything after it
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)  # [B, k]
    a = jnp.sum(acc, axis=1)  # [B] in [0, n_valid]

    # distribution for the final token, taken at slot a
    p_final = jnp.take_along_axis(probs, a[:, None, None], axis=1)[:, 0]  # [B,V]
    rejected = a < n_valid  # a rejection happened at slot a
    draft_a = jnp.take_along_axis(draft, jnp.minimum(a, k - 1)[:, None], 1)[:, 0]
    residual = p_final.at[jnp.arange(b), draft_a].set(0.0)
    residual = residual / jnp.maximum(residual.sum(-1, keepdims=True), 1e-20)
    dist = jnp.where(rejected[:, None], residual, p_final)
    final = jax.random.categorical(key_s, jnp.log(jnp.maximum(dist, 1e-20)), -1)

    # emit = draft[:a] + [final]
    slots = jnp.arange(kp1)[None, :]
    emit = jnp.where(slots < a[:, None],
                     jnp.pad(draft, ((0, 0), (0, 1))),
                     0)
    emit = jnp.where(slots == a[:, None], final[:, None], emit)
    return emit.astype(jnp.int32), (a + 1).astype(jnp.int32)
