"""Mixture-of-experts FFN: top-k routing with capacity-bounded dispatch.

TPU-first formulation (GShard/Switch style): routing is expressed as two
einsums against one-hot dispatch/combine tensors, so the whole layer is
MXU matmuls with static shapes — no scatter, no dynamic shapes, scannable
and shardable.  Expert weights carry a leading expert axis that shards over
the ``ep`` mesh axis (parallel.sharding); under GSPMD the dispatch einsum
lowers to an all-to-all over ``ep``.

The reference has no experts (dense API models only; SURVEY.md §2.2 "EP:
out of scope unless a MoE checkpoint is adopted; design mesh axes so EP can
be added") — this module plus the ``ep`` axis is that design carried out.

Capacity semantics: each expert processes at most C tokens per call
(C = capacity_factor * N * k / E); overflow tokens lose that expert's
contribution (their residual stream passes through unchanged) — the
standard trade for static shapes on TPU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lmrs_tpu.config import ModelConfig
from lmrs_tpu.ops.quant import qeinsum


def expert_capacity(n_tokens: int, cfg: ModelConfig) -> int:
    """Static per-expert token capacity for a call with ``n_tokens`` tokens."""
    k = min(cfg.n_experts_per_token, cfg.n_experts)
    c = math.ceil(cfg.expert_capacity_factor * n_tokens * k / cfg.n_experts)
    return max(1, min(n_tokens, c))


def moe_mlp(mp, cfg: ModelConfig, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """MoE SwiGLU FFN.  x [B,S,D] -> (out [B,S,D], aux_loss scalar f32).

    ``mp`` holds one layer's expert params: router [D,E], w_gate/w_up
    [E,D,F], w_down [E,F,D].  The aux loss is the Switch load-balancing
    term E * Σ_e f_e·P_e (≈1 when balanced), from top-1 assignments.
    """
    dt = x.dtype
    b, s, d = x.shape
    e = cfg.n_experts
    k = min(cfg.n_experts_per_token, e)
    n = b * s
    xt = x.reshape(n, d)

    # --- routing (f32 for a stable softmax) ---
    logits = jnp.einsum("nd,de->ne", xt.astype(jnp.float32),
                        mp["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)              # [N,E]
    gate_vals, gate_idx = jax.lax.top_k(probs, k)        # [N,k]
    gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

    # --- capacity assignment: slot-major cumsum so primary (slot-0)
    # assignments claim capacity before secondary ones ---
    c = expert_capacity(n, cfg)
    expert_flat = gate_idx.T.reshape(k * n)              # [kN] slot-major
    onehot = jax.nn.one_hot(expert_flat, e, dtype=jnp.int32)
    pos = ((jnp.cumsum(onehot, axis=0) - onehot) * onehot).sum(-1)  # [kN]
    keep = (pos < c).astype(jnp.float32)
    gates_flat = gate_vals.T.reshape(k * n) * keep

    # dispatch/combine one-hots: [kN,E,C] -> merge the k slots -> [N,E,C]
    slot_oh = jax.nn.one_hot(jnp.clip(pos, 0, c - 1), c, dtype=jnp.float32)
    dispatch = (onehot.astype(jnp.float32) * keep[:, None])[:, :, None] * slot_oh[:, None, :]
    combine = gates_flat[:, None, None] * dispatch
    dispatch = dispatch.reshape(k, n, e, c).sum(0)
    combine = combine.reshape(k, n, e, c).sum(0)

    # --- expert FFN: all-MXU einsums over [E,C,·] ---
    xin = jnp.einsum("nd,nec->ecd", xt, dispatch.astype(dt))
    gate_h = qeinsum("ecd,edf->ecf", xin, mp["w_gate"], dt)
    up = qeinsum("ecd,edf->ecf", xin, mp["w_up"], dt)
    from lmrs_tpu.models.transformer import gate_act

    ff = gate_act(cfg, gate_h).astype(dt) * up
    y = qeinsum("ecf,efd->ecd", ff, mp["w_down"], dt)
    out = jnp.einsum("nec,ecd->nd", combine.astype(dt), y)

    # --- Switch load-balance loss ---
    f = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(f * probs.mean(0))
    return out.reshape(b, s, d), aux
