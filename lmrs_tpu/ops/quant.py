"""Int8 weight-only quantization.

TPU decode is HBM-bandwidth-bound (SURVEY.md §7.4: the scheduler/kernel
design problem is feeding the MXU, not FLOPs) — storing the big projection
matrices as int8 halves the bytes streamed per decode step vs bfloat16.
Dequantization is a convert+multiply that XLA fuses into the consuming
matmul, so the bf16 tensor never materializes in HBM.

Scheme: symmetric per-output-channel scales.  For a weight of shape
[..., out], ``s = max|w| / 127`` over all axes except the last, ``q =
round(w / s)`` as int8; a quantized leaf is the dict ``{"q": int8, "s":
f32}``.  Weights stay in this form in the param pytree; every use site in
models/transformer.py goes through ``deq`` (a no-op passthrough for plain
arrays, so dense/bf16 params take the same code path).

The reference has no weights at all (the model is behind OpenAI's API) —
this is serving-stack surface with no reference counterpart.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf.keys()) == {"q", "s"}


def quantize_weight(w: jnp.ndarray, axes: tuple[int, ...]) -> dict[str, jnp.ndarray]:
    """Symmetric int8 quantization; ``axes`` are the contracting axes of the
    consuming matmul — scales are shared only along them, so every output
    channel (and every stacked layer / expert) gets its own scale."""
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=axes, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def deq(x: Any, dtype) -> jnp.ndarray:
    """Dequantize a {"q","s"} leaf to ``dtype``; plain arrays pass through.
    The convert*scale is an elementwise producer of the consuming matmul —
    XLA fuses it, so only int8 is read from HBM.  Matmul call sites should
    prefer ``qeinsum``, which folds the scale into the matmul OUTPUT
    instead of paying it per weight element."""
    if is_quantized(x):
        return (x["q"].astype(jnp.float32) * x["s"]).astype(dtype)
    return x


def qeinsum(spec: str, x: jnp.ndarray, leaf: Any, dtype) -> jnp.ndarray:
    """``einsum(spec, x, W)`` where W may be a quantized {"q","s"} leaf.

    Quantized weights contract as raw int8 values (converted to ``dtype``
    — lossless, |q| <= 127 fits bf16's 8-bit mantissa exactly) and the
    scale multiplies the OUTPUT: scales are per-output-channel by
    construction (``quantize_weight`` shares them only along the consuming
    matmul's contracting axes, which are 1-sized in ``s``), so
    ``einsum(x, q*s) == einsum(x, q) * s`` with ``s`` broadcasting
    right-aligned onto the result.  This moves the dequant multiply from
    one-per-WEIGHT-element — VPU work proportional to weight bytes, which
    measurably throttles the int8 weight stream below HBM rate at 8B
    shapes (docs/PERF.md round 5) — to one-per-OUTPUT-element (~D× fewer
    at decode), and drops a rounding step: the old path rounded q*s to
    bf16 per element before the MXU, this one feeds exact integers.
    """
    if is_quantized(leaf):
        y = jnp.einsum(spec, x, leaf["q"].astype(dtype))
        return (y * leaf["s"]).astype(dtype)
    return jnp.einsum(spec, x, leaf)


# Weight names eligible for quantization: the large projection matrices.
# Embeddings stay full-precision (gather path), router stays full-precision
# (tiny, and routing decisions are precision-sensitive), norms are vectors.
_QUANT_NAMES = frozenset({"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"})


def _contract_axes(name: str, ndim: int) -> tuple[int, ...]:
    """Contracting axes of the matmul that consumes each stacked weight:
    wq/wk/wv [L,D,H,hd] contract D; wo [L,H,hd,D] contracts (H,hd); dense
    FFN [L,in,out] contracts axis 1; MoE FFN [L,E,in,out] contracts axis 2;
    lm_head [D,V] contracts D."""
    if name == "wo":
        return (1, 2)
    if name in ("wq", "wk", "wv"):
        return (1,)
    if name in ("w_gate", "w_up", "w_down"):
        return (2,) if ndim == 4 else (1,)
    if name == "lm_head":
        return (0,)
    raise ValueError(f"no contraction rule for weight {name!r}")


def _walk_quantizable(params: Any, qfn, plain) -> Any:
    """Shared eligibility walk: eligible projection leaves map through
    ``qfn(leaf, contract_axes)``, everything else through ``plain(leaf)``.
    ``lm_head.weight`` is included; ``embed.weight`` is not."""
    def walk(tree: Any, path: tuple[str, ...]) -> Any:
        if isinstance(tree, dict) and not is_quantized(tree):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        name = path[-1] if path else ""
        if name in _QUANT_NAMES:
            return qfn(tree, _contract_axes(name, tree.ndim))
        if len(path) >= 2 and path[-2] == "lm_head":
            return qfn(tree, _contract_axes("lm_head", tree.ndim))
        return plain(tree)

    return walk(params, ())


def quantize_params(params: Any) -> Any:
    """Quantize the projection weights of a transformer param pytree.

    Returns a new pytree where eligible leaves become {"q","s"} dicts;
    structure is otherwise identical (scan/shard/jit all still work)."""
    return _walk_quantizable(params, quantize_weight, lambda x: x)


def random_quantized_init(cfg, seed: int) -> Any:
    """Random param tree in ALREADY-QUANTIZED form, built host-side with
    numpy — throughput-identical to quantize(random-init) without ever
    materializing the full-precision tree.  Needed for quantized
    random-init at 8B shape (bench-8b): the 16 GB bf16 tree cannot
    coexist with anything on a 16 GB chip, and under the axon tunnel no
    jax CPU backend is registered to stage it on.  Structure comes from
    ``jax.eval_shape`` over the real initializer, so it can never drift
    from ``init_params``."""
    import numpy as np

    from lmrs_tpu.models.transformer import init_params

    shapes = jax.eval_shape(
        lambda k: init_params(cfg, k), jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)

    def qfn(sd, axes):
        s_shape = tuple(1 if a in axes else n
                        for a, n in enumerate(sd.shape))
        return {"q": rng.integers(-127, 128, sd.shape, dtype=np.int8),
                "s": np.full(s_shape, 2e-4, np.float32)}

    def plain(sd):
        arr = rng.standard_normal(sd.shape, dtype=np.float32) * 0.02
        return arr.astype(sd.dtype)

    return _walk_quantizable(shapes, qfn, plain)


def quantized_bytes(params: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def match_quantized_specs(specs: Any, params: Any) -> Any:
    """Adapt a PartitionSpec pytree to a quantized param pytree: wherever a
    param leaf is {"q","s"}, the spec leaf P becomes {"q": P, "s": P(...)}
    (scales replicated — they are tiny)."""
    from jax.sharding import PartitionSpec as P

    def walk(spec: Any, param: Any) -> Any:
        if is_quantized(param):
            return {"q": spec, "s": P(*([None] * param["s"].ndim))}
        if isinstance(param, dict):
            return {k: walk(spec[k], param[k]) for k in param}
        return spec

    return walk(specs, params)


# ------------------------------------------------------- KV-cache int8 (r3)
#
# Decode's slope term is the KV page walk (docs/PERF.md round 3: 3.5
# us/live-token vs a 2.16 us HBM floor); int8 pages halve the streamed
# bytes AND double the tokens each HBM GiB holds.  Scheme: symmetric int8
# with one scale per (slot, kv head, channel), fixed at prefill time from
# the prompt's K/V stats (per-channel handles K's channel-consistent
# outliers — the KIVI finding; the per-slot factor tracks sequence-level
# magnitude).  Decode/verify tokens quantize with the SAME slot scale
# (clamped): requantizing written pages on scale change is a non-starter.
# Scales live in scheduler-owned [L, B, K, hd] f32 buffers threaded
# through the dispatch programs — VMEM-resident at kernel time, no
# per-page scale DMAs (the layout analysis that rejected per-token scale
# pools, docs/PERF.md round 3).


def kv_scale_from(kv: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Per-(row, kv head, channel) symmetric scale from a prefill's K or V.

    kv: [B, S, K, hd]; valid: [B, S] bool (True where the token is a real
    prompt token — padding and out-of-prompt rows must not inflate the
    scale).  Returns [B, K, hd] f32, floored so dequant never divides by
    ~0 on all-masked rows."""
    a = jnp.where(valid[:, :, None, None], jnp.abs(kv.astype(jnp.float32)), 0.0)
    return jnp.maximum(jnp.max(a, axis=1) / 127.0, 1e-8)


def kv_quant_tokens(kv: jnp.ndarray, token_scales: jnp.ndarray) -> jnp.ndarray:
    """Quantize K or V rows with PER-TOKEN scales: the packed-prefill path,
    where one [1, S] row holds many prompts and each token quantizes with
    its own segment's (slot's) scales.  kv [B, S, K, hd],
    token_scales [B, S, K, hd] (or broadcastable) -> int8 [B, S, K, hd].
    THE int8 KV quantization rule — ``kv_quant`` delegates here so the
    packed and per-row paths can never diverge."""
    q = jnp.round(kv.astype(jnp.float32) / token_scales)
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def kv_quant(kv: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Quantize K or V rows with their row scales.  kv [B, S, K, hd],
    scale [B, K, hd] -> int8 [B, S, K, hd] (clipped: decode tokens reuse
    the prefill-time scale, so out-of-range values saturate)."""
    return kv_quant_tokens(kv, scale[:, None])


def kv_dequant(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """Dequantize gathered int8 KV.  q [B, T, K, hd], scale [B, K, hd]."""
    return (q.astype(jnp.float32) * scale[:, None]).astype(dtype)
