"""Rotary position embeddings.

Precomputed sin/cos tables (static shapes, computed once per compile) applied
to query/key heads.  Table layout [S, head_dim/2] keeps the apply step a pure
elementwise op that XLA fuses into the attention projections."""

from __future__ import annotations

import jax.numpy as jnp


def rope_table(max_seq_len: int, head_dim: int, theta: float = 10000.0):
    """Returns (sin, cos) tables of shape [max_seq_len, head_dim // 2]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # [S, half]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(
    x: jnp.ndarray,  # [..., S, n_heads, head_dim]
    positions: jnp.ndarray,  # [..., S] absolute positions
    sin: jnp.ndarray,
    cos: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate pairs (x[..2i], x[..2i+1]) by the position angle.

    Uses the "split halves" convention (first half paired with second half),
    matching Llama's reference formulation.
    """
    half = x.shape[-1] // 2
    s = sin[positions]  # [..., S, half]
    c = cos[positions]
    s = s[..., None, :]  # broadcast over heads: [..., S, 1, half]
    c = c[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
