"""Paged decode attention: single-token queries against a paged KV pool.

Two implementations with one contract:

* ``paged_decode_xla`` — gather-based fallback (any platform): gathers the
  slot's pages into a contiguous [B, W, K, hd] window and runs masked
  attention.  Cost ∝ the (bucketed) window, independent of real lengths.
* ``paged_decode_pallas`` — ragged Pallas kernel (TPU): grid over (batch,);
  each program walks ONLY its row's live pages — a dynamic ``fori_loop``
  bound from SMEM — DMA-ing K/V pages HBM→VMEM and folding them into an
  online softmax.  Decode cost is proportional to the tokens actually in
  the cache (the Ragged Paged Attention idea, PAPERS.md), which is the
  whole point of paging: decode is HBM-bandwidth-bound and the bandwidth
  spent is exactly the live KV bytes.

The kv-head axis is folded INTO each program as a statically-unrolled loop
(round 3; previously grid=(B, K)): one program per batch row walks all
kv heads' pages through one double-buffered DMA pipeline that crosses head
boundaries.  At bench shape this cuts programs/step 8× (3,456 → 432 per
model step) — the round-2 decode fixed cost was diagnosed as program +
small-DMA launch latency, not bandwidth (docs/PERF.md round 2: 9.39 ms
fitted fixed cost vs a 2.49 ms weight-stream floor).

The BATCH axis folds the same way with ``row_group > 1`` (round 6, the
multi-row page walk): one program walks a GROUP of G rows through the
shared pipeline — grid=(B/G,) — priming row r+1's first page and running
its RMW cycle inside row r's compute bubbles (``_make_group_kernel``).
The per-program fixed cost that grid=(B,) pays per ROW is paid per GROUP;
at the 8B bench shape ~2.8 ms of the decode step was this per-row cost
(24 rows × 32 layers × 3.6 µs — docs/PERF.md r5 intercept decomposition),
which G-row programs divide by up to G.  Callers pass a host-side
length-balanced row order (``balanced_row_order``) so one straggler row
cannot serialize a whole group.  ``row_group=1`` (the LMRS_MULTIROW=0
kill switch) is byte-for-byte the previous per-row grid.

Cache layout: [P_total, K, page_size, hd] (PAGE-major, round 3: one page's
ALL kv heads are a single contiguous [K, page_size, hd] DMA — the
head-major layout issued kh separate per-head page DMAs, and the decode
fixed-cost split measured the walk DMA-issue-bound, not bandwidth-bound;
docs/PERF.md round 3).  P_total flattens the layer axis into the page axis
— engine/kv_cache.PagedKVCache — and callers pass GLOBAL page ids.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lmrs_tpu.utils.jax_compat import shard_map

NEG_INF = -1e30


def balanced_row_order(lengths, row_group: int) -> np.ndarray:
    """Host-side length-balanced row→group assignment for the multi-row
    decode kernels (``row_group > 1``): a permutation of rows such that
    each consecutive size-G slice — one kernel program's group — carries a
    near-equal total live length.  Within a group the rows share ONE DMA
    pipeline and walk sequentially, so an unbalanced assignment lets a
    straggler row serialize its whole group (and, under megacore grid
    partitioning, unbalanced groups serialize the cores).

    LPT greedy: rows sorted by length descending, each placed in the
    group with the smallest running total that still has a free seat.
    When ``len(lengths) % row_group != 0`` the LAST group keeps the short
    seat count (the kernel pads the trailing rows with inactive ones).
    Deterministic — ties break on row index — so greedy A/B runs
    reproduce exactly.  Returns ``perm`` with dispatch row i holding
    original row ``perm[i]``: gather inputs by ``perm``, scatter outputs
    back through it.  Pure numpy; never traced.
    """
    lengths = np.asarray(lengths)
    b = len(lengths)
    g = max(1, int(row_group))
    n_groups = max(1, -(-b // g))
    # identity fast path: one group, or uniform lengths (the common
    # equal-chunk map workload) — balancing is a no-op, and returning
    # identity lets the scheduler skip the reorder entirely (it also
    # keeps sampled rows' draws aligned with the LMRS_MULTIROW=0 A/B
    # control when there was nothing to balance)
    if n_groups == 1 or (b and (lengths == lengths[0]).all()):
        return np.arange(b, dtype=np.int64)
    order = np.argsort(-lengths, kind="stable")
    sums = np.zeros(n_groups)
    seats = np.full(n_groups, g)
    if b % g:
        seats[-1] = b - g * (n_groups - 1)
    groups: list[list[int]] = [[] for _ in range(n_groups)]
    for r in order:
        gi = min((i for i in range(n_groups) if seats[i] > 0),
                 key=lambda i: sums[i])
        groups[gi].append(int(r))
        sums[gi] += lengths[r]
        seats[gi] -= 1
    return np.concatenate([np.asarray(grp, np.int64) for grp in groups])


def _n_live_pages(page_tables_ref, kv_lens_ref, row, page_size, length=None):
    """Live pages of ``row``, clamped to the table width: a row whose
    length exceeds its table (e.g. an inactive row carrying a stale/garbage
    length) must never index page_tables_ref out of bounds — SMEM reads are
    not range-checked.  ``length`` overrides the SMEM length (the span
    kernel's per-tile walks use a running prefix length, not the row's)."""
    length_ = kv_lens_ref[row] if length is None else length
    return jnp.minimum(
        jax.lax.div(length_ + page_size - 1, page_size),
        page_tables_ref.shape[1],
    )


def _fetch_page(page_tables_ref, k_hbm, v_hbm, k_scr, v_scr, sem,
                row, p, slot):
    """Start the K+V page DMAs for (row, page index p) into double-buffer
    ``slot`` — ONE [K, ps, hd] copy each brings every kv head's rows of the
    page (the page-major layout's point).  ONE shared implementation: the
    walk's steady-state prefetches and the fused kernel's cross-row prime
    must agree on the slot/semaphore layout or the next wait pairs with
    the wrong DMA."""
    page = page_tables_ref[row, p]
    pltpu.make_async_copy(k_hbm.at[page], k_scr.at[slot], sem.at[slot, 0]).start()
    pltpu.make_async_copy(v_hbm.at[page], v_scr.at[slot], sem.at[slot, 1]).start()


# ------------------------------------------------------------ XLA fallback


def paged_decode_xla(
    q: jnp.ndarray,            # [B, H, hd]
    k_pages: jnp.ndarray,      # [P, K, ps, hd]
    v_pages: jnp.ndarray,      # [P, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W] page ids (live window)
    kv_lens: jnp.ndarray,      # [B] tokens in cache (incl. current)
    kv_scales=None,            # (k_scale, v_scale) [B, K, hd] for int8 pools
) -> jnp.ndarray:
    b, h, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    n_rep = h // kh
    w = page_tables.shape[1]
    # gather pages: [B, W, K, ps, hd] -> [B, W*ps, K, hd]
    k = k_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(b, w * ps, kh, hd)
    v = v_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(b, w * ps, kh, hd)
    if kv_scales is not None:
        from lmrs_tpu.ops.quant import kv_dequant

        k = kv_dequant(k, kv_scales[0], q.dtype)
        v = kv_dequant(v, kv_scales[1], q.dtype)
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    logits = jnp.einsum("bhd,bkhd->bhk", q, k).astype(jnp.float32) * hd**-0.5
    pos = jnp.arange(w * ps)[None, None, :]
    mask = pos < kv_lens[:, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", probs.astype(v.dtype), v)


# ------------------------------------------------------------ Pallas kernel


def _ragged_decode_all_heads(
    # scalar prefetch
    page_tables_ref,  # SMEM [B, W]
    kv_lens_ref,      # SMEM [B]
    # inputs
    q_ref,            # VMEM [kh, n_tokens*n_rep_p, hd] (this row, all heads)
    k_hbm,            # ANY  [P, K, ps, hd] (full page-major pool)
    v_hbm,            # ANY  [P, K, ps, hd]
    # output
    o_ref,            # VMEM [kh, n_tokens*n_rep_p, hd]
    # scratch
    k_scr,            # VMEM [2, K, ps, hd] double-buffered whole pages
    v_scr,            # VMEM [2, K, ps, hd]
    acc_scr,          # VMEM [kh, n_tokens*n_rep_p, hd] f32
    m_scr,            # VMEM [kh, n_tokens*n_rep_p, 128] f32
    l_scr,            # VMEM [kh, n_tokens*n_rep_p, 128] f32
    sem,              # DMA semaphores (2, 2): [buffer parity, k/v]
    *,
    page_size: int,
    sm_scale: float,
    kh: int,
    n_rep_p: int = 0,   # rows per token (0 = single-token: all rows one group)
    n_tokens: int = 1,  # queries per row (speculative verify: k+1)
    max_pos: int | None = None,  # static cap: no position >= this is valid
    row=None,           # batch row to walk (default: this program's row)
    external_prime: bool = False,  # caller already DMA'd page 0 into slot 0
    after_walk=None,    # hook between the page loop and the output write:
                        # the multi-row group kernels start the NEXT row's
                        # first-page fetch here so its DMA overlaps this
                        # row's epilogue (softmax normalize + output write)
    get_kscale=None,    # (row, ki) -> [hd] f32: int8 pools.  The scales are
    get_vscale=None,    # per-CHANNEL on the contracted axis, so K's dequant
                        # folds into q (one multiply per head, before the
                        # loop) and V's into the accumulator (after it) —
                        # pages stream as raw int8, only a type convert per
                        # page
    length=None,        # override for kv_lens_ref[row]: the span kernel
                        # walks each query TILE with a running prefix length
                        # (base + tiles-so-far * QT), not the row's total
):
    """Walk ONE batch row's live pages through a double-buffered DMA
    pipeline — PAGE-major (round 3): each loop step DMAs one page's ALL kv
    heads as a single [K, ps, hd] copy and unrolls the head compute over
    the buffered block.  The head-major predecessor issued kh separate
    per-head page DMAs; the decode fixed-cost split measured the walk
    DMA-issue-bound (docs/PERF.md round 3), so fewer/bigger copies is the
    lever.  Every head keeps its own online-softmax state (acc/m/l gain a
    leading kh axis, statically indexed).

    With ``n_tokens > 1`` (ragged speculative verify) the q rows group as
    [token j][query head group]: token j sits at absolute position
    ``length - n_tokens + j`` and its rows attend positions < that + 1 —
    per-row causal limits over the SAME single page walk, so verifying
    k drafts costs one walk, not a full page-window gather."""
    b = pl.program_id(0) if row is None else row
    if length is None:
        length = kv_lens_ref[b]
    n_pages = _n_live_pages(page_tables_ref, kv_lens_ref, b, page_size,
                            length=length)

    def fetch(p, slot):
        _fetch_page(page_tables_ref, k_hbm, v_hbm, k_scr, v_scr, sem,
                    b, p, slot)

    @pl.when(n_pages == 0)
    def _zero():  # inactive row: defined output, no page walk
        o_ref[...] = jnp.zeros(o_ref.shape, o_ref.dtype)

    if not external_prime:
        @pl.when(n_pages > 0)
        def _prime():
            fetch(0, 0)

    # int8 dequant is row-count-agnostic: the K scale folds into EVERY q
    # row (all tokens share the slot's per-channel scales — draft tokens
    # were quantized with the same scales in the RMW) and the V scale
    # folds into every accumulator row after the walk, so n_tokens > 1
    # (speculative verify) needs no special casing here.

    m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
    l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
    acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)
    # per-head q, pre-scaled for int8 pools: q·(s⊙k8) = (q⊙s)·k8
    qs = []
    for ki in range(kh):
        q = q_ref[ki].astype(jnp.float32)  # [rows, hd]
        if get_kscale is not None:
            q = q * get_kscale(b, ki)[None, :]
        qs.append(q)
    rows = qs[0].shape[0]

    def body(p, _):
        slot = jax.lax.rem(p, 2)

        # overlap: the NEXT page's DMA streams while this one computes
        @pl.when(p + 1 < n_pages)
        def _prefetch():
            fetch(p + 1, jax.lax.rem(p + 1, 2))

        page = page_tables_ref[b, p]
        pltpu.make_async_copy(
            k_hbm.at[page], k_scr.at[slot], sem.at[slot, 0]).wait()
        pltpu.make_async_copy(
            v_hbm.at[page], v_scr.at[slot], sem.at[slot, 1]).wait()

        # positional causal mask: identical for every head, computed once
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 1)
        if n_tokens == 1:
            limit = length  # every row is the newest token
        else:
            # row r belongs to token j = r // n_rep_p at absolute position
            # length - n_tokens + j: strict per-row causality
            j = jax.lax.broadcasted_iota(
                jnp.int32, (rows, page_size), 0) // n_rep_p
            limit = length - n_tokens + j + 1
            if max_pos is not None:
                # positions >= max_pos were never written (write cap in the
                # RMW): a query past the cap sees the real prefix only
                limit = jnp.minimum(limit, max_pos)
        masked = pos < limit

        for ki in range(kh):
            k = k_scr[slot, ki].astype(jnp.float32)  # [ps, hd]
            s = jax.lax.dot_general(
                qs[ki], k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale  # [rows, ps]
            s = jnp.where(masked, s, NEG_INF)
            m_prev = m_scr[ki, :, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            pw = jnp.exp(s - m_new)
            pw = jnp.where(m_new > NEG_INF * 0.5, pw, 0.0)
            l_scr[ki] = jnp.broadcast_to(
                alpha * l_scr[ki, :, :1] + jnp.sum(pw, axis=1, keepdims=True),
                l_scr.shape[1:])
            vv = v_scr[slot, ki].astype(jnp.float32)
            acc_scr[ki] = acc_scr[ki] * alpha + jax.lax.dot_general(
                pw, vv, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[ki] = jnp.broadcast_to(m_new, m_scr.shape[1:])
        return _

    jax.lax.fori_loop(0, n_pages, body, None)

    # safe to issue new DMAs into the double buffers here: every copy the
    # loop started has been waited, and the last page's compute consumed
    # its buffer before the loop returned
    if after_walk is not None:
        after_walk()

    @pl.when(n_pages > 0)
    def _write():
        for ki in range(kh):
            l = l_scr[ki, :, :1]
            out = acc_scr[ki] / jnp.where(l > 0, l, 1.0)
            if get_vscale is not None:
                # per-channel V scale on the output axis: pw·(s⊙v8) =
                # (pw·v8)⊙s — folded once per head after the loop
                out = out * get_vscale(b, ki)[None, :]
            o_ref[ki] = out.astype(o_ref.dtype)


def _make_rmw(
    page_tables_ref, kv_lens_ref,
    get_knew,         # (row, ki) -> VMEM [t_pad, hd] the T new tokens' K
    get_vnew,
    k_out,            # ANY  [P, K, ps, hd] aliased pool
    v_out,
    k8_scr,           # VMEM [n_win, kh, wh, hd] (window-major: one window's
    v8_scr,           #   ALL heads are a single contiguous DMA block)
    wsem,             # DMA semaphores (n_win, 2)
    *,
    page_size: int,
    kh: int,
    n_tokens: int,
    t_pad: int,
    hd: int,
    max_pos: int | None = None,
    wh: int = 8,        # RMW window height = the pool dtype's sublane tile
                        # (8 for bf16/f32 pools, 32 for int8)
    get_kscale=None,    # (row, ki) -> [hd] f32: quantize new tokens into
    get_vscale=None,    # int8 pools with the row's per-channel scales
):
    """Row-parametrized RMW scatter of T consecutive new tokens' K/V into
    the page pool in place.  ``for_row(row)`` returns the three phases —
    ``(start_reads, blend_write, drain)`` — so a caller can interleave one
    row's RMW cycle with another row's page walk (the fused kernel runs row
    b+1's cycle inside row b's walk: their pages are disjoint because slots
    own their pages exclusively).  Exactly ONE cycle may be in flight at a
    time (the phases share k8/v8 scratch and ``wsem``).

    The positions are consecutive, so they cover at most
    ``n_win = (T-2)//8 + 2`` aligned 8-row windows, and page_size % 8 == 0
    means no window straddles a page — each window is ONE read-blend-write
    RMW covering ALL kv heads (a single strided [K, wh, hd] copy each way;
    round 5 — the per-(head, window) copies before it were 2·K tiny DMA
    issues per direction, the dominant share of the measured ~6 µs/row
    decode fixed cost), reads all issued before any blend so they overlap.

    ``max_pos`` (static): tokens at positions >= it are NOT written — the
    max-seq-len cap for draft tokens that overhang the end of the cache
    (the caller passes the UNCLAMPED length, so the base position is
    always exact; a clamped length would slide the whole span backwards
    over real cache entries)."""
    assert page_size % wh == 0, (
        f"RMW window offsets are computed in {wh}-row units; a non-multiple "
        f"page_size={page_size} would silently alias (scheduler gates this)")
    n_win = 1 if n_tokens == 1 else (n_tokens - 2) // wh + 2

    def for_row(b, length=None):
        # ``length`` override: the span kernel RMWs one QT-token tile at a
        # time with a running prefix length instead of the row's total
        if length is None:
            length = kv_lens_ref[b]
        base = jnp.maximum(length - n_tokens, 0)  # first new token's position
        win0 = jax.lax.div(base, wh) * wh  # provably wh-aligned
        # A window is touched ONLY if it holds a valid token position.  An
        # overhanging window (past the table span or max_pos) must be
        # skipped entirely, not clipped: a clipped page index keeps the raw
        # offset and can ALIAS an earlier window's rows when
        # page_size <= wh*(n_win-1) (e.g. ps=8 with any draft span ending at
        # the table edge) — its stale write-back would then revert the valid
        # window's freshly written K/V.
        limit = jnp.minimum(base + n_tokens,
                            page_tables_ref.shape[1] * page_size)
        if max_pos is not None:
            limit = jnp.minimum(limit, max_pos)

        def win_page(wi):
            start = win0 + wh * wi
            page_idx = jnp.clip(jax.lax.div(start, page_size), 0,
                                page_tables_ref.shape[1] - 1)
            return start, page_tables_ref[b, page_idx]

        def read_copies(wi, start, page):
            # rem(start, ps) is wh-aligned (start = wh*k, ps % wh == 0) but
            # Mosaic's divisibility prover can't see through rem; the w*wh
            # form it can.  ONE [K, wh, hd] copy per direction covers every
            # head's rows of the window (strided on the HBM side, contiguous
            # in the window-major scratch).
            off = pl.ds(jax.lax.rem(jax.lax.div(start, wh), page_size // wh) * wh, wh)
            return (pltpu.make_async_copy(k_out.at[page, :, off],
                                          k8_scr.at[wi], wsem.at[wi, 0]),
                    pltpu.make_async_copy(v_out.at[page, :, off],
                                          v8_scr.at[wi], wsem.at[wi, 1]))

        def write_copies(wi, start, page):
            off = pl.ds(jax.lax.rem(jax.lax.div(start, wh), page_size // wh) * wh, wh)
            return (pltpu.make_async_copy(k8_scr.at[wi],
                                          k_out.at[page, :, off], wsem.at[wi, 0]),
                    pltpu.make_async_copy(v8_scr.at[wi],
                                          v_out.at[page, :, off], wsem.at[wi, 1]))

        def start_reads():
            for wi in range(n_win):
                start, page = win_page(wi)

                @pl.when(start < limit)
                def _read(wi=wi, start=start, page=page):
                    rk, rv = read_copies(wi, start, page)
                    rk.start()
                    rv.start()

        def blend_write():
            for wi in range(n_win):
                start, page = win_page(wi)

                @pl.when(start < limit)
                def _blend(wi=wi, start=start, page=page):
                    rk, rv = read_copies(wi, start, page)
                    wk, wv = write_copies(wi, start, page)
                    rk.wait()
                    rv.wait()
                    # row r of this window holds token j = start+r-base
                    # when 0 <= j < T; select token rows with a tiny 0/1
                    # matmul (no dynamic VMEM indexing) and blend where
                    # a token lands.  The mask is head-independent —
                    # computed once, blended per head.
                    row = jax.lax.broadcasted_iota(jnp.int32, (wh, t_pad), 0)
                    tok = jax.lax.broadcasted_iota(jnp.int32, (wh, t_pad), 1)
                    j = start + row - base
                    valid = (j == tok) & (tok < n_tokens)
                    if max_pos is not None:
                        valid &= (start + row) < max_pos
                    sel = valid.astype(jnp.float32)
                    hit = (jnp.sum(sel, axis=1, keepdims=True) > 0)
                    hit = jnp.broadcast_to(hit, (wh, hd))
                    for ki in range(kh):
                        k_rows = jax.lax.dot_general(
                            sel, get_knew(b, ki).astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                        v_rows = jax.lax.dot_general(
                            sel, get_vnew(b, ki).astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                        if get_kscale is not None:
                            # quantize the landing rows with the row's
                            # per-channel scales (int8 pools)
                            k_rows = jnp.clip(jnp.round(
                                k_rows / get_kscale(b, ki)[None, :]),
                                -127, 127)
                            v_rows = jnp.clip(jnp.round(
                                v_rows / get_vscale(b, ki)[None, :]),
                                -127, 127)
                        k8_scr[wi, ki] = jnp.where(
                            hit, k_rows.astype(k8_scr.dtype), k8_scr[wi, ki])
                        v8_scr[wi, ki] = jnp.where(
                            hit, v_rows.astype(v8_scr.dtype), v8_scr[wi, ki])
                    wk.start()
                    wv.start()

        def drain():
            for wi in range(n_win):
                start, page = win_page(wi)

                @pl.when(start < limit)
                def _drain(wi=wi, start=start, page=page):
                    wk, wv = write_copies(wi, start, page)
                    wk.wait()
                    wv.wait()

        return start_reads, blend_write, drain

    return for_row


def _write_new_tokens_all_heads(
    page_tables_ref, kv_lens_ref,
    knew_ref,         # VMEM [kh, t_pad, hd] the T new tokens' K (rows 0..T-1)
    vnew_ref,
    k_out,            # ANY  [P, K, ps, hd] aliased pool
    v_out,
    k8_scr,           # VMEM [n_win, kh, wh, hd]
    v8_scr,
    wsem,             # DMA semaphores (n_win, 2)
    *,
    page_size: int,
    kh: int,
    n_tokens: int,
    max_pos: int | None = None,
    wh: int = 8,
    get_kscale=None,    # (row, ki) -> [hd] f32: int8 pools (quantize the
    get_vscale=None,    # new tokens with the row's per-channel scales)
):
    """One whole RMW cycle for this program's own row (the multi-token
    verify kernel's path; the fused decode kernel uses ``_make_rmw``
    directly to pipeline the cycle across grid iterations)."""
    rmw = _make_rmw(
        page_tables_ref, kv_lens_ref,
        lambda _row, ki: knew_ref[ki], lambda _row, ki: vnew_ref[ki],
        k_out, v_out, k8_scr, v8_scr, wsem,
        page_size=page_size, kh=kh, n_tokens=n_tokens,
        t_pad=knew_ref.shape[1], hd=knew_ref.shape[-1], max_pos=max_pos,
        wh=wh, get_kscale=get_kscale, get_vscale=get_vscale,
    )
    start_reads, blend_write, drain = rmw(pl.program_id(0))
    start_reads()
    blend_write()
    drain()


def _make_group_kernel(*, g: int, ps: int, kh: int, hd: int, n_tokens: int,
                       t_pad: int, n_rep_p: int, max_pos: int | None,
                       wh: int, quantized: bool, sm_scale: float):
    """Row-GROUP decode kernel body (the multi-row page walk): one program
    walks ``g`` consecutive batch rows' live pages through a single shared
    double-buffered DMA pipeline instead of one program per row.  The
    per-program fixed cost — launch, scratch init, pipeline prime — is
    paid once per group, and the cross-row software pipeline runs at ROW
    granularity inside the program: while row r computes, row r+1's RMW
    windows read/blend/write and its first page prefetches into row r's
    compute bubbles.  This generalizes the per-row fused kernel's
    cross-iteration trick (which already measured 3.6 µs/row fused vs 5.2
    walk-only — the pipeline pays; docs/PERF.md round 5) from grid
    iterations to unrolled in-program rows, where no program boundary sits
    between them.

    Shared by the single-token fused decode (``n_tokens == 1``) and the
    speculative multi-token verify (``n_tokens > 1``): the RMW machinery
    and the walk are already row- and token-count-parametrized.  The
    pipeline invariants are the per-row kernel's, unchanged: rows' pages
    are disjoint (slots own their pages exclusively), exactly one RMW
    cycle is in flight at a time, and row r+1's first-page prime happens
    only after r+1's RMW drain.  The LAST row of group ``gi`` hands off to
    the FIRST row of group ``gi+1`` exactly as consecutive grid iterations
    used to — the pipeline crosses group boundaries seamlessly.

    Expects the caller's operand layout: q/o blocked ``(g, kh, rows, hd)``
    per group; knew/vnew (and int8 scales) as WHOLE-array blocks — row
    r+1's RMW runs inside row r's walk, so per-row blocks cannot work
    (same constraint as the per-row fused kernel).  The batch must be
    padded to a multiple of ``g``; padded rows carry length 0 (zero
    output, null-page RMW — the masked-row convention throughout).
    """

    def kernel(pt_ref, len_ref, q_ref, knew_ref, vnew_ref, *rest):
        if quantized:
            (ksc_ref, vsc_ref, k_hbm, v_hbm, o_ref, k_out, v_out, k_scr,
             v_scr, acc_scr, m_scr, l_scr, k8_scr, v8_scr, sem, wsem) = rest
            gks = lambda row, ki: ksc_ref[row, ki]
            gvs = lambda row, ki: vsc_ref[row, ki]
        else:
            (k_hbm, v_hbm, o_ref, k_out, v_out, k_scr, v_scr, acc_scr,
             m_scr, l_scr, k8_scr, v8_scr, sem, wsem) = rest
            gks = gvs = None
        gi = pl.program_id(0)
        nrows = pl.num_programs(0) * g
        base = gi * g
        rmw = _make_rmw(
            pt_ref, len_ref,
            lambda row, ki: knew_ref[row, ki],
            lambda row, ki: vnew_ref[row, ki],
            k_out, v_out, k8_scr, v8_scr, wsem,
            page_size=ps, kh=kh, n_tokens=n_tokens, t_pad=t_pad, hd=hd,
            max_pos=max_pos, wh=wh, get_kscale=gks, get_vscale=gvs,
        )

        def prime_row(row):
            # same fetch layout as the walk body: its step-0 wait pairs
            # with fetch(page 0, slot 0)
            @pl.when(_n_live_pages(pt_ref, len_ref, row, ps) > 0)
            def _():
                _fetch_page(pt_ref, k_out, v_out, k_scr, v_scr, sem,
                            row, 0, 0)

        @pl.when(gi == 0)
        def _bootstrap():  # the very first row has no predecessor
            sr, bw, dr = rmw(0)
            sr()
            bw()
            dr()
            prime_row(0)

        for j in range(g):  # static unroll: one walk per group row
            row = base + j
            nxt = row + 1
            # clamped for closure creation only (same contract as the
            # per-row fused kernel): for_row's scalar SMEM reads trace
            # unguarded; the pl.when guards keep the phases from
            # EXECUTING past the last row
            nxt_reads, nxt_blend, nxt_drain = rmw(
                jnp.minimum(nxt, nrows - 1))

            @pl.when(nxt < nrows)
            def _next_rmw_reads(nxt_reads=nxt_reads):
                nxt_reads()

            def after_walk(nxt=nxt, nxt_blend=nxt_blend,
                           nxt_drain=nxt_drain):
                # row nxt's RMW completes and its first page primes while
                # row ``row``'s epilogue (normalize + output write) runs
                @pl.when(nxt < nrows)
                def _():
                    nxt_blend()
                    nxt_drain()
                    prime_row(nxt)

            _ragged_decode_all_heads(
                pt_ref, len_ref, q_ref.at[j], k_out, v_out, o_ref.at[j],
                k_scr, v_scr, acc_scr, m_scr, l_scr, sem,
                page_size=ps, sm_scale=sm_scale, kh=kh,
                n_rep_p=n_rep_p, n_tokens=n_tokens, max_pos=max_pos,
                row=row, external_prime=True, after_walk=after_walk,
                get_kscale=gks, get_vscale=gvs,
            )

    return kernel


def _pad_rows(x, bp: int, fill=0):
    """Pad axis 0 of ``x`` from b to ``bp`` rows with ``fill`` (group-path
    batch padding; padded rows carry length 0 and are inactive)."""
    b = x.shape[0]
    if b == bp:
        return x
    pad = [(0, bp - b)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad, constant_values=fill)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "max_pos", "row_group"))
def paged_decode_pallas_multi(
    q: jnp.ndarray,            # [B, T, H, hd] queries (token-major)
    k_new: jnp.ndarray,        # [B, T, K, hd] the T tokens' K (post-rope)
    v_new: jnp.ndarray,        # [B, T, K, hd]
    k_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    v_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W] GLOBAL page ids
    kv_lens: jnp.ndarray,      # [B] length INCLUDING all T tokens (UNclamped:
                               # may exceed max_pos near the cap; the base
                               # position kv_lens - T must be the true one)
    interpret: bool = False,
    max_pos: int | None = None,  # static position cap (max_seq_len)
    kscale: jnp.ndarray | None = None,  # [B, K, hd] f32: int8 pools — the
    vscale: jnp.ndarray | None = None,  # per-(slot, head, channel) scales
    row_group: int = 1,        # rows per program (multi-row page walk);
                               # 1 = the per-row grid (LMRS_MULTIROW=0)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Ragged multi-token verify: the speculative-decoding analog of
    ``paged_decode_pallas_fused``.  One program per batch row writes all T
    new tokens' K/V into their pages in place and attends each token's
    query rows to the live pages with strict per-token causality — ONE
    ragged page walk for the whole [B, T] verify step, replacing the
    full page-window gather that made round-2 speculation 12x slower
    (docs/PERF.md; VERDICT r2 item 3).

    Near the max-seq-len boundary the caller passes the UNclamped length
    (base = kv_lens - T is then always the true first-token position) and
    ``max_pos``: tokens overhanging the cap are neither written nor
    attended — a clamped length would instead slide the whole write span
    backwards over real cache entries.

    With ``kscale``/``vscale`` the pools are int8 (VERDICT r4 item 4): the
    RMW quantizes the draft tokens' rows with the slot's frozen
    per-channel scales, windows widen to the int8 sublane tile (32), and
    the walk folds K's dequant into every token's q rows and V's into the
    accumulator — the same folds as the single-token fused kernel, which
    are row-count-agnostic."""
    b, t, h, hd = q.shape
    kh = k_pages.shape[1]
    ps = k_pages.shape[2]
    quantized = kscale is not None
    assert quantized == (k_pages.dtype == jnp.int8), (
        "int8 pools need scales and vice versa")
    wh = 32 if quantized else 8
    n_rep = h // kh
    n_rep_p = -(-n_rep // 8) * 8
    rows = t * n_rep_p
    # [B, T, H, hd] -> [B, kh, T*n_rep_p, hd], token-major row groups
    qg = q.reshape(b, t, kh, n_rep, hd)
    if n_rep_p != n_rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, n_rep_p - n_rep), (0, 0)))
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(b, kh, rows, hd)
    t_pad = -(-t // 8) * 8
    knew = k_new.transpose(0, 2, 1, 3)  # [B, K, T, hd]
    vnew = v_new.transpose(0, 2, 1, 3)
    if t_pad != t:
        knew = jnp.pad(knew, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
        vnew = jnp.pad(vnew, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    n_win = 1 if t == 1 else (t - 2) // wh + 2

    g = max(1, min(row_group, b))
    if g > 1:
        # multi-row page walk: pad the batch to a multiple of g (padded
        # rows: length 0, inactive) and dispatch one program per GROUP.
        # knew/vnew (and scales) become whole-array blocks — the group
        # kernel's pipeline runs row r+1's RMW inside row r's walk, so
        # per-row blocks cannot cross rows (same constraint as the fused
        # kernel); their VMEM footprint scales with batch.
        bp = -(-b // g) * g
        qg = _pad_rows(qg, bp)
        knew, vnew = _pad_rows(knew, bp), _pad_rows(vnew, bp)
        page_tables = _pad_rows(page_tables, bp)
        kv_lens = _pad_rows(kv_lens, bp)
        new_tok_bytes = 2 * bp * kh * t_pad * hd * knew.dtype.itemsize
        assert new_tok_bytes <= 4 * 1024 * 1024, (
            f"multi-row verify keeps all rows' draft K/V in VMEM "
            f"({new_tok_bytes/2**20:.1f} MiB at B={bp}, T={t_pad}, kh={kh}, "
            f"hd={hd}); shard the batch or lower max_batch_slots")
        scale_specs = []
        if quantized:
            # pad scales with ones: a padded row's null-page RMW still
            # quantizes (harmless garbage by convention), and a zero
            # scale would turn that into NaN rows
            kscale = _pad_rows(kscale.astype(jnp.float32), bp, fill=1)
            vscale = _pad_rows(vscale.astype(jnp.float32), bp, fill=1)
            scale_specs = [
                pl.BlockSpec((bp, kh, hd), lambda gi, *_: (0, 0, 0)),
                pl.BlockSpec((bp, kh, hd), lambda gi, *_: (0, 0, 0)),
            ]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bp // g,),
            in_specs=[
                pl.BlockSpec((g, kh, rows, hd), lambda gi, *_: (gi, 0, 0, 0)),
                pl.BlockSpec((bp, kh, t_pad, hd), lambda gi, *_: (0, 0, 0, 0)),
                pl.BlockSpec((bp, kh, t_pad, hd), lambda gi, *_: (0, 0, 0, 0)),
                *scale_specs,
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((g, kh, rows, hd), lambda gi, *_: (gi, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),  # whole pages
                pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
                pltpu.VMEM((kh, rows, hd), jnp.float32),
                pltpu.VMEM((kh, rows, 128), jnp.float32),
                pltpu.VMEM((kh, rows, 128), jnp.float32),
                pltpu.VMEM((n_win, kh, wh, hd), k_pages.dtype),
                pltpu.VMEM((n_win, kh, wh, hd), v_pages.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.SemaphoreType.DMA((n_win, 2)),
            ],
        )
        kernel = _make_group_kernel(
            g=g, ps=ps, kh=kh, hd=hd, n_tokens=t, t_pad=t_pad,
            n_rep_p=n_rep_p, max_pos=max_pos, wh=wh, quantized=quantized,
            sm_scale=hd**-0.5)
        operands = [qg, knew, vnew]
        if quantized:
            operands += [kscale, vscale]
        pool_at = 2 + len(operands)
        out, k_pages, v_pages = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((bp, kh, rows, hd), q.dtype),
                jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            ],
            input_output_aliases={pool_at: 1, pool_at + 1: 2},
            interpret=interpret,
        )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
          *operands, k_pages, v_pages)
        out = out[:b].reshape(b, kh, t, n_rep_p, hd)[:, :, :, :n_rep]
        return (out.transpose(0, 2, 1, 3, 4).reshape(b, t, h, hd),
                k_pages, v_pages)

    scale_specs = []
    if quantized:
        scale_specs = [
            pl.BlockSpec((b, kh, hd), lambda bi, *_: (0, 0, 0)),
            pl.BlockSpec((b, kh, hd), lambda bi, *_: (0, 0, 0)),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kh, rows, hd), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec((1, kh, t_pad, hd), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec((1, kh, t_pad, hd), lambda bi, *_: (bi, 0, 0, 0)),
            *scale_specs,
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, kh, rows, hd), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),  # whole pages
            pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
            pltpu.VMEM((kh, rows, hd), jnp.float32),
            pltpu.VMEM((kh, rows, 128), jnp.float32),
            pltpu.VMEM((kh, rows, 128), jnp.float32),
            pltpu.VMEM((n_win, kh, wh, hd), k_pages.dtype),
            pltpu.VMEM((n_win, kh, wh, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((n_win, 2)),
        ],
    )

    def kernel(pt_ref, len_ref, q_ref, knew_ref, vnew_ref, *rest):
        if quantized:
            (ksc_ref, vsc_ref, k_hbm, v_hbm, o_ref, k_out, v_out, k_scr,
             v_scr, acc_scr, m_scr, l_scr, k8_scr, v8_scr, sem, wsem) = rest
            gks = lambda row, ki: ksc_ref[row, ki]
            gvs = lambda row, ki: vsc_ref[row, ki]
        else:
            (k_hbm, v_hbm, o_ref, k_out, v_out, k_scr, v_scr, acc_scr,
             m_scr, l_scr, k8_scr, v8_scr, sem, wsem) = rest
            gks = gvs = None
        _write_new_tokens_all_heads(
            pt_ref, len_ref, knew_ref.at[0], vnew_ref.at[0], k_out, v_out,
            k8_scr, v8_scr, wsem, page_size=ps, kh=kh, n_tokens=t,
            max_pos=max_pos, wh=wh, get_kscale=gks, get_vscale=gvs,
        )
        _ragged_decode_all_heads(
            pt_ref, len_ref, q_ref.at[0], k_out, v_out, o_ref.at[0],
            k_scr, v_scr, acc_scr, m_scr, l_scr, sem,
            page_size=ps, sm_scale=hd**-0.5, kh=kh,
            n_rep_p=n_rep_p, n_tokens=t, max_pos=max_pos,
            get_kscale=gks, get_vscale=gvs,
        )

    operands = [qg, knew, vnew]
    if quantized:
        operands += [kscale.astype(jnp.float32), vscale.astype(jnp.float32)]
    pool_at = 2 + len(operands)  # k_pages index among ALL args
    out, k_pages, v_pages = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, kh, rows, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        input_output_aliases={pool_at: 1, pool_at + 1: 2},
        interpret=interpret,
    )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      *operands, k_pages, v_pages)
    out = out.reshape(b, kh, t, n_rep_p, hd)[:, :, :, :n_rep]
    return out.transpose(0, 2, 1, 3, 4).reshape(b, t, h, hd), k_pages, v_pages


def paged_decode_multi_xla(
    q: jnp.ndarray,            # [B, T, H, hd]
    k_new: jnp.ndarray,        # [B, T, K, hd]
    v_new: jnp.ndarray,        # [B, T, K, hd]
    k_pages: jnp.ndarray,      # [P, K, ps, hd]
    v_pages: jnp.ndarray,      # [P, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W]
    kv_lens: jnp.ndarray,      # [B] incl. the T tokens (unclamped; see kernel)
    max_pos: int | None = None,
    kv_scales=None,            # (k_scale, v_scale) [B, K, hd] for int8 pools
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scatter + gather reference for the multi-token verify: same contract
    as ``paged_decode_pallas_multi`` on any platform (correctness baseline
    + CPU fallback for the speculative verify forward).  A token is
    written ONLY when its position lies inside BOTH the table span (W*ps)
    and ``max_pos`` — matching the kernel, which SKIPS out-of-span
    windows; a clipped write would scribble real rows of the last tabled
    page (the stale-length degenerate class).  Skipped writes park on the
    reserved null page (id 0)."""
    b, t, h, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    w = page_tables.shape[1]
    base = jnp.maximum(kv_lens - t, 0)
    pos = base[:, None] + jnp.arange(t)[None, :]  # [B, T]
    page = jnp.take_along_axis(
        page_tables, jnp.clip(pos // ps, 0, w - 1), axis=1)  # [B, T]
    off = pos % ps
    in_span = pos < w * ps
    if max_pos is not None:
        in_span &= pos < max_pos
    page = jnp.where(in_span, page, 0)  # overhang lands on the null page
    off = jnp.where(in_span, off, 0)
    if kv_scales is not None:
        from lmrs_tpu.ops.quant import kv_quant

        k_new = kv_quant(k_new, kv_scales[0])
        v_new = kv_quant(v_new, kv_scales[1])
    # page-major scatter: advanced indices (page, off) with the head slice
    # between put the advanced dims first -> updates take [B, T, K, hd]
    k_pages = k_pages.at[page, :, off].set(k_new)
    v_pages = v_pages.at[page, :, off].set(v_new)

    n_rep = h // kh
    k_win = k_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, w * ps, kh, hd)
    v_win = v_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, w * ps, kh, hd)
    if kv_scales is not None:
        from lmrs_tpu.ops.quant import kv_dequant

        k_win = kv_dequant(k_win, kv_scales[0], q.dtype)
        v_win = kv_dequant(v_win, kv_scales[1], q.dtype)
    if n_rep > 1:
        k_win = jnp.repeat(k_win, n_rep, axis=2)
        v_win = jnp.repeat(v_win, n_rep, axis=2)
    logits = jnp.einsum("bthd,bkhd->bthk", q, k_win).astype(jnp.float32) * hd**-0.5
    col = jnp.arange(w * ps)[None, None, None, :]
    mask = col <= pos[:, :, None, None]  # query t attends positions <= its own
    if max_pos is not None:
        mask &= col < max_pos
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bthk,bkhd->bthd", probs.astype(v_win.dtype), v_win)
    return out, k_pages, v_pages


# ------------------------------------------------- ragged span kernel (RPA)

# Query-tile height of the span kernel.  Spans are host-packed to QT-token
# alignment (pack_spans), so every tile's flat offset is provably aligned
# for Mosaic's dynamic-slice prover and no tile straddles two spans.
SPAN_QT = 8


# canonical bucket edges of the ragged-span compile-key family — defined
# jax-free in utils/perf_model so the mock engine can share them without
# importing the kernel stack; re-exported here for kernel-side callers
from lmrs_tpu.utils.perf_model import pow2_bucket  # noqa: E402,F401


def pack_spans(q_lens, floor: int = 16):
    """Host-side span packer for the ragged span kernel: given per-row real
    query lengths (0 = inactive row), return ``(q_starts, total)`` where
    span i occupies flat tokens [q_starts[i], q_starts[i] + q_lens[i]) of a
    buffer whose rows are SPAN_QT-aligned, and ``total`` is the aligned
    token count (bucket it pow2 before allocating — the compile key).
    Pure numpy; never traced."""
    q_lens = np.asarray(q_lens, np.int64)
    aligned = -(-q_lens // SPAN_QT) * SPAN_QT
    q_starts = np.concatenate([[0], np.cumsum(aligned)[:-1]])
    return q_starts.astype(np.int32), int(max(floor, aligned.sum()))


@functools.partial(jax.jit, static_argnames=("interpret", "max_pos"))
def ragged_spans_pallas(
    q: jnp.ndarray,            # [Tp, H, hd] flat query tokens (all spans)
    k_new: jnp.ndarray,        # [Tp, K, hd] the tokens' K (post-rope)
    v_new: jnp.ndarray,        # [Tp, K, hd]
    k_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    v_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W] GLOBAL page ids
    kv_lens: jnp.ndarray,      # [B] tokens in cache BEFORE this dispatch
                               # (the span base positions; NOT including the
                               # span's own tokens — unlike the multi kernel)
    q_starts: jnp.ndarray,     # [B] SPAN_QT-aligned flat span offsets
    q_lens: jnp.ndarray,       # [B] real span lengths (0 = inactive row)
    interpret: bool = False,
    max_pos: int | None = None,
    kscale: jnp.ndarray | None = None,  # [B, K, hd] f32 (int8 pools)
    vscale: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE ragged kernel for every phase (the RPA shape, PAPERS.md): each
    dispatch is a list of (row, query-span) pairs over the paged pool —
    plain decode is q_len=1 rows, speculative verify q_len=k+1 rows, a
    SARATHI mixed step is decode rows plus one long prefill-slice row, and
    a prefill continuation chunk is a long-span row.  One program per
    batch row loops over its span's SPAN_QT-token tiles: per tile it DMAs
    the tile's q rows and new-token K/V from HBM, RMWs the tokens into the
    row's pages (``_make_rmw`` with a running prefix length), and walks the
    prefix pages through the existing double-buffered pipeline with
    per-token causal limits.  VMEM is bounded by the TILE — span length
    only moves the trip counts — so the compile bucket family is
    (pow2 total-query-tokens, page window) instead of the per-phase matrix.

    Token j of row b sits at absolute position ``kv_lens[b] + j``; tile t
    walks with prefix length ``kv_lens[b] + (t+1)*QT`` so its per-token
    limits are exact.  The last tile's padding tokens write garbage K/V at
    FUTURE positions (masked by every real query's limit; overwritten by
    the row's next real tokens — the mixed path's existing convention) and
    their query rows compute garbage outputs the consumer never gathers.
    Flat tokens outside every span are untouched in the output buffer.

    Per-tile page walks restart at page 0 (attention needs the whole
    prefix), so a c-token span costs ~c/QT partial walks — fine at mixed
    and chunk sizes where spans ≲ the prefill chunk; the flash path
    remains the right tool for large FRESH prefills with no prior KV."""
    tp, h, hd = q.shape
    kh = k_pages.shape[1]
    ps = k_pages.shape[2]
    b = page_tables.shape[0]
    quantized = kscale is not None
    assert quantized == (k_pages.dtype == jnp.int8), (
        "int8 pools need scales and vice versa")
    assert tp % SPAN_QT == 0, "pad the flat token buffer to SPAN_QT"
    wh = 32 if quantized else 8
    n_rep = h // kh
    n_rep_p = -(-n_rep // 8) * 8
    qt = SPAN_QT
    tile_rows = qt * n_rep_p
    n_win = (qt - 2) // wh + 2
    sm_scale = hd**-0.5

    # [Tp, H, hd] -> [kh, Tp*n_rep_p, hd], token-major row groups
    qg = q.reshape(tp, kh, n_rep, hd)
    if n_rep_p != n_rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, n_rep_p - n_rep), (0, 0)))
    qg = qg.transpose(1, 0, 2, 3).reshape(kh, tp * n_rep_p, hd)
    knew = k_new.transpose(1, 0, 2)  # [kh, Tp, hd]
    vnew = v_new.transpose(1, 0, 2)

    def kernel(pt_ref, len_ref, qs_ref, ql_ref, q_hbm, kn_hbm, vn_hbm,
               *rest):
        if quantized:
            (ksc_ref, vsc_ref, k_hbm, v_hbm, o_hbm, k_out, v_out,
             k_scr, v_scr, q_scr, o_scr, kn_scr, vn_scr,
             acc_scr, m_scr, l_scr, k8_scr, v8_scr, sem, wsem, dsem) = rest
            gks = lambda row, ki: ksc_ref[row, ki]
            gvs = lambda row, ki: vsc_ref[row, ki]
        else:
            (k_hbm, v_hbm, o_hbm, k_out, v_out,
             k_scr, v_scr, q_scr, o_scr, kn_scr, vn_scr,
             acc_scr, m_scr, l_scr, k8_scr, v8_scr, sem, wsem, dsem) = rest
            gks = gvs = None
        bi = pl.program_id(0)
        ql = ql_ref[bi]
        base = len_ref[bi]
        rmw = _make_rmw(
            pt_ref, len_ref,
            lambda _row, ki: kn_scr[ki], lambda _row, ki: vn_scr[ki],
            k_out, v_out, k8_scr, v8_scr, wsem,
            page_size=ps, kh=kh, n_tokens=qt, t_pad=qt, hd=hd,
            max_pos=max_pos, wh=wh, get_kscale=gks, get_vscale=gvs,
        )

        @pl.when(ql > 0)
        def _row():
            n_tiles = jax.lax.div(ql + qt - 1, qt)

            def tile(ti, carry):
                # tile index in QT units: q_starts is QT-aligned, so the
                # div-mul form gives Mosaic a provably aligned offset
                t8 = jax.lax.div(qs_ref[bi], qt) + ti
                cq = pltpu.make_async_copy(
                    q_hbm.at[:, pl.ds(t8 * tile_rows, tile_rows)],
                    q_scr, dsem.at[0])
                ck = pltpu.make_async_copy(
                    kn_hbm.at[:, pl.ds(t8 * qt, qt)], kn_scr, dsem.at[1])
                cv = pltpu.make_async_copy(
                    vn_hbm.at[:, pl.ds(t8 * qt, qt)], vn_scr, dsem.at[2])
                cq.start()
                ck.start()
                cv.start()
                cq.wait()
                ck.wait()
                cv.wait()
                tile_len = base + (ti + 1) * qt
                start_reads, blend_write, drain = rmw(bi, length=tile_len)
                start_reads()
                blend_write()
                drain()
                _ragged_decode_all_heads(
                    pt_ref, len_ref, q_scr, k_out, v_out, o_scr,
                    k_scr, v_scr, acc_scr, m_scr, l_scr, sem,
                    page_size=ps, sm_scale=sm_scale, kh=kh,
                    n_rep_p=n_rep_p, n_tokens=qt, max_pos=max_pos,
                    row=bi, length=tile_len,
                    get_kscale=gks, get_vscale=gvs,
                )
                co = pltpu.make_async_copy(
                    o_scr, o_hbm.at[:, pl.ds(t8 * tile_rows, tile_rows)],
                    dsem.at[3])
                co.start()
                co.wait()
                return carry

            jax.lax.fori_loop(0, n_tiles, tile, None)

    scale_specs = []
    operands = [qg, knew, vnew]
    if quantized:
        scale_specs = [
            pl.BlockSpec((b, kh, hd), lambda bi, *_: (0, 0, 0)),
            pl.BlockSpec((b, kh, hd), lambda bi, *_: (0, 0, 0)),
        ]
        operands += [kscale.astype(jnp.float32), vscale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # q rows stream per tile
            pl.BlockSpec(memory_space=pl.ANY),  # knew
            pl.BlockSpec(memory_space=pl.ANY),  # vnew
            *scale_specs,
            pl.BlockSpec(memory_space=pl.ANY),  # k pool
            pl.BlockSpec(memory_space=pl.ANY),  # v pool
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # o rows stream per tile
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),  # whole pages
            pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
            pltpu.VMEM((kh, tile_rows, hd), q.dtype),    # q tile
            pltpu.VMEM((kh, tile_rows, hd), q.dtype),    # o tile
            pltpu.VMEM((kh, qt, hd), k_new.dtype),       # new-token K tile
            pltpu.VMEM((kh, qt, hd), v_new.dtype),
            pltpu.VMEM((kh, tile_rows, hd), jnp.float32),
            pltpu.VMEM((kh, tile_rows, 128), jnp.float32),
            pltpu.VMEM((kh, tile_rows, 128), jnp.float32),
            pltpu.VMEM((n_win, kh, wh, hd), k_pages.dtype),
            pltpu.VMEM((n_win, kh, wh, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((n_win, 2)),
            pltpu.SemaphoreType.DMA((4,)),  # q/kn/vn loads + o store
        ],
    )
    pool_at = 4 + len(operands)  # k_pages index among ALL (flat) args
    out, k_pages, v_pages = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((kh, tp * n_rep_p, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        input_output_aliases={pool_at: 1, pool_at + 1: 2},
        interpret=interpret,
    )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      q_starts.astype(jnp.int32), q_lens.astype(jnp.int32),
      *operands, k_pages, v_pages)
    out = out.reshape(kh, tp, n_rep_p, hd)[:, :, :n_rep]
    return out.transpose(1, 0, 2, 3).reshape(tp, h, hd), k_pages, v_pages


def ragged_spans_xla(
    q: jnp.ndarray,            # [Tp, H, hd]
    k_new: jnp.ndarray,        # [Tp, K, hd]
    v_new: jnp.ndarray,        # [Tp, K, hd]
    k_pages: jnp.ndarray,      # [P, K, ps, hd]
    v_pages: jnp.ndarray,      # [P, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W]
    kv_lens: jnp.ndarray,      # [B] tokens in cache BEFORE this dispatch
    q_starts: jnp.ndarray,     # [B]
    q_lens: jnp.ndarray,       # [B]
    row_flat: jnp.ndarray,     # [Tp] owning row per flat token (>= B: none)
    max_pos: int | None = None,
    kv_scales=None,            # (k_scale, v_scale) [B, K, hd] for int8 pools
    anc_masks: jnp.ndarray | None = None,  # [Tp] int32 ancestor bitmasks
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scatter + gather reference for the ragged span kernel: same contract
    on any platform (correctness baseline, the sp>1 path, and the CPU /
    first-run-lowering fallback).  ``row_flat`` is the host-built inverse
    of the span list — the kernel derives it from (q_starts, q_lens); XLA
    wants it materialized.  Out-of-span tokens park their writes on the
    reserved null page (id 0) and produce zero output rows.

    ``anc_masks`` generalizes the causal mask to token TREES (ISSUE 19
    tree speculation): flat token t with a nonzero mask attends the real
    context (cols strictly below its row's ``kv_lens``) plus exactly the
    span-local offsets whose bit is set — its root-to-self ancestor path,
    host-built, capacity 32 offsets per span.  Tokens with mask 0 (prefill
    chunks, plain rows, padding — any span-local layout that IS linear)
    keep the linear ``col <= pos`` rule bit-for-bit, so one dispatch mixes
    tree spans with arbitrarily long linear spans.  K/V writes are
    unchanged (span-offset columns): a tree node's K/V lands at a column
    only its own descendants can see this dispatch, and the scheduler
    heals accepted non-first-chain columns on the row's next span."""
    tp, h, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    b, w = page_tables.shape
    rf = jnp.clip(row_flat, 0, b - 1)
    off = jnp.arange(tp) - q_starts[rf]
    in_span = (row_flat < b) & (off >= 0) & (off < q_lens[rf])
    pos = kv_lens[rf] + off  # absolute position of each flat token
    writable = in_span & (pos < w * ps)
    if max_pos is not None:
        writable &= pos < max_pos
    pos_c = jnp.where(writable, pos, 0)
    page = jnp.where(
        writable,
        page_tables[rf, jnp.clip(pos_c // ps, 0, w - 1)], 0)
    if kv_scales is not None:
        # per-token rule: each flat token quantizes with its OWN row's
        # scales (the span analog of the packed-prefill path)
        from lmrs_tpu.ops.quant import kv_quant_tokens

        k_new = kv_quant_tokens(k_new, kv_scales[0][rf])
        v_new = kv_quant_tokens(v_new, kv_scales[1][rf])
    k_pages = k_pages.at[page, :, pos_c % ps].set(k_new)
    v_pages = v_pages.at[page, :, pos_c % ps].set(v_new)

    n_rep = h // kh
    k_win = k_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, w * ps, kh, hd)
    v_win = v_pages[page_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, w * ps, kh, hd)
    if kv_scales is not None:
        from lmrs_tpu.ops.quant import kv_dequant

        k_win = kv_dequant(k_win, kv_scales[0], q.dtype)
        v_win = kv_dequant(v_win, kv_scales[1], q.dtype)
    if n_rep > 1:
        k_win = jnp.repeat(k_win, n_rep, axis=2)
        v_win = jnp.repeat(v_win, n_rep, axis=2)
    kt = k_win[rf]  # [Tp, W*ps, H, hd] — per-token window gather
    vt = v_win[rf]
    logits = jnp.einsum("thd,tkhd->thk", q, kt).astype(jnp.float32) * hd**-0.5
    col = jnp.arange(w * ps)[None, None, :]
    mask = in_span[:, None, None] & (col <= pos[:, None, None])
    if anc_masks is not None:
        col_off = col - kv_lens[rf][:, None, None]
        bit = (anc_masks[:, None, None] >> jnp.clip(col_off, 0, 31)) & 1
        tree_ok = (col_off < 0) | ((col_off < 32) & (bit == 1))
        mask = in_span[:, None, None] & jnp.where(
            anc_masks[:, None, None] == 0, col <= pos[:, None, None], tree_ok)
    if max_pos is not None:
        mask &= col < max_pos
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # fully-masked (out-of-span) rows: uniform probs -> zero them explicitly
    out = jnp.einsum("thk,tkhd->thd", probs.astype(vt.dtype), vt)
    out = jnp.where(in_span[:, None, None], out, 0).astype(q.dtype)
    return out, k_pages, v_pages


@functools.partial(jax.jit, static_argnames=("interpret", "row_group"))
def paged_decode_pallas_fused(
    q: jnp.ndarray,            # [B, H, hd]
    k_new: jnp.ndarray,        # [B, K, hd] current token K (post-rope)
    v_new: jnp.ndarray,        # [B, K, hd]
    k_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    v_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W] GLOBAL page ids
    kv_lens: jnp.ndarray,      # [B] incl. current token
    interpret: bool = False,
    kscale: jnp.ndarray | None = None,  # [B, K, hd] f32: int8 pools — the
    vscale: jnp.ndarray | None = None,  # per-(slot, head, channel) scales
    row_group: int = 1,        # rows per program (multi-row page walk);
                               # 1 = the per-row grid (LMRS_MULTIROW=0)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Write-fused ragged decode: scatter the current token's K/V into the
    page pool (in place — the pools are input/output aliased) and attend the
    live pages, in one kernel, one program per BATCH ROW (all kv heads).
    Replaces XLA scatter + kernel: the XLA scatter on the multi-GiB pool was
    measured copying the whole pool per decode step (no in-place aliasing
    through the scan carry).

    With ``row_group > 1`` one program walks a GROUP of rows through the
    shared pipeline (``_make_group_kernel``): programs/step drop by the
    group factor and the per-program fixed cost — the dominant share of
    the measured ~3.6 µs/row decode attention cost at 8B (docs/PERF.md
    round 5) — amortizes over the group.  Exact-output-equal to the
    per-row grid; callers balance groups host-side (balanced_row_order).

    With ``kscale``/``vscale`` the pools are int8: pages stream as raw int8
    (half the decode bytes), K's per-channel dequant folds into q before
    the walk and V's into the accumulator after it, the RMW quantizes the
    new token's rows, and windows are 32 rows (the int8 sublane tile)."""
    b, h, hd = q.shape
    kh = k_pages.shape[1]
    ps = k_pages.shape[2]
    quantized = kscale is not None
    assert quantized == (k_pages.dtype == jnp.int8), (
        "int8 pools need scales and vice versa")
    wh = 32 if quantized else 8
    n_rep = h // kh
    n_rep_p = -(-n_rep // 8) * 8
    qg = q.reshape(b, kh, n_rep, hd)
    if n_rep_p != n_rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, n_rep_p - n_rep), (0, 0)))
    # pad the singleton row dim to 8 for sublane alignment (see n_rep_p)
    knew = jnp.broadcast_to(k_new[:, :, None], (b, kh, 8, hd))
    vnew = jnp.broadcast_to(v_new[:, :, None], (b, kh, 8, hd))
    # knew/vnew live whole in VMEM (the cross-row RMW needs the next row's
    # slice — see in_specs) so their footprint scales with batch; keep it
    # well under the ~16 MiB core budget alongside the page scratch
    new_tok_bytes = 2 * b * kh * 8 * hd * knew.dtype.itemsize
    assert new_tok_bytes <= 4 * 1024 * 1024, (
        f"fused decode keeps all rows' new-token K/V in VMEM "
        f"({new_tok_bytes/2**20:.1f} MiB at B={b}, kh={kh}, hd={hd}); "
        "shard the batch or lower max_batch_slots")

    g = max(1, min(row_group, b))
    if g > 1:
        # multi-row page walk: one program per GROUP of g rows (padded
        # rows are inactive), same operands as the per-row grid except
        # q/o block per group.  knew/vnew/scales were already whole-array
        # blocks here (the cross-row RMW needed them), so only the grid
        # and q/o blocking change.
        bp = -(-b // g) * g
        qg = _pad_rows(qg, bp)
        knew, vnew = _pad_rows(knew, bp), _pad_rows(vnew, bp)
        page_tables = _pad_rows(page_tables, bp)
        kv_lens = _pad_rows(kv_lens, bp)
        scale_specs = []
        if quantized:
            # ones, not zeros: a padded row's null-page RMW still divides
            # by its scale (garbage-by-convention, but NaN-free)
            kscale = _pad_rows(kscale.astype(jnp.float32), bp, fill=1)
            vscale = _pad_rows(vscale.astype(jnp.float32), bp, fill=1)
            scale_specs = [
                pl.BlockSpec((bp, kh, hd), lambda gi, *_: (0, 0, 0)),
                pl.BlockSpec((bp, kh, hd), lambda gi, *_: (0, 0, 0)),
            ]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bp // g,),
            in_specs=[
                pl.BlockSpec((g, kh, n_rep_p, hd),
                             lambda gi, *_: (gi, 0, 0, 0)),
                pl.BlockSpec((bp, kh, 8, hd), lambda gi, *_: (0, 0, 0, 0)),
                pl.BlockSpec((bp, kh, 8, hd), lambda gi, *_: (0, 0, 0, 0)),
                *scale_specs,
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec((g, kh, n_rep_p, hd),
                             lambda gi, *_: (gi, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),  # whole pages
                pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
                pltpu.VMEM((kh, n_rep_p, hd), jnp.float32),
                pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
                pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
                pltpu.VMEM((1, kh, wh, hd), k_pages.dtype),  # one RMW window
                pltpu.VMEM((1, kh, wh, hd), v_pages.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.SemaphoreType.DMA((1, 2)),
            ],
        )
        kernel = _make_group_kernel(
            g=g, ps=ps, kh=kh, hd=hd, n_tokens=1, t_pad=8, n_rep_p=0,
            max_pos=None, wh=wh, quantized=quantized, sm_scale=hd**-0.5)
        operands = [qg, knew, vnew]
        if quantized:
            operands += [kscale, vscale]
        pool_at = 2 + len(operands)
        out, k_pages, v_pages = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((bp, kh, n_rep_p, hd), q.dtype),
                jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            ],
            input_output_aliases={pool_at: 1, pool_at + 1: 2},
            interpret=interpret,
        )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
          *operands, k_pages, v_pages)
        return out[:b, :, :n_rep].reshape(b, h, hd), k_pages, v_pages

    scale_specs = []
    scale_scratch = []
    if quantized:
        # whole-array f32 blocks (~100 KB at bench shape): the cross-row
        # RMW quantizes the NEXT row's tokens, so per-row blocks can't work
        scale_specs = [
            pl.BlockSpec((b, kh, hd), lambda bi, *_: (0, 0, 0)),
            pl.BlockSpec((b, kh, hd), lambda bi, *_: (0, 0, 0)),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kh, n_rep_p, hd), lambda bi, *_: (bi, 0, 0, 0)),
            # knew/vnew map as ONE whole-array block (constant index map):
            # iteration b runs row b+1's RMW cycle mid-walk, so it must read
            # the NEXT row's slice — a per-row block can't cross iterations
            pl.BlockSpec((b, kh, 8, hd), lambda bi, *_: (0, 0, 0, 0)),
            pl.BlockSpec((b, kh, 8, hd), lambda bi, *_: (0, 0, 0, 0)),
            *scale_specs,
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, kh, n_rep_p, hd), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),  # whole pages x2
            pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
            pltpu.VMEM((kh, n_rep_p, hd), jnp.float32),
            pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
            pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
            pltpu.VMEM((1, kh, wh, hd), k_pages.dtype),  # one RMW window
            pltpu.VMEM((1, kh, wh, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((1, 2)),
        ],
    )

    def kernel(pt_ref, len_ref, q_ref, knew_ref, vnew_ref, *rest):
        if quantized:
            (ksc_ref, vsc_ref, k_hbm, v_hbm, o_ref, k_out, v_out, k_scr,
             v_scr, acc_scr, m_scr, l_scr, k8_scr, v8_scr, sem, wsem) = rest
            gks = lambda row, ki: ksc_ref[row, ki]
            gvs = lambda row, ki: vsc_ref[row, ki]
        else:
            (k_hbm, v_hbm, o_ref, k_out, v_out, k_scr, v_scr, acc_scr,
             m_scr, l_scr, k8_scr, v8_scr, sem, wsem) = rest
            gks = gvs = None
        # Cross-row software pipeline (round 3): rows' pages are DISJOINT
        # (slots own their pages exclusively), so iteration b
        #   1. starts row b+1's RMW window READS (tiny DMAs that land
        #      while row b's pages stream),
        #   2. walks row b (its first page was DMA'd by iteration b-1),
        #   3. blends + writes + drains row b+1's RMW and primes row b+1's
        #      first page fetch (safe: the RMW just drained, so even a
        #      1-page row reads fresh K/V).
        # Iteration 0 bootstraps its own RMW + prime inline.  Exactly one
        # RMW cycle is in flight at a time, so the shared scratch/sems are
        # race-free; the n_tokens=1 degenerate of the multi-token writer
        # keeps one shared RMW implementation.
        nb = pl.num_programs(0)
        bi = pl.program_id(0)
        rmw = _make_rmw(
            pt_ref, len_ref,
            lambda row, ki: knew_ref[row, ki], lambda row, ki: vnew_ref[row, ki],
            k_out, v_out, k8_scr, v8_scr, wsem,
            page_size=ps, kh=kh, n_tokens=1, t_pad=8, hd=hd,
            wh=wh, get_kscale=gks, get_vscale=gvs,
        )
        nxt = bi + 1
        # clamp for closure creation only: for_row's scalar SMEM reads trace
        # unguarded at kernel top level, and nxt == nb at the last iteration
        # would read past len_ref; the pl.when guards below keep the phases
        # from EXECUTING there, the clamp keeps the reads in bounds
        nxt_reads, nxt_blend, nxt_drain = rmw(jnp.minimum(nxt, nb - 1))

        def prime_row(row):
            # same fetch layout as the walk's body: the wait at the next
            # iteration's step 0 is fetch(page 0, slot 0)
            @pl.when(_n_live_pages(pt_ref, len_ref, row, ps) > 0)
            def _():
                _fetch_page(pt_ref, k_out, v_out, k_scr, v_scr, sem,
                            row, 0, 0)

        @pl.when(bi == 0)
        def _bootstrap():
            sr, bw, dr = rmw(0)
            sr()
            bw()
            dr()
            prime_row(0)

        @pl.when(nxt < nb)
        def _next_rmw_reads():
            nxt_reads()

        _ragged_decode_all_heads(
            pt_ref, len_ref, q_ref.at[0], k_out, v_out, o_ref.at[0],
            k_scr, v_scr, acc_scr, m_scr, l_scr, sem,
            page_size=ps, sm_scale=hd**-0.5, kh=kh,
            external_prime=True,
            get_kscale=gks, get_vscale=gvs,
        )

        @pl.when(nxt < nb)
        def _next_rmw_write():
            nxt_blend()
            nxt_drain()
            prime_row(nxt)

    # operand order after the 2 scalar-prefetch args: qg, knew, vnew,
    # [kscale, vscale,] k_pages, v_pages — the pool alias indices shift by 2
    # when the scale operands are present
    operands = [qg, knew, vnew]
    if quantized:
        operands += [kscale.astype(jnp.float32), vscale.astype(jnp.float32)]
    pool_at = 2 + len(operands)  # k_pages index among ALL args
    out, k_pages, v_pages = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, kh, n_rep_p, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # indices count the scalar-prefetch operands; pools alias so the
        # page write happens in the caller's buffers, no pool copy
        input_output_aliases={pool_at: 1, pool_at + 1: 2},
        interpret=interpret,
    )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      *operands, k_pages, v_pages)
    return out[:, :, :n_rep].reshape(b, h, hd), k_pages, v_pages


def paged_decode_fused_sharded(
    q: jnp.ndarray,            # [B, H, hd] (H sharded over tp)
    k_new: jnp.ndarray,        # [B, K, hd] (K sharded over tp)
    v_new: jnp.ndarray,        # [B, K, hd]
    k_pages: jnp.ndarray,      # [P_total, K, ps, hd] (kv-head sharded)
    v_pages: jnp.ndarray,      # [P_total, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W] replicated
    kv_lens: jnp.ndarray,      # [B] replicated
    mesh,
    interpret: bool = False,
    kscale: jnp.ndarray | None = None,  # [B, K, hd] (K sharded over tp)
    vscale: jnp.ndarray | None = None,
    row_group: int = 1,  # rows per program (multi-row page walk)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Write-fused ragged decode under a tensor-parallel mesh.

    XLA cannot auto-partition a ``pallas_call``, so the kernel runs inside
    ``shard_map`` over the ``tp`` (kv-head) axis: the page pools are already
    kv-head-sharded (engine/kv_cache.py), each shard's page walk and in-place
    K/V write touch only local HBM, and query heads shard consistently with
    their kv head (H/tp = (K/tp) * n_rep) — no cross-chip KV traffic, same
    contract as the single-device kernel per shard (each shard's program
    loops its LOCAL kv heads).  Page tables and lengths replicate
    (host-built, O(B*W) ints)."""
    from jax.sharding import PartitionSpec as P

    head = P(None, "tp", None)
    pool = P(None, "tp", None, None)  # page-major: kv heads are axis 1
    extra_in = ()
    extra_args = ()
    if kscale is not None:
        # scales shard with their kv heads (axis 1 of [B, K, hd])
        extra_in = (head, head)
        extra_args = (kscale, vscale)

    def call(q_, kn_, vn_, kp_, vp_, pt_, kl_, *sc):
        ks_, vs_ = sc if sc else (None, None)
        return paged_decode_pallas_fused(
            q_, kn_, vn_, kp_, vp_, pt_, kl_, interpret=interpret,
            kscale=ks_, vscale=vs_, row_group=row_group)

    fn = shard_map(
        call,
        mesh=mesh,
        in_specs=(head, head, head, pool, pool, P(None, None), P(None),
                  *extra_in),
        out_specs=(head, pool, pool),
        check_vma=False,
    )
    return fn(q, k_new, v_new, k_pages, v_pages, page_tables, kv_lens,
              *extra_args)


@functools.partial(jax.jit, static_argnames=("interpret", "row_group"))
def paged_decode_pallas(
    q: jnp.ndarray,            # [B, H, hd]
    k_pages: jnp.ndarray,      # [P, K, ps, hd]
    v_pages: jnp.ndarray,      # [P, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W]
    kv_lens: jnp.ndarray,      # [B]
    interpret: bool = False,
    row_group: int = 1,        # rows per program (multi-row page walk)
) -> jnp.ndarray:
    b, h, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    n_rep = h // kh
    # group query heads by kv head: [B, K, n_rep, hd].  The group dim is a
    # Mosaic block sublane dim, so pad it to 8 rows (bf16/f32 tiling both
    # divide 8; the MXU pads small dots to 8x128 anyway, so this is free) —
    # n_rep=1 (MHA) would otherwise fail sublane alignment on real TPUs.
    n_rep_p = -(-n_rep // 8) * 8
    qg = q.reshape(b, kh, n_rep, hd)
    if n_rep_p != n_rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, n_rep_p - n_rep), (0, 0)))

    g = max(1, min(row_group, b))
    if g > 1:
        # walk-only multi-row variant (no RMW): one program walks g rows
        # through the shared double-buffered pipeline, priming row r+1's
        # first page during row r's epilogue.  Used by the rowcost probe's
        # group arm; the serving path runs the fused variant.
        bp = -(-b // g) * g
        qg = _pad_rows(qg, bp)
        page_tables = _pad_rows(page_tables, bp)
        kv_lens = _pad_rows(kv_lens, bp)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bp // g,),
            in_specs=[
                pl.BlockSpec((g, kh, n_rep_p, hd),
                             lambda gi, *_: (gi, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((g, kh, n_rep_p, hd),
                                   lambda gi, *_: (gi, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),
                pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
                pltpu.VMEM((kh, n_rep_p, hd), jnp.float32),
                pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
                pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )

        def group_kernel(pt_ref, len_ref, q_ref, k_hbm, v_hbm, o_ref,
                         k_scr, v_scr, acc_scr, m_scr, l_scr, sem):
            gi = pl.program_id(0)
            nrows = pl.num_programs(0) * g
            base = gi * g

            def prime_row(row):
                @pl.when(_n_live_pages(pt_ref, len_ref, row, ps) > 0)
                def _():
                    _fetch_page(pt_ref, k_hbm, v_hbm, k_scr, v_scr, sem,
                                row, 0, 0)

            @pl.when(gi == 0)
            def _bootstrap():
                prime_row(0)

            for j in range(g):
                row = base + j
                nxt = row + 1

                def after_walk(nxt=nxt):
                    @pl.when(nxt < nrows)
                    def _():
                        prime_row(nxt)

                _ragged_decode_all_heads(
                    pt_ref, len_ref, q_ref.at[j], k_hbm, v_hbm, o_ref.at[j],
                    k_scr, v_scr, acc_scr, m_scr, l_scr, sem,
                    page_size=ps, sm_scale=hd**-0.5, kh=kh,
                    row=row, external_prime=True, after_walk=after_walk,
                )

        out = pl.pallas_call(
            group_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((bp, kh, n_rep_p, hd), q.dtype),
            interpret=interpret,
        )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32), qg,
          k_pages, v_pages)
        return out[:b, :, :n_rep].reshape(b, h, hd)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kh, n_rep_p, hd), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, kh, n_rep_p, hd), lambda bi, *_: (bi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, kh, ps, hd), k_pages.dtype),  # whole pages x2
            pltpu.VMEM((2, kh, ps, hd), v_pages.dtype),
            pltpu.VMEM((kh, n_rep_p, hd), jnp.float32),
            pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
            pltpu.VMEM((kh, n_rep_p, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    def kernel(pt_ref, len_ref, q_ref, k_hbm, v_hbm, o_ref,
               k_scr, v_scr, acc_scr, m_scr, l_scr, sem):
        _ragged_decode_all_heads(
            pt_ref, len_ref,
            q_ref.at[0], k_hbm, v_hbm, o_ref.at[0],
            k_scr, v_scr, acc_scr, m_scr, l_scr, sem,
            page_size=ps, sm_scale=hd**-0.5, kh=kh,
        )

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kh, n_rep_p, hd), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), kv_lens.astype(jnp.int32), qg, k_pages, v_pages)
    return out[:, :, :n_rep].reshape(b, h, hd)
