"""Attention: XLA reference path with GQA, causal masking, KV-cache decode.

This is the always-correct baseline the Pallas kernels (ops/flash_attention.py,
ops/paged_attention.py) are validated against, and the fallback on non-TPU
platforms.  Softmax statistics in f32; matmuls in the input dtype (bf16 on
TPU) so they land on the MXU.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand KV heads for grouped-query attention: [B,S,K,hd] -> [B,S,K*rep,hd]."""
    if n_rep == 1:
        return k
    b, s, kh, hd = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kh, n_rep, hd)).reshape(
        b, s, kh * n_rep, hd
    )


def attention(
    q: jnp.ndarray,  # [B, Sq, H, hd]
    k: jnp.ndarray,  # [B, Skv, K, hd]
    v: jnp.ndarray,  # [B, Skv, K, hd]
    q_positions: jnp.ndarray,  # [B, Sq] absolute position of each query
    kv_length: jnp.ndarray | None = None,  # [B] valid KV prefix length
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Causal attention over a (possibly padded) KV buffer.

    Masking rule: query at absolute position p attends KV slots [0, p], and
    only slots < kv_length are valid.  Works for both prefill (Sq == Skv,
    positions 0..S-1) and single-token decode (Sq == 1 against the cache).
    """
    b, sq, h, hd = q.shape
    kh = k.shape[2]
    k = _repeat_kv(k, h // kh)
    v = _repeat_kv(v, h // kh)

    scale = hd ** -0.5
    # [B, H, Sq, Skv]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if logit_softcap is not None:  # Gemma-2 style softcap
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    skv = k.shape[1]
    kv_pos = jnp.arange(skv)[None, None, None, :]  # [1,1,1,Skv]
    causal = kv_pos <= q_positions[:, None, :, None]  # [B,1,Sq,Skv]
    mask = causal
    if kv_length is not None:
        valid = kv_pos < kv_length[:, None, None, None]
        mask = jnp.logical_and(mask, valid)
    logits = jnp.where(mask, logits, NEG_INF)

    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def packed_attention(
    q: jnp.ndarray,  # [B, S, H, hd]
    k: jnp.ndarray,  # [B, S, K, hd]
    v: jnp.ndarray,  # [B, S, K, hd]
    segment_ids: jnp.ndarray,  # [B, S] per-token segment (pad: any id < 0)
    length: jnp.ndarray | None = None,  # [B] total valid (packed) tokens
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Packed-prompt self-attention (XLA reference / fallback): several
    prompts concatenated into one row, masked to same-segment pairs with
    causality on the global row index (segments are contiguous, so this is
    per-segment causal attention).  The correctness contract for the flash
    kernel's ``segment_ids`` path (tests/test_kernels.py)."""
    b, s, h, hd = q.shape
    kh = k.shape[2]
    k = _repeat_kv(k, h // kh)
    v = _repeat_kv(v, h // kh)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * hd**-0.5
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    idx = jnp.arange(s)
    causal = idx[None, :] <= idx[:, None]  # [Sq, Skv]: k at or before q
    same_seg = segment_ids[:, :, None] == segment_ids[:, None, :]  # [B,Sq,Skv]
    mask = jnp.logical_and(causal[None], same_seg)
    if length is not None:
        mask = jnp.logical_and(mask, (idx[None, None, :] < length[:, None, None]))
    logits = jnp.where(mask[:, None], logits, NEG_INF)
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
