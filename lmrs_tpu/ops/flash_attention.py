"""Pallas flash-attention (prefill) kernel for TPU.

Online-softmax tiling (flash-attention v2 schedule): grid over
(batch, q_head, q_block, kv_block) with f32 running max / sum / accumulator
in VMEM scratch; KV blocks stream through VMEM, so memory is O(blocks) not
O(S²) and the matmuls are MXU-shaped.  GQA is handled in the index maps — a
query head reads its kv-head's blocks directly, no materialized repeat.

Causal + ragged masking: blocks entirely above the diagonal are skipped
(predicated off), the diagonal block is masked elementwise, and a per-row
valid-length (`lengths`, from SMEM) masks padded KV — the kernel equivalent
of ops.attention's (causal & kv_length) rule.

Correctness contract: must match ops.attention.attention() to f32 tolerance —
see tests/test_kernels.py.  Falls back to interpret mode off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lmrs_tpu.utils.env import env_int
from lmrs_tpu.utils.jax_compat import shard_map, tpu_compiler_params

NEG_INF = -1e30

# Default q/kv tile: bigger tiles = fewer grid programs = less per-program
# overhead, up to the VMEM ceiling (2048 tiles fail to compile at hd=128).
# Measured r2: 256 -> 512 was +3.6 MFU points; r4 interleaved sweep
# (min-of-4-rounds, RTT-amortized chains): 512 -> 1024 is a further 1.5x
# on the kernel at the bench packed shape (S=4096: 1.79 -> 1.20 ms, 19.5 ->
# 29.1% MFU; S=2048: 1.8x).  The wrapper clamps blocks to the sequence, so
# small buckets degrade gracefully.  Env knob for A/B sweeps.
_DEFAULT_BLOCK = env_int("LMRS_FLASH_BLOCK", 1024, lo=128)


def _flash_kernel(
    lengths_ref,  # SMEM [B] valid kv length per batch row (unblocked)
    q_ref,        # VMEM [1, 1, QB, hd]
    k_ref,        # VMEM [1, 1, KB, hd]
    v_ref,        # VMEM [1, 1, KB, hd]
    *args,        # [sq_ref (1, QB), sk_ref (1, KB) when has_segs;]
                  # o_ref, m_scr, l_scr, acc_scr
    q_block: int,
    kv_block: int,
    sm_scale: float,
    skip_padded_q: bool,
    has_segs: bool = False,
):
    if has_segs:
        # packed-prompt prefill: per-token segment ids; a key is visible to
        # a query only within the same segment (cross-segment attention is
        # the packing bug this mask exists to prevent)
        sq_ref, sk_ref, o_ref, m_scr, l_scr, acc_scr = args
    else:
        sq_ref = sk_ref = None
        o_ref, m_scr, l_scr, acc_scr = args
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * q_block
    k_start = ki * kv_block
    length = lengths_ref[pl.program_id(0)]

    # A (q, kv) block pair is live iff some VALID query row can see it:
    # the kv block starts at or before the last query position, intersects
    # the valid prefix, and the q block contains at least one valid row —
    # padded q blocks (prompt bucketed up past its length) would otherwise
    # re-compute attention over the whole valid prefix for garbage rows
    # (~43% of the MXU work for a 2.3k prompt in the 4096 bucket).  Skipped
    # blocks still init/finalize, so their output rows are well-defined
    # zeros, and valid rows never attend them (causal + length mask).
    live = jnp.logical_and(k_start <= q_start + q_block - 1, k_start < length)
    if skip_padded_q:
        live = jnp.logical_and(live, q_start < length)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [QB, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [KB, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [QB, KB]

        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (q_block, kv_block), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (q_block, kv_block), 1)
        mask = jnp.logical_and(k_pos <= q_pos, k_pos < length)
        if has_segs:
            mask = jnp.logical_and(mask, sq_ref[0][:, None] == sk_ref[0][None, :])
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                      # [QB, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [QB, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)            # [QB, 1]
        p = jnp.exp(s - m_new)                     # [QB, KB]
        # fully-masked rows: m_new == NEG_INF -> p == exp(0) == 1; zero them
        p = jnp.where(m_new > NEG_INF * 0.5, p, 0.0)

        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)        # [KB, hd]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("q_block", "kv_block", "interpret",
                              "skip_padded_q")
)
def flash_attention(
    q: jnp.ndarray,          # [B, Sq, H, hd]
    k: jnp.ndarray,          # [B, Skv, K, hd]
    v: jnp.ndarray,          # [B, Skv, K, hd]
    lengths: jnp.ndarray | None = None,  # [B] valid kv length
    q_block: int = _DEFAULT_BLOCK,
    kv_block: int = _DEFAULT_BLOCK,
    interpret: bool = False,
    skip_padded_q: bool = True,
    segment_ids: jnp.ndarray | None = None,  # [B, S] packed-prompt segments
) -> jnp.ndarray:
    """Causal flash attention over fresh (position-0-based) sequences.

    Requires Sq == Skv (self-attention prefill / training).  Returns
    [B, Sq, H, hd] in q.dtype.  With ``skip_padded_q`` (default), rows at
    positions >= lengths[b] are exactly zero — their blocks are predicated
    off entirely (a bucketed prompt would otherwise burn MXU time computing
    attention for garbage rows); pass False to compute them anyway.

    ``segment_ids`` enables packed-prompt prefill (several prompts
    concatenated into one row): attention is additionally masked to
    same-segment pairs, so causal masking on the global row index becomes
    per-segment causality (segments are contiguous).
    """
    b, sq, h, hd = q.shape
    skv, kh = k.shape[1], k.shape[2]
    assert sq == skv, "flash_attention is for self-attention prefill"
    n_rep = h // kh
    if lengths is None:
        lengths = jnp.full((b,), sq, jnp.int32)

    q_block = min(q_block, sq)
    kv_block = min(kv_block, skv)
    pad_q = (-sq) % q_block
    pad_kv = (-skv) % kv_block
    if pad_q or pad_kv:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_kv), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_kv), (0, 0), (0, 0)))
        if segment_ids is not None:
            # pad tokens get segment -1: matches nothing valid (and the
            # length mask already excludes them as keys)
            segment_ids = jnp.pad(segment_ids, ((0, 0), (0, pad_q)),
                                  constant_values=-1)
    sq_p, skv_p = q.shape[1], k.shape[1]

    # head-major layout for blocking
    qt = q.transpose(0, 2, 1, 3)  # [B, H, S, hd]
    kt = k.transpose(0, 2, 1, 3)  # [B, K, S, hd]
    vt = v.transpose(0, 2, 1, 3)

    has_segs = segment_ids is not None
    grid = (b, h, sq_p // q_block, skv_p // kv_block)
    kernel = functools.partial(
        _flash_kernel, q_block=q_block, kv_block=kv_block,
        sm_scale=hd ** -0.5, skip_padded_q=skip_padded_q, has_segs=has_segs,
    )
    in_specs = [
        # whole [B] array in SMEM (rank-1 blocking is restricted on real
        # TPU lowering); the kernel indexes it by program_id(0)
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, q_block, hd),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, kv_block, hd),
                     lambda bi, hi, qi, ki: (bi, hi // n_rep, ki, 0)),
        pl.BlockSpec((1, 1, kv_block, hd),
                     lambda bi, hi, qi, ki: (bi, hi // n_rep, ki, 0)),
    ]
    operands = [lengths.astype(jnp.int32), qt, kt, vt]
    if has_segs:
        segs = segment_ids.astype(jnp.int32)
        in_specs += [
            pl.BlockSpec((1, q_block), lambda bi, hi, qi, ki: (bi, qi)),
            pl.BlockSpec((1, kv_block), lambda bi, hi, qi, ki: (bi, ki)),
        ]
        operands += [segs, segs]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, q_block, hd),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, 128), jnp.float32),
            pltpu.VMEM((q_block, hd), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)

    out = out.transpose(0, 2, 1, 3)  # back to [B, S, H, hd]
    if pad_q:
        out = out[:, :sq]
    return out


def flash_attention_sharded(
    q: jnp.ndarray,        # [B, Sq, H, hd] (H sharded over tp)
    k: jnp.ndarray,        # [B, Skv, K, hd] (K sharded over tp)
    v: jnp.ndarray,        # [B, Skv, K, hd]
    lengths: jnp.ndarray,  # [B] replicated
    mesh,
    interpret: bool = False,
    segment_ids: jnp.ndarray | None = None,  # [B, S] replicated
) -> jnp.ndarray:
    """Flash prefill under a tensor-parallel mesh: ``shard_map`` over the
    ``tp`` head axis (a pallas_call cannot be auto-partitioned by XLA).
    Attention is independent per head and Q heads shard together with their
    kv head (GQA grouping stays shard-local), so each shard runs the
    unmodified kernel on its local heads."""
    from jax.sharding import PartitionSpec as P

    head4 = P(None, None, "tp", None)
    if segment_ids is None:
        fn = shard_map(
            functools.partial(flash_attention, interpret=interpret),
            mesh=mesh,
            in_specs=(head4, head4, head4, P(None)),
            out_specs=head4,
            check_vma=False,
        )
        return fn(q, k, v, lengths)
    fn = shard_map(
        lambda q_, k_, v_, l_, s_: flash_attention(
            q_, k_, v_, l_, interpret=interpret, segment_ids=s_),
        mesh=mesh,
        in_specs=(head4, head4, head4, P(None), P(None, None)),
        out_specs=head4,
        check_vma=False,
    )
    return fn(q, k, v, lengths, segment_ids)
