"""L4 ops: core numerical kernels (XLA-fused reference paths + Pallas)."""

from lmrs_tpu.ops.norms import rms_norm
from lmrs_tpu.ops.rope import apply_rope, rope_table
from lmrs_tpu.ops.attention import attention
from lmrs_tpu.ops.sampling import sample_logits

__all__ = ["apply_rope", "attention", "rms_norm", "rope_table", "sample_logits"]
