"""On-device token sampling: greedy / temperature / top-k / top-p.

Sampler parameters arrive as arrays, not Python values, so ONE compiled
function serves every request's sampler config (single decode-step cache
entry).  Row-mixing uses where-masks; the two expensive stages — the
full-vocab sort behind top-k/top-p and the categorical draw — are gated
by ``lax.cond`` on traced any-row-needs-it scalars (round 5: the
unconditional sort cost 4.8 ms/step at a 128k vocab).  NOTE: under
``vmap`` those conds lower to select-both-branches and the sort would
silently return; the engine calls this from scan/while_loop contexts
only.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def filter_logits(
    logits: jnp.ndarray,  # [B, V] f32
    temperature: jnp.ndarray,  # [B] 0.0 => greedy
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    top_p: jnp.ndarray,  # [B] f32, 1.0 => disabled
) -> jnp.ndarray:
    """Temperature-scaled, top-k/top-p-masked logits [B, V] (-inf outside the
    nucleus).  softmax of the result is the sampling distribution for
    temperature > 0 rows; greedy rows are the caller's argmax special case."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)

    # temperature scaling (guard divide-by-zero; greedy rows overridden later)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # top-k mask: keep the k largest per row (k==0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] descending
    k_idx = jnp.clip(top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    k_mask = jnp.where((top_k > 0)[:, None], scaled >= kth, True)

    # top-p (nucleus) mask over the sorted distribution
    probs_desc = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_desc, axis=-1)
    # keep tokens whose cumulative prob (exclusive) < top_p
    cutoff_count = jnp.sum(cum - probs_desc < top_p[:, None], axis=-1)  # [B]
    p_idx = jnp.clip(cutoff_count - 1, 0, v - 1)
    pth = jnp.take_along_axis(sorted_desc, p_idx[:, None], axis=-1)
    p_mask = jnp.where((top_p < 1.0)[:, None], scaled >= pth, True)

    return jnp.where(k_mask & p_mask, scaled, -jnp.inf)


def filtered_probs(
    logits: jnp.ndarray,  # [B, V] f32
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """The per-row sampling distribution [B, V]: softmax of the filtered
    logits for temperature > 0, a one-hot argmax for greedy rows — the
    acceptance-test target in speculative decoding (ops/speculative.py)."""
    v = logits.shape[-1]
    probs = jax.nn.softmax(filter_logits(logits, temperature, top_k, top_p), -1)
    greedy = jax.nn.one_hot(jnp.argmax(logits, -1), v, dtype=probs.dtype)
    return jnp.where((temperature > 0)[:, None], probs, greedy)


def sample_logits(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] 0.0 => greedy
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    top_p: jnp.ndarray,  # [B] f32, 1.0 => disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B].

    Greedy is expressed as temperature==0 (the categorical draw is replaced by
    argmax via where), so batches can mix greedy and sampled requests.

    The expensive paths are gated by ``lax.cond`` on traced scalars (one
    compiled executable, device-side branch): the full-vocab sort inside
    ``filter_logits`` only runs when some row actually has top-k/top-p
    active, and the categorical draw only when some row samples.  At the
    8B shape the unconditional sort cost a measured 4.8 ms per decode
    step ([24, 128k] f32) — ~20% of the step — with every row greedy
    (docs/PERF.md round 5).  Branch outputs are identical to the
    unconditional formulation for every row mix: the temperature-only
    branch equals filter_logits with all masks disabled, so the same key
    over the same distribution draws the same token.
    """
    logits = logits.astype(jnp.float32)
    greedy_ids = jnp.argmax(logits, axis=-1)

    def _draw(_):
        def _filtered(_):
            return filter_logits(logits, temperature, top_k, top_p)

        def _temp_only(_):
            safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
            return logits / safe_t

        needs_filter = jnp.any(
            (temperature > 0) & ((top_k > 0) | (top_p < 1.0)))
        masked = jax.lax.cond(needs_filter, _filtered, _temp_only, None)
        return jax.random.categorical(key, masked, axis=-1)

    any_sampled = jnp.any(temperature > 0)
    sampled = jax.lax.cond(any_sampled, _draw, lambda _: greedy_ids, None)
    return jnp.where(temperature > 0, sampled, greedy_ids)
