"""On-device token sampling: greedy / temperature / top-k / top-p.

All branches are trace-friendly (lax.cond-free formulations using where-masks)
so one compiled function serves every request's sampler config — the sampler
parameters arrive as arrays, not Python values, keeping the decode step's
compilation cache to a single entry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] 0.0 => greedy
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    top_p: jnp.ndarray,  # [B] f32, 1.0 => disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B].

    Greedy is expressed as temperature==0 (the categorical draw is replaced by
    argmax via where), so batches can mix greedy and sampled requests.
    """
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_ids = jnp.argmax(logits, axis=-1)

    # temperature scaling (guard divide-by-zero; greedy rows overridden below)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # top-k mask: keep the k largest per row (k==0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] descending
    k_idx = jnp.clip(top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    k_mask = jnp.where((top_k > 0)[:, None], scaled >= kth, True)

    # top-p (nucleus) mask over the sorted distribution
    probs_desc = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_desc, axis=-1)
    # keep tokens whose cumulative prob (exclusive) < top_p
    cutoff_count = jnp.sum(cum - probs_desc < top_p[:, None], axis=-1)  # [B]
    p_idx = jnp.clip(cutoff_count - 1, 0, v - 1)
    pth = jnp.take_along_axis(sorted_desc, p_idx[:, None], axis=-1)
    p_mask = jnp.where((top_p < 1.0)[:, None], scaled >= pth, True)

    masked = jnp.where(k_mask & p_mask, scaled, -jnp.inf)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy_ids)
