"""On-device token sampling: greedy / temperature / top-k / top-p.

All branches are trace-friendly (lax.cond-free formulations using where-masks)
so one compiled function serves every request's sampler config — the sampler
parameters arrive as arrays, not Python values, keeping the decode step's
compilation cache to a single entry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def filter_logits(
    logits: jnp.ndarray,  # [B, V] f32
    temperature: jnp.ndarray,  # [B] 0.0 => greedy
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    top_p: jnp.ndarray,  # [B] f32, 1.0 => disabled
) -> jnp.ndarray:
    """Temperature-scaled, top-k/top-p-masked logits [B, V] (-inf outside the
    nucleus).  softmax of the result is the sampling distribution for
    temperature > 0 rows; greedy rows are the caller's argmax special case."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)

    # temperature scaling (guard divide-by-zero; greedy rows overridden later)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # top-k mask: keep the k largest per row (k==0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] descending
    k_idx = jnp.clip(top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    k_mask = jnp.where((top_k > 0)[:, None], scaled >= kth, True)

    # top-p (nucleus) mask over the sorted distribution
    probs_desc = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_desc, axis=-1)
    # keep tokens whose cumulative prob (exclusive) < top_p
    cutoff_count = jnp.sum(cum - probs_desc < top_p[:, None], axis=-1)  # [B]
    p_idx = jnp.clip(cutoff_count - 1, 0, v - 1)
    pth = jnp.take_along_axis(sorted_desc, p_idx[:, None], axis=-1)
    p_mask = jnp.where((top_p < 1.0)[:, None], scaled >= pth, True)

    return jnp.where(k_mask & p_mask, scaled, -jnp.inf)


def filtered_probs(
    logits: jnp.ndarray,  # [B, V] f32
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """The per-row sampling distribution [B, V]: softmax of the filtered
    logits for temperature > 0, a one-hot argmax for greedy rows — the
    acceptance-test target in speculative decoding (ops/speculative.py)."""
    v = logits.shape[-1]
    probs = jax.nn.softmax(filter_logits(logits, temperature, top_k, top_p), -1)
    greedy = jax.nn.one_hot(jnp.argmax(logits, -1), v, dtype=probs.dtype)
    return jnp.where((temperature > 0)[:, None], probs, greedy)


def sample_logits(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] 0.0 => greedy
    top_k: jnp.ndarray,  # [B] int32, 0 => disabled
    top_p: jnp.ndarray,  # [B] f32, 1.0 => disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B].

    Greedy is expressed as temperature==0 (the categorical draw is replaced by
    argmax via where), so batches can mix greedy and sampled requests.
    """
    greedy_ids = jnp.argmax(logits.astype(jnp.float32), axis=-1)
    masked = filter_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy_ids)
