"""Normalization ops.

RMSNorm in f32 accumulation regardless of input dtype — the standard TPU
recipe (bf16 inputs, f32 statistics) so XLA fuses it into the surrounding
matmuls without precision loss."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm: x * rsqrt(mean(x^2)) * (1 + scale) computed in f32.

    Uses the (1 + scale) parameterization (Gemma/Llama-3 style) so a
    zero-initialized scale is the identity transform.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * (1.0 + scale.astype(jnp.float32))).astype(dtype)
