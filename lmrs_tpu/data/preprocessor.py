"""Transcript segment preprocessing.

Capability parity with the reference preprocessor (preprocessor.py:15-361):
drop empties, clean text, merge consecutive same-speaker segments under a
duration cap (keeping per-original timing + inline ``[MM:SS]`` markers), or
re-bucket into fixed time intervals.  Pure functions of their inputs — the
deterministic half of the pipeline, unit-tested directly (SURVEY.md §4).

Divergences from the reference (deliberate, per SURVEY.md §2.3):
* no dead ``is_single_speaker`` computation (quirk 4);
* no ``print`` progress — structured logging only (§5.5).
"""

from __future__ import annotations

import logging
import re
from typing import Any, Iterable

logger = logging.getLogger("lmrs.preprocessor")

Segment = dict[str, Any]

_WS_RE = re.compile(r"\s+")
_REPEAT_WORD_RE = re.compile(r"\b(\w+)(\s+\1\b)+", re.IGNORECASE)
_PUNCT_SPACE_RE = re.compile(r"([.!?,;:])([A-Za-z])")


def clean_text_py(text: str) -> str:
    """Pure-Python clean_text (the parity reference for the native path)."""
    if not text:
        return ""
    text = _WS_RE.sub(" ", text).strip()
    text = _REPEAT_WORD_RE.sub(r"\1", text)
    text = _PUNCT_SPACE_RE.sub(r"\1 \2", text)
    return text


def clean_text(text: str) -> str:
    """Normalize a segment's text (reference clean_text, preprocessor.py:69-89).

    Collapses whitespace, dedups immediately-repeated words ("the the" →
    "the"), and restores a missing space after sentence punctuation
    ("end.Next" → "end. Next").  Runs the C++ scan (runtime/native) when the
    native library is built; falls back to the regex implementation.
    """
    if not text:
        return ""
    from lmrs_tpu.runtime.native import clean_text_native

    cleaned = clean_text_native(text)
    if cleaned is not None:
        return cleaned
    return clean_text_py(text)


def _clean_all(texts: list) -> list[str]:
    """Clean a list of texts — one native batch call, or the per-string path.

    Non-string entries (e.g. ``"text": null`` in the input JSON) clean to ""
    and are dropped by the caller, matching clean_text's falsy-input rule.
    """
    from lmrs_tpu.runtime.native import clean_text_batch

    texts = [t if isinstance(t, str) else "" for t in texts]
    batch = clean_text_batch(texts)
    if batch is not None:
        return batch
    return [clean_text_py(t) for t in texts]


def format_timestamp(seconds: float) -> str:
    """Seconds → ``MM:SS`` (or ``H:MM:SS`` past one hour).

    Reference: preprocessor.py:91-107.
    """
    seconds = max(0, int(seconds))
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    if h:
        return f"{h}:{m:02d}:{s:02d}"
    return f"{m:02d}:{s:02d}"


def preprocess_transcript(
    segments: Iterable[Segment],
    merge_same_speaker: bool = True,
    time_interval_seconds: float | None = None,
    max_segment_duration: float = 120.0,
    preserve_timestamps: bool = True,
) -> list[Segment]:
    """Clean + merge diarized segments (reference preprocess_transcript,
    preprocessor.py:15-67).

    Input schema per segment: ``{"start": s, "end": s, "text": str,
    "speaker": str}`` (README.md:162-175).  Output segments add
    ``segment_timestamps`` (per-original timing) when merged.
    """
    segments = list(segments)
    texts = _clean_all([seg.get("text", "") for seg in segments])
    cleaned: list[Segment] = []
    for seg, text in zip(segments, texts):
        if not text:
            continue  # drop empty segments (preprocessor.py:37-39)
        cleaned.append(
            {
                "start": float(seg.get("start", 0.0)),
                "end": float(seg.get("end", 0.0)),
                "text": text,
                "speaker": seg.get("speaker", "UNKNOWN"),
            }
        )

    if time_interval_seconds:
        out = aggregate_by_time_interval(cleaned, time_interval_seconds, preserve_timestamps)
    elif merge_same_speaker:
        out = combine_same_speaker_segments(cleaned, max_segment_duration, preserve_timestamps)
    else:
        out = cleaned

    logger.info("preprocessed %d segments -> %d", len(list(cleaned)), len(out))
    return out


def combine_same_speaker_segments(
    segments: list[Segment],
    max_segment_duration: float = 120.0,
    preserve_timestamps: bool = True,
) -> list[Segment]:
    """Merge consecutive same-speaker segments up to a duration cap.

    Reference: combine_same_speaker_segments (preprocessor.py:109-165) +
    create_combined_segment (:167-215).
    """
    if not segments:
        return []
    merged: list[Segment] = []
    run: list[Segment] = [segments[0]]
    for seg in segments[1:]:
        same = seg["speaker"] == run[0]["speaker"]
        would_span = seg["end"] - run[0]["start"]
        if same and would_span <= max_segment_duration:
            run.append(seg)
        else:
            merged.append(_combine_run(run, preserve_timestamps))
            run = [seg]
    merged.append(_combine_run(run, preserve_timestamps))
    return merged


def _combine_run(run: list[Segment], preserve_timestamps: bool) -> Segment:
    if len(run) == 1:
        seg = dict(run[0])
        seg["segment_timestamps"] = [(seg["start"], seg["end"])]
        return seg
    if preserve_timestamps:
        # Inline [MM:SS] markers keep provenance through the merge
        # (reference embeds markers at preprocessor.py:190-197).
        parts = [f"[{format_timestamp(s['start'])}] {s['text']}" for s in run]
    else:
        parts = [s["text"] for s in run]
    return {
        "start": run[0]["start"],
        "end": run[-1]["end"],
        "text": " ".join(parts),
        "speaker": run[0]["speaker"],
        "segment_timestamps": [(s["start"], s["end"]) for s in run],
    }


def aggregate_by_time_interval(
    segments: list[Segment],
    interval_seconds: float,
    preserve_timestamps: bool = True,
) -> list[Segment]:
    """Re-bucket segments into fixed wall-clock intervals.

    Reference: aggregate_by_time_interval (preprocessor.py:217-324).  Buckets
    that receive no segments are simply absent.  Multi-speaker buckets get
    ``speaker="MULTIPLE"`` and per-utterance ``SPEAKER:`` prefixes.
    """
    if not segments or interval_seconds <= 0:
        return segments
    buckets: dict[int, list[Segment]] = {}
    for seg in segments:
        buckets.setdefault(int(seg["start"] // interval_seconds), []).append(seg)

    out: list[Segment] = []
    for idx in sorted(buckets):
        group = buckets[idx]
        speakers = {s["speaker"] for s in group}
        parts = []
        for s in group:
            prefix = f"[{format_timestamp(s['start'])}] " if preserve_timestamps else ""
            who = f"{s['speaker']}: " if len(speakers) > 1 else ""
            parts.append(f"{prefix}{who}{s['text']}")
        out.append(
            {
                "start": group[0]["start"],
                "end": group[-1]["end"],
                "text": " ".join(parts),
                "speaker": group[0]["speaker"] if len(speakers) == 1 else "MULTIPLE",
                "segment_timestamps": [(s["start"], s["end"]) for s in group],
            }
        )
    return out


def extract_speakers(segments: Iterable[Segment]) -> list[str]:
    """Unique speakers in first-appearance order (preprocessor.py:326-342)."""
    seen: dict[str, None] = {}
    for seg in segments:
        seen.setdefault(seg.get("speaker", "UNKNOWN"))
    return list(seen)


def get_transcript_duration(segments: list[Segment]) -> float:
    """Total span in seconds (preprocessor.py:344-361)."""
    if not segments:
        return 0.0
    return max(s["end"] for s in segments) - min(s["start"] for s in segments)


if __name__ == "__main__":  # stage demo (pattern: preprocessor.py:364-441)
    from lmrs_tpu.utils.demo import load_demo_transcript

    segs = load_demo_transcript()["segments"]
    out = preprocess_transcript(segs)
    print(f"segments in : {len(segs)}")
    print(f"segments out: {len(out)} (merge ratio {len(out) / max(len(segs), 1):.3f})")
    print(f"speakers    : {extract_speakers(out)}")
    print(f"duration    : {get_transcript_duration(out) / 3600:.2f} h")
    if out:
        print(f"first merged segment:\n  {out[0]['text'][:300]}")
