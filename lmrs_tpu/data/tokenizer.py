"""Pluggable tokenization authority.

The reference hardwires tiktoken ``cl100k_base`` as the token-count authority
(big_chunkeroosky.py:27,43; result_aggregator.py:36,50).  In the TPU build the
authority must be the *serving model's* tokenizer (SURVEY.md §7.4 item 4), so
everything downstream takes a ``Tokenizer`` object:

* ``ApproxTokenizer`` — deterministic ~4 chars/token estimator for offline
  counting parity with the reference's stubbed baseline run (BASELINE.md).
* ``ByteTokenizer`` — real reversible text<->ids mapping (UTF-8 bytes + special
  tokens).  Default vocabulary for randomly-initialized in-tree models; lets
  the full TPU engine run end-to-end with zero downloaded assets.
* ``SentencePieceTokenizer`` / ``HFTokenizer`` — adapters for real Gemma/Llama
  vocabularies when checkpoint assets are present on disk (gated import; this
  environment has no egress).
"""

from __future__ import annotations

import re
import zlib
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    """Minimal surface every stage relies on."""

    bos_id: int
    eos_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def count(self, text: str) -> int: ...


class ApproxTokenizer:
    """Heuristic counter: ~4 chars/token with a word-boundary floor.

    Matches the stub used to measure the reference baseline offline
    (BASELINE.md: "tiktoken stubbed at 4 chars/token").  ``encode`` maps each
    whitespace-delimited piece to a hashed id so code paths that need ids
    still work; ``decode`` is not faithful and only used in tests.
    """

    bos_id = 1
    eos_id = 2
    pad_id = 0
    vocab_size = 32768

    _word_re = re.compile(r"\S+")

    def count_py(self, text: str) -> int:
        """Pure-Python counter (parity reference for the native path)."""
        if not text:
            return 0
        return max(len(text) // 4, len(self._word_re.findall(text)) // 2, 1)

    def count(self, text: str) -> int:
        if not text:
            return 0
        from lmrs_tpu.runtime.native import count_approx_native

        n = count_approx_native(text)
        if n is not None:
            return n
        return self.count_py(text)

    def count_batch(self, texts: list[str]) -> list[int]:
        """Batched counting — one native FFI crossing for the whole list
        (the chunker hot loop, SURVEY.md §3.5 #2)."""
        from lmrs_tpu.runtime.native import count_approx_batch

        batch = count_approx_batch(texts)
        if batch is not None:
            return batch
        return [self.count_py(t) for t in texts]

    def encode(self, text: str) -> list[int]:
        return [
            3 + (zlib.crc32(w.encode("utf-8")) % (self.vocab_size - 3))
            for w in self._word_re.findall(text)
        ]

    def decode(self, ids: Sequence[int]) -> str:
        return " ".join(f"<{i}>" for i in ids)


class ByteTokenizer:
    """Reversible UTF-8 byte-level tokenizer.

    ids 0..2 are pad/bos/eos; byte b maps to id b+3.  vocab_size=259 rounds up
    to 512 in model configs for MXU-friendly embedding shapes.
    """

    pad_id = 0
    bos_id = 1
    eos_id = 2
    vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return [b + 3 for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # ids outside [3, 258] (specials, or vocab rounded up for MXU-friendly
        # embedding shapes) are skipped
        return bytes(i - 3 for i in ids if 3 <= i <= 258).decode("utf-8", errors="replace")

    def count(self, text: str) -> int:
        return len(text.encode("utf-8"))


class SentencePieceTokenizer:
    """Adapter over a local ``.model`` SentencePiece file (Gemma/Llama-2)."""

    def __init__(self, model_path: str):
        import sentencepiece as spm  # gated: not guaranteed in image

        self._sp = spm.SentencePieceProcessor(model_file=model_path)
        self.bos_id = self._sp.bos_id()
        self.eos_id = self._sp.eos_id()
        self.pad_id = max(self._sp.pad_id(), 0)
        self.vocab_size = self._sp.vocab_size()

    def encode(self, text: str) -> list[int]:
        return list(self._sp.encode(text))

    def decode(self, ids: Sequence[int]) -> str:
        return self._sp.decode(list(ids))

    def count(self, text: str) -> int:
        return len(self.encode(text))


class HFTokenizer:
    """Adapter over a locally-cached HuggingFace tokenizer (Llama-3 BPE)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer  # local files only; no egress

        self._tok = AutoTokenizer.from_pretrained(name_or_path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id or 1
        self.eos_id = self._tok.eos_token_id or 2
        self.pad_id = self._tok.pad_token_id or 0
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def count(self, text: str) -> int:
        return len(self.encode(text))


def get_tokenizer(name: str = "approx") -> Tokenizer:
    """Resolve a tokenizer spec: "approx", "byte", ``*.model`` path, or HF id."""
    if name == "approx":
        return ApproxTokenizer()
    if name == "byte":
        return ByteTokenizer()
    if name.endswith(".model"):
        return SentencePieceTokenizer(name)
    return HFTokenizer(name)
