"""Token-budget transcript chunker ("Big Chunkeroosky" capability).

Greedy packer over preprocessed segments into chunks bounded by
``max_tokens_per_chunk - context_tokens``, with sentence-aware splitting of
oversized segments, clause/word fallbacks for pathological sentences,
per-sentence timestamp interpolation by character position, and a context
header per chunk (time range, speakers, ordinal, position-in-transcript).

Reference: big_chunkeroosky.py:20-567 (greedy loop :80-137; sentence split
:267-435; clause fallback :437-542; header :197-232; finalize :147-195).

Deliberate fixes over the reference (SURVEY.md §2.3):
* ``overlap_tokens`` is real: each chunk after the first re-includes trailing
  sentences of the previous chunk up to the overlap budget (quirk 1 — the
  reference stores the knob and never reads it).
* ``position_percentage`` is measured against the WHOLE transcript span, not
  the chunk's own span (quirk 2).
* Sentence segmentation is an in-tree splitter (no NLTK punkt download).
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from typing import Any

from lmrs_tpu.data.preprocessor import format_timestamp
from lmrs_tpu.data.tokenizer import Tokenizer, get_tokenizer

logger = logging.getLogger("lmrs.chunker")

Segment = dict[str, Any]

# Sentence boundary: terminal punctuation (+ closing quotes/brackets) followed
# by whitespace and an upper-case/digit/bracket start.  Common abbreviations
# are protected.  Replaces NLTK punkt (big_chunkeroosky.py:14-18,44) — punkt
# model data is not available offline.
_ABBREV = r"(?<!\b[A-Z])(?<!\bDr)(?<!\bMr)(?<!\bMs)(?<!\bMrs)(?<!\bSt)(?<!\bvs)(?<!\be\.g)(?<!\bi\.e)(?<!\betc)"
_SENT_RE = re.compile(_ABBREV + r'([.!?]+["\')\]]*)\s+(?=["\'(\[]?[A-Z0-9])')
_CLAUSE_RE = re.compile(r"(?<=[,;:])\s+")


def split_sentences(text: str) -> list[str]:
    """Split text into sentences, keeping terminal punctuation attached."""
    if not text:
        return []
    parts: list[str] = []
    last = 0
    for m in _SENT_RE.finditer(text):
        parts.append(text[last : m.end(1)].strip())
        last = m.end(1)
    tail = text[last:].strip()
    if tail:
        parts.append(tail)
    return [p for p in parts if p]


@dataclass
class Chunk:
    """One map-stage work item (reference chunk record schema,
    big_chunkeroosky.py:70-77,166-195)."""

    segments: list[Segment] = field(default_factory=list)
    text: str = ""
    token_count: int = 0
    start_time: float = 0.0
    end_time: float = 0.0
    speakers: list[str] = field(default_factory=list)
    chunk_index: int = 0
    total_chunks: int = 0
    position_percentage: float = 0.0
    text_with_context: str = ""
    # filled by the map stage (llm_executor.py:205-211 equivalents)
    summary: str | None = None
    tokens_used: int = 0
    device_seconds: float = 0.0
    error: str | None = None
    system_prompt: str | None = None

    def to_dict(self) -> dict:
        return {
            "segments": self.segments,
            "text": self.text,
            "token_count": self.token_count,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "speakers": self.speakers,
            "chunk_index": self.chunk_index,
            "total_chunks": self.total_chunks,
            "position_percentage": self.position_percentage,
            "text_with_context": self.text_with_context,
            "summary": self.summary,
            "tokens_used": self.tokens_used,
            "error": self.error,
        }


class TranscriptChunker:
    """Greedy token-budget packer (reference BigChunkeroosky,
    big_chunkeroosky.py:23-44)."""

    def __init__(
        self,
        max_tokens_per_chunk: int = 4000,
        overlap_tokens: int = 200,
        tokenizer: Tokenizer | str = "approx",
        context_tokens: int = 150,
    ):
        if max_tokens_per_chunk <= context_tokens:
            raise ValueError("max_tokens_per_chunk must exceed context_tokens")
        self.max_tokens_per_chunk = max_tokens_per_chunk
        self.overlap_tokens = max(0, overlap_tokens)
        self.context_tokens = context_tokens
        self.effective_max_tokens = max_tokens_per_chunk - context_tokens
        self.tokenizer = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer

    # -- public API ---------------------------------------------------------

    def chunk_transcript(self, segments: list[Segment]) -> list[Chunk]:
        """Pack segments into token-budgeted chunks (big_chunkeroosky.py:46-145).

        One-shot chunking IS the incremental state machine fed everything
        at once (``incremental()``): the live-session tier depends on the
        two paths never diverging, so there is exactly one packing loop."""
        if not segments:
            return []
        inc = self.incremental()
        inc.append(segments)
        chunks = inc.chunks()
        logger.info(
            "chunked %d segments -> %d chunks (budget %d tok, overlap %d)",
            len(segments), len(chunks), self.effective_max_tokens, self.overlap_tokens,
        )
        return chunks

    def incremental(self) -> "IncrementalChunking":
        """Append-only chunking state for a transcript that GROWS (live
        sessions, lmrs_tpu/live/): ``append`` extends the stream,
        ``chunks`` snapshots the pack so far.  Sealed chunk identities
        ``(chunk_index, start_time, end_time)`` and text are frozen the
        moment a later chunk opens; only the open tail chunk extends —
        the stability every downstream cache key (map summaries, reduce
        nodes) leans on."""
        return IncrementalChunking(self)

    def postprocess_chunks(self, chunks: list[Chunk]) -> list[Chunk]:
        """Backfill total_chunks + refresh headers (big_chunkeroosky.py:544-567)."""
        total = len(chunks)
        for c in chunks:
            c.total_chunks = total
            c.text_with_context = self._create_context_header(c) + c.text
        return chunks

    # -- internals ----------------------------------------------------------

    def _count(self, text: str) -> int:
        return self.tokenizer.count(text)

    def _count_batch(self, texts: list[str]) -> list[int]:
        """One call for many strings (native batch path when available)."""
        fn = getattr(self.tokenizer, "count_batch", None)
        if fn is not None:
            return fn(texts)
        return [self.tokenizer.count(t) for t in texts]

    def _overlap_segments(self, packed: list[Segment]) -> list[Segment]:
        """Trailing sentences of a finished chunk, up to ``overlap_tokens``.

        Real implementation of the knob the reference ignores (quirk 1).
        Overlap re-enters the next chunk as a synthetic context segment so
        timestamps stay truthful.
        """
        if not self.overlap_tokens:
            return []
        picked: list[str] = []
        budget = self.overlap_tokens
        last = packed[-1]
        for sent in reversed(split_sentences(last["text"])):
            n = self._count(sent)
            if n > budget:
                break
            picked.insert(0, sent)
            budget -= n
        if not picked:
            return []
        return [
            {
                "start": last["start"],
                "end": last["end"],
                "text": " ".join(picked),
                "speaker": last.get("speaker", "UNKNOWN"),
                "is_overlap": True,
            }
        ]

    def _finalize_chunk(
        self, segments: list[Segment], index: int, t0: float, t1: float
    ) -> Chunk:
        """Assemble the chunk record (big_chunkeroosky.py:147-195).

        ``position_percentage`` is the chunk start's position within the WHOLE
        transcript span — the reference mistakenly normalizes by the chunk's
        own span (quirk 2)."""
        start = min(s["start"] for s in segments)
        end = max(s["end"] for s in segments)
        speakers: dict[str, None] = {}
        for s in segments:
            speakers.setdefault(s.get("speaker", "UNKNOWN"))
        text = " ".join(self._format_segment(s) for s in segments)
        span = max(t1 - t0, 1e-9)
        chunk = Chunk(
            segments=[dict(s) for s in segments],
            text=text,
            token_count=self._count(text),
            start_time=start,
            end_time=end,
            speakers=list(speakers),
            chunk_index=index,
            position_percentage=100.0 * (start - t0) / span,
        )
        chunk.text_with_context = self._create_context_header(chunk) + chunk.text
        return chunk

    def _format_segment(self, seg: Segment) -> str:
        """Per-segment text with a leading timestamp marker
        (big_chunkeroosky.py:244-265)."""
        marker = f"[{format_timestamp(seg['start'])}]"
        if seg.get("is_overlap"):
            return f"(context from previous chunk: {seg['text']})"
        if seg["text"].startswith("["):  # already carries inline markers
            return seg["text"]
        return f"{marker} {seg['text']}"

    def _create_context_header(self, chunk: Chunk) -> str:
        """Orientation header the map model sees (big_chunkeroosky.py:197-232)."""
        time_range = (
            f"{format_timestamp(chunk.start_time)} - {format_timestamp(chunk.end_time)}"
        )
        total = chunk.total_chunks or "?"
        return (
            f"[TRANSCRIPT SECTION {chunk.chunk_index + 1} of {total}]\n"
            f"[TIME RANGE: {time_range}]\n"
            f"[SPEAKERS: {', '.join(chunk.speakers)}]\n"
            f"[POSITION: {chunk.position_percentage:.0f}% through the transcript]\n\n"
        )

    def stable_context_header(self, chunk: Chunk) -> str:
        """Append-stable variant of the context header (live sessions,
        lmrs_tpu/live/): no ``of N`` total and no position percentage —
        both change on every append, so a map prompt carrying them could
        never be cached across refreshes (the summary a sealed chunk got
        at 8 chunks would differ from the one a cold run of 31 chunks
        gives it).  Everything left is a pure function of the chunk
        itself."""
        time_range = (
            f"{format_timestamp(chunk.start_time)} - {format_timestamp(chunk.end_time)}"
        )
        return (
            f"[TRANSCRIPT SECTION {chunk.chunk_index + 1}]\n"
            f"[TIME RANGE: {time_range}]\n"
            f"[SPEAKERS: {', '.join(chunk.speakers)}]\n\n"
        )

    def _chunk_large_segment(self, seg: Segment) -> list[Segment]:
        """Split an oversized segment into sentence-level pieces, each under
        the budget, with timestamps interpolated by character position
        (big_chunkeroosky.py:267-435, interpolation :351-366)."""
        sentences = split_sentences(seg["text"])
        sent_counts = dict(zip(sentences, self._count_batch(sentences)))
        pieces: list[Segment] = []
        total_chars = max(len(seg["text"]), 1)
        span = seg["end"] - seg["start"]
        cursor = 0

        def time_at(char_pos: int) -> float:
            return seg["start"] + span * (char_pos / total_chars)

        buf: list[str] = []
        buf_tokens = 0
        buf_start_char = 0

        def flush_buf(end_char: int) -> None:
            nonlocal buf, buf_tokens, buf_start_char
            if buf:
                pieces.append(
                    {
                        "start": time_at(buf_start_char),
                        "end": time_at(end_char),
                        "text": " ".join(buf),
                        "speaker": seg.get("speaker", "UNKNOWN"),
                    }
                )
            buf, buf_tokens = [], 0
            buf_start_char = end_char

        for sent in sentences:
            n = sent_counts[sent]
            if n > self.effective_max_tokens:
                flush_buf(cursor)
                # advance the char cursor per fragment so interior flushes
                # interpolate distinct timestamps (not the sentence start)
                for frag in self._split_long_sentence(sent):
                    fn = self._count(frag)
                    if buf_tokens + fn > self.effective_max_tokens:
                        flush_buf(cursor)
                    buf.append(frag)
                    buf_tokens += fn
                    cursor += len(frag) + 1
                flush_buf(cursor)
                continue
            if buf_tokens + n > self.effective_max_tokens:
                flush_buf(cursor)
            buf.append(sent)
            buf_tokens += n
            cursor += len(sent) + 1
        flush_buf(total_chars)
        return pieces

    def _split_long_sentence(self, sentence: str) -> list[str]:
        """Clause-level split with ~20-word group fallback
        (big_chunkeroosky.py:437-542)."""
        clauses = _CLAUSE_RE.split(sentence)
        out: list[str] = []
        for clause in clauses:
            if self._count(clause) <= self.effective_max_tokens:
                out.append(clause)
                continue
            words = clause.split()
            for i in range(0, len(words), 20):
                out.append(" ".join(words[i : i + 20]))
        return [c for c in out if c]


class IncrementalChunking:
    """Append-only chunking state (``TranscriptChunker.incremental``).

    THE packing loop of the repo — ``chunk_transcript`` routes through it
    — restructured so the greedy cursor survives between appends.  The
    greedy packer is forward-only (a chunk's contents depend only on
    segments before it), which is what makes incremental emission
    byte-identical to a one-shot pack over the same segment prefix:

    * **sealed chunks** (everything before the open tail) froze their
      segment list, text, token count, and ``(chunk_index, start_time,
      end_time)`` identity the moment the next chunk opened — an append
      can never move an emitted boundary;
    * the **open tail chunk** extends (or flushes and opens successors)
      exactly as the one-shot loop would have, had the appended segments
      been present from the start;
    * snapshot-time fields that depend on the WHOLE transcript so far
      (``total_chunks``, ``position_percentage``, the context header) are
      recomputed per ``chunks()`` call — they are presentation, not
      identity, and the one-shot path recomputes them the same way.

    Not thread-safe: callers (the live session tier) serialize appends
    per session.
    """

    def __init__(self, chunker: TranscriptChunker):
        self._ck = chunker
        self._sealed: list[Chunk] = []   # identity/text frozen forever
        self._current: list[Segment] = []  # the open tail's segments
        self._current_tokens = 0
        self._t0: float | None = None    # running min(start) over the stream
        self._t1: float | None = None    # running max(end)
        self._n_segments = 0

    @property
    def sealed_count(self) -> int:
        """Chunks whose identity and text can never change again."""
        return len(self._sealed)

    @property
    def chunk_count(self) -> int:
        """Sealed chunks + the open tail (what ``chunks()`` would return)."""
        return len(self._sealed) + (1 if self._current else 0)

    @property
    def chunker(self) -> TranscriptChunker:
        return self._ck

    @property
    def n_segments(self) -> int:
        return self._n_segments

    def append(self, segments: list[Segment]) -> None:
        """Extend the stream.  Continues the greedy pack exactly where the
        previous append left it (big_chunkeroosky.py:46-145 loop body)."""
        ck = self._ck
        if not segments:
            return
        for s in segments:
            self._t0 = s["start"] if self._t0 is None else min(self._t0, s["start"])
            self._t1 = s["end"] if self._t1 is None else max(self._t1, s["end"])
        self._n_segments += len(segments)
        seg_counts = ck._count_batch([s["text"] for s in segments])
        for seg, n in zip(segments, seg_counts):
            if n > ck.effective_max_tokens:
                # Oversized segment: flush, then split sentence-aware into
                # its own run of chunks (big_chunkeroosky.py:101-128).
                self._flush()
                if self._current:  # drop overlap before an oversized split run
                    self._current, self._current_tokens = [], 0
                for piece in ck._chunk_large_segment(seg):
                    pn = ck._count(piece["text"])
                    if self._current_tokens + pn > ck.effective_max_tokens:
                        self._flush()
                    self._current.append(piece)
                    self._current_tokens += pn
                continue
            if self._current_tokens + n > ck.effective_max_tokens:
                self._flush()
                if self._current_tokens + n > ck.effective_max_tokens:
                    # overlap seeding left no room for this segment — drop
                    # the overlap rather than exceed the budget
                    self._current, self._current_tokens = [], 0
            self._current.append(seg)
            self._current_tokens += n

    def _flush(self) -> None:
        """Seal the open tail and seed the next chunk with its overlap."""
        ck = self._ck
        if self._current:
            self._sealed.append(ck._finalize_chunk(
                self._current, len(self._sealed), self._t0, self._t1))
            overlap = ck._overlap_segments(self._current)
            self._current = overlap
            self._current_tokens = sum(ck._count(s["text"]) for s in overlap)

    def chunks(self) -> list[Chunk]:
        """Snapshot the pack so far — byte-identical to
        ``chunk_transcript`` over the same segment stream.

        Sealed chunks are the SAME objects across snapshots (their
        ``summary``/accounting fields, written by the map stage, survive);
        the open tail chunk is rebuilt per snapshot since appends extend
        it.  ``position_percentage`` / ``total_chunks`` / the context
        header are refreshed against the stream seen so far."""
        out = list(self._sealed)
        if self._current:
            out.append(self._ck._finalize_chunk(
                self._current, len(self._sealed), self._t0, self._t1))
        # whole-transcript presentation fields: the span grew with every
        # append, so sealed chunks' stored positions are stale snapshots
        span = max((self._t1 or 0.0) - (self._t0 or 0.0), 1e-9)
        for c in out:
            c.position_percentage = 100.0 * (c.start_time - (self._t0 or 0.0)) / span
        self._ck.postprocess_chunks(out)
        return out


if __name__ == "__main__":  # stage demo (pattern: big_chunkeroosky.py:570-606)
    from lmrs_tpu.data.preprocessor import preprocess_transcript
    from lmrs_tpu.utils.demo import load_demo_transcript

    segs = preprocess_transcript(load_demo_transcript()["segments"])
    chunker = TranscriptChunker()
    chunks = chunker.postprocess_chunks(chunker.chunk_transcript(segs))
    print(f"{len(segs)} segments -> {len(chunks)} chunks")
    for c in chunks[:3]:
        print(f"  chunk {c.chunk_index}/{c.total_chunks}: {c.token_count} tok, "
              f"{c.start_time:.0f}-{c.end_time:.0f}s, pos {c.position_percentage:.1f}%")
    if chunks:
        print("--- context header of chunk 0 ---")
        header = chunks[0].text_with_context[: len(chunks[0].text_with_context)
                                             - len(chunks[0].text)]
        print(header.strip()[:400])
