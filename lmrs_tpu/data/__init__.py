"""L1 data plane: transcript preprocessing, chunking, tokenization."""

from lmrs_tpu.data.chunker import Chunk, TranscriptChunker
from lmrs_tpu.data.preprocessor import (
    clean_text,
    extract_speakers,
    format_timestamp,
    get_transcript_duration,
    preprocess_transcript,
)
from lmrs_tpu.data.tokenizer import get_tokenizer

__all__ = [
    "Chunk",
    "TranscriptChunker",
    "clean_text",
    "extract_speakers",
    "format_timestamp",
    "get_transcript_duration",
    "get_tokenizer",
    "preprocess_transcript",
]
