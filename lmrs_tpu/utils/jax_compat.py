"""Version-bridging wrappers for JAX APIs that moved between releases.

The repo targets the modern public surface (``jax.shard_map`` with its
``check_vma`` kwarg) but must also run on the pinned CPU build
(jax 0.4.37), where ``shard_map`` still lives in ``jax.experimental``
under the older ``check_rep`` spelling — accessing ``jax.shard_map``
there raises ``AttributeError`` from the deprecation registry.

All call sites import from HERE (the lmrs-lint deprecated-API sub-pass
flags direct ``jax.shard_map`` / ``jax.experimental.shard_map`` use
anywhere else), so the day the old build is dropped this module shrinks
to one line instead of a five-file sweep.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):  # modern surface (jax >= 0.6)

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # pinned 0.4.x: experimental home, check_rep spelling

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


shard_map.__doc__ = """``jax.shard_map`` on every supported jax.

Keyword-only, mirroring the modern signature; ``check_vma`` maps onto the
legacy ``check_rep`` on 0.4.x builds (same meaning: verify per-axis value
replication instead of trusting ``out_specs``)."""


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across the rename: modern Pallas calls it
    ``CompilerParams``, 0.4.x ``TPUCompilerParams`` — same fields
    (``dimension_semantics`` et al.)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)
