"""Version-bridging wrappers for JAX APIs that moved between releases.

The repo targets the modern public surface (``jax.shard_map`` with its
``check_vma`` kwarg) but must also run on the pinned CPU build
(jax 0.4.37), where ``shard_map`` still lives in ``jax.experimental``
under the older ``check_rep`` spelling — accessing ``jax.shard_map``
there raises ``AttributeError`` from the deprecation registry.

All call sites import from HERE (the lmrs-lint deprecated-API sub-pass
flags direct ``jax.shard_map`` / ``jax.experimental.shard_map`` use
anywhere else), so the day the old build is dropped this module shrinks
to one line instead of a five-file sweep.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):  # modern surface (jax >= 0.6)

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # pinned 0.4.x: experimental home, check_rep spelling

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


shard_map.__doc__ = """``jax.shard_map`` on every supported jax.

Keyword-only, mirroring the modern signature; ``check_vma`` maps onto the
legacy ``check_rep`` on 0.4.x builds (same meaning: verify per-axis value
replication instead of trusting ``out_specs``)."""


_SHARDED_DONATION_PROBE: list = []  # memoized [error-or-None]


def sharded_donation_error() -> str | None:
    """Capability probe for donated sharded train updates, memoized per
    process.  The pinned CPU jaxlib (0.4.37 under the forced-host-device
    environment) fails donation aliasing on dp×tp-sharded train steps
    with ``INTERNAL: Expected aliased input ... to have the same size``
    — the runtime compares a replicated input's GLOBAL shape against the
    output's per-shard sub-shape.  Real TPU builds are unaffected.

    Runs ONE micro train step (dim 16, 1 layer, ~2 s on CPU) through the
    repo's own ``make_train_step`` — the exact machinery the capability
    gates — and returns the error string ONLY for the known
    donation-aliasing signature.  Any other failure returns ``None``
    (as does a probe that cannot run: fewer than 4 devices, optax
    missing): a genuine regression in make_train_step/shard_params must
    FAIL the real tests, never hide behind an "environmental" skip.
    Tests that need donated sharded updates skip-with-reason on a
    non-None return instead of erroring."""
    if _SHARDED_DONATION_PROBE:
        return _SHARDED_DONATION_PROBE[0]
    err: str | None = None
    try:
        import jax.numpy as jnp
        import numpy as np
        import optax

        from lmrs_tpu.config import MeshConfig, ModelConfig
        from lmrs_tpu.models.transformer import init_params
        from lmrs_tpu.parallel.mesh import build_mesh
        from lmrs_tpu.parallel.sharding import shard_params
        from lmrs_tpu.training.train import make_train_step

        if len(jax.devices()) < 4:
            _SHARDED_DONATION_PROBE.append(None)
            return None
        cfg = ModelConfig(vocab_size=32, dim=16, n_layers=1, n_heads=4,
                          n_kv_heads=2, hidden_dim=32, max_seq_len=32,
                          dtype="float32")
        mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=1, pp=1),
                          jax.devices()[:4])
        params = shard_params(init_params(cfg, jax.random.PRNGKey(0)),
                              mesh, cfg.tie_embeddings)
        opt = optax.adam(1e-3)
        step = make_train_step(cfg, opt, mesh)
        tokens = jnp.asarray(np.zeros((4, 16), dtype=np.int32))
        _, _, loss = step(params, opt.init(params), tokens)
        float(loss)
    except ImportError:
        err = None  # can't probe here; don't mask anything
    except Exception as e:  # noqa: BLE001 - filtered to the known class
        # ONLY the documented runtime-aliasing bug counts as a missing
        # capability; anything else is a real error the tests must see
        if "Expected aliased input" in str(e):
            err = f"{type(e).__name__}: {e}"
    _SHARDED_DONATION_PROBE.append(err)
    return err


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across the rename: modern Pallas calls it
    ``CompilerParams``, 0.4.x ``TPUCompilerParams`` — same fields
    (``dimension_semantics`` et al.)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)
