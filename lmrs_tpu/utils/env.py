"""Validated parser for ``LMRS_*`` environment knobs — the ONE env read path.

Every ``LMRS_*`` read in the tree routes through these helpers; the
``lmrs-lint`` env pass (``lmrs_tpu/analysis/envpass.py``) enforces that no
new ``os.environ``/``getenv`` call site for an ``LMRS_`` name appears
outside this module.  The rules exist because ad-hoc parsing produced real
production bugs (PR 8's review round):

* **empty means default** — ``LMRS_POSTMORTEM_MIN_S=""`` silently parsed
  to an unthrottled ``0``; an ``export NAME=`` must behave like unset;
* **numbers must be finite** — a NaN ``duration_s`` survived ``min``/
  ``max`` clamps and wedged the profiler's capture flag forever; NaN/inf
  never escape these helpers;
* **bad values degrade, never crash** — ``LMRS_FLASH_BLOCK=""`` used to
  raise ``ValueError`` at *module import*; here a warning is logged once
  per knob and the default is used;
* **bounds clamp** — callers state the valid range once, next to the
  default.

Reads are recorded in :data:`KNOWN_READS` (name -> kind) so tooling — the
lint pass and the ``docs/KNOBS.md`` drift checker — can enumerate the live
knob surface of whatever modules are imported.
"""

from __future__ import annotations

import logging
import math
import os
from contextlib import contextmanager

logger = logging.getLogger("lmrs.env")

# knob name -> kind ("str" | "bool" | "int" | "float" | "list"), recorded
# at read time; the analysis drift checker enumerates env reads statically
# (AST), this runtime map is the debugging/introspection view
KNOWN_READS: dict[str, str] = {}

_TRUTHY = frozenset(("1", "true", "yes", "on"))
_FALSY = frozenset(("0", "false", "no", "off"))

_warned: set[str] = set()


def _warn_once(name: str, message: str) -> None:
    if name not in _warned:
        _warned.add(name)
        logger.warning("%s: %s", name, message)


def _raw(name: str, kind: str) -> str | None:
    """The raw value, with unset / empty / whitespace-only folded to None
    (the empty-string-means-default rule)."""
    KNOWN_READS[name] = kind
    raw = os.environ.get(name)
    if raw is None:
        return None
    raw = raw.strip()
    return raw or None


def env_str(name: str, default: str = "", *,
            choices: tuple[str, ...] | None = None) -> str:
    """String knob; values outside ``choices`` (when given, compared
    case-insensitively) warn and fall back to the default."""
    raw = _raw(name, "str")
    if raw is None:
        return default
    if choices is not None and raw.lower() not in choices:
        _warn_once(name, f"unknown value {raw!r} (choices: "
                         f"{', '.join(choices)}); using {default!r}")
        return default
    return raw


def env_bool(name: str, default: bool) -> bool:
    """Boolean knob: 1/true/yes/on vs 0/false/no/off (case-insensitive).
    Anything else warns and keeps the default — a typo'd kill switch must
    be visible, not silently truthy."""
    raw = _raw(name, "bool")
    if raw is None:
        return default
    low = raw.lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    _warn_once(name, f"not a boolean: {raw!r}; using {default}")
    return default


def env_int(name: str, default: int, *, lo: int | None = None,
            hi: int | None = None) -> int:
    raw = _raw(name, "int")
    if raw is None:
        return default
    try:
        val = int(raw, 10)
    except ValueError:
        _warn_once(name, f"not an integer: {raw!r}; using {default}")
        return default
    return _clamp(name, val, lo, hi)


def env_float(name: str, default: float, *, lo: float | None = None,
              hi: float | None = None) -> float:
    """Float knob with the finite guard: NaN and ±inf are rejected (they
    survive min/max clamps and poison downstream arithmetic — the wedged-
    profiler bug class)."""
    raw = _raw(name, "float")
    if raw is None:
        return default
    try:
        val = float(raw)
    except ValueError:
        _warn_once(name, f"not a number: {raw!r}; using {default}")
        return default
    if not math.isfinite(val):
        _warn_once(name, f"non-finite value {raw!r}; using {default}")
        return default
    return _clamp(name, val, lo, hi)


def env_list(name: str, default: tuple[str, ...] = ()) -> tuple[str, ...]:
    """Comma-separated list knob (``LMRS_HOSTS=a:1,b:2``); empty items
    dropped."""
    raw = _raw(name, "list")
    if raw is None:
        return tuple(default)
    return tuple(item.strip() for item in raw.split(",") if item.strip())


@contextmanager
def env_override(name: str, value: str):
    """Scoped ``LMRS_*`` override for harness scripts that build several
    engine arms in one process (gates are read once at construction).
    Lives HERE so the lint's single-env-path rule keeps holding: writes,
    like reads, have exactly one sanctioned site."""
    prev = os.environ.get(name)
    os.environ[name] = value
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


def _clamp(name: str, val, lo, hi):
    if lo is not None and val < lo:
        _warn_once(name, f"value {val} below minimum {lo}; clamping")
        return lo
    if hi is not None and val > hi:
        _warn_once(name, f"value {val} above maximum {hi}; clamping")
        return hi
    return val
