"""Per-stage wall-clock spans + optional jax.profiler traces.

The reference reports manual ``time.time()`` deltas per stage (main.py:110,
239-245; llm_executor.py:129,150-154; result_aggregator.py:72,102-103); this
keeps that user-visible stage report and adds structured spans that can also
emit ``jax.profiler.TraceAnnotation`` ranges when profiling is enabled
(SURVEY.md §5.1).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


def format_duration(seconds: float) -> str:
    """Human duration, reference _format_duration (main.py:324-332)."""
    seconds = int(seconds)
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    if h:
        return f"{h}h {m}m {s}s"
    if m:
        return f"{m}m {s}s"
    return f"{s}s"


@dataclass
class StageTimer:
    """Collects named stage spans; optionally mirrors them into jax.profiler."""

    profile: bool = False
    spans: dict[str, float] = field(default_factory=dict)
    _t0: float = field(default_factory=time.time)

    @contextlib.contextmanager
    def stage(self, name: str):
        ctx = contextlib.nullcontext()
        if self.profile:
            import jax.profiler

            ctx = jax.profiler.TraceAnnotation(name)
        start = time.time()
        with ctx:
            yield
        end = time.time()
        self.spans[name] = self.spans.get(name, 0.0) + (end - start)
        # mirror the stage into the lifecycle tracer's pipeline track (the
        # engine-level spans nest under these in Perfetto)
        from lmrs_tpu.obs import PID_PIPELINE, get_tracer

        tr = get_tracer()
        if tr:
            tr.complete(name, start, end, pid=PID_PIPELINE)

    @property
    def total(self) -> float:
        return time.time() - self._t0

    def report(self) -> dict[str, float]:
        out = {k: round(v, 4) for k, v in self.spans.items()}
        out["total"] = round(self.total, 4)
        return out
