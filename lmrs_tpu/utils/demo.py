"""Shared input loader for the per-module ``__main__`` demos.

The reference makes every pipeline module self-demoing against
``transcript-example.json`` (preprocessor.py:364, big_chunkeroosky.py:570,
llm_executor.py:460, result_aggregator.py:527) — the de-facto smoke tests.
This helper feeds the same pattern here: the real example transcript when the
reference checkout is present, otherwise a deterministic synthetic one.
"""

from __future__ import annotations

import json
import random
from pathlib import Path

_CANDIDATES = (
    Path("/root/reference/transcript-example.json"),
    Path(__file__).resolve().parents[2] / "tests" / "data" / "transcript-example.json",
)


def load_demo_transcript(max_segments: int | None = None) -> dict:
    """``{"segments": [...]}`` — example fixture if present, else synthetic."""
    for p in _CANDIDATES:
        if p.exists():
            data = json.loads(p.read_text())
            break
    else:
        data = {"segments": _synthesize()}
    if max_segments is not None:
        data = {**data, "segments": data["segments"][:max_segments]}
    return data


def _synthesize(n: int = 600) -> list[dict]:
    rng = random.Random(0)
    words = (
        "the roadmap review covers inference latency kernel design hiring "
        "budget datasets evaluation and the quarterly launch milestones"
    ).split()
    segs, t = [], 0.0
    for i in range(n):
        dur = 2.0 + rng.random() * 6.0
        text = " ".join(rng.choice(words) for _ in range(10 + rng.randrange(15)))
        segs.append({"start": round(t, 2), "end": round(t + dur, 2),
                     "text": text.capitalize() + ".",
                     "speaker": f"SPEAKER_{(i // 7) % 2:02d}"})
        t += dur + rng.random()
    return segs
