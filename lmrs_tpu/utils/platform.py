"""Accelerator platform probe shared by the kernel-routing gates
(scheduler._pick_kernel, transformer._use_flash_prefill)."""

from __future__ import annotations

import os

import jax


def honor_platform_env() -> None:
    """Some hosts' sitecustomize force-registers an accelerator backend
    (jax.config.update("jax_platforms", ...)), silently overriding the
    standard JAX_PLATFORMS env var; re-apply any explicit request (a wedged
    accelerator tunnel otherwise hangs even pure-CPU runs).  Call before
    the first backend use.  The ONE shared copy of this workaround —
    CLIs and bench.py all route here."""
    value = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if value:
        jax.config.update("jax_platforms", value)


def on_tpu() -> bool:
    """True when the default JAX backend is a TPU-family device (anything
    that is not the cpu/gpu XLA backends — covers tpu and tunneled variants)."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    return platform not in ("cpu", "gpu")
