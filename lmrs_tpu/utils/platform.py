"""Accelerator platform probe shared by the kernel-routing gates
(scheduler._pick_kernel, transformer._use_flash_prefill)."""

from __future__ import annotations

import os

import jax


def honor_platform_env() -> None:
    """Some hosts' sitecustomize force-registers an accelerator backend
    (jax.config.update("jax_platforms", ...)), silently overriding the
    standard JAX_PLATFORMS env var; re-apply an explicit cpu request.
    Call before the first backend use."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")


def on_tpu() -> bool:
    """True when the default JAX backend is a TPU-family device (anything
    that is not the cpu/gpu XLA backends — covers tpu and tunneled variants)."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    return platform not in ("cpu", "gpu")
