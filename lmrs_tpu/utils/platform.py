"""Accelerator platform probe shared by the kernel-routing gates
(scheduler._pick_kernel, transformer._use_flash_prefill)."""

from __future__ import annotations

import jax


def on_tpu() -> bool:
    """True when the default JAX backend is a TPU-family device (anything
    that is not the cpu/gpu XLA backends — covers tpu and tunneled variants)."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    return platform not in ("cpu", "gpu")
