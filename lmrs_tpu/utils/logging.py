"""Logging setup: one root config instead of the reference's per-module
copy-pasted ``basicConfig`` blocks (main.py:32-40, llm_executor.py:22-26, …).
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


def setup_logging(quiet: bool = False, level: int | None = None,
                  stream=None) -> None:
    """Configure the ``lmrs`` logger tree.  quiet → WARNING (main.py
    --quiet).  ``stream`` defaults to stdout (the reference logs to
    stdout, main.py:32-40); artifact-emitting callers whose stdout is a
    machine-read contract (bench.py's one-JSON-line) pass stderr."""
    root = logging.getLogger("lmrs")
    if not root.handlers:
        handler = logging.StreamHandler(stream if stream is not None
                                        else sys.stdout)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(handler)
    root.setLevel(level if level is not None else (logging.WARNING if quiet else logging.INFO))
