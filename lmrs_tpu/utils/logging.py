"""Logging setup: one root config instead of the reference's per-module
copy-pasted ``basicConfig`` blocks (main.py:32-40, llm_executor.py:22-26, …).

Repeated ``setup_logging`` calls are honored: the managed handler's level,
stream, and format are UPDATED in place (the original first-call-wins
behavior silently ignored a later ``--quiet`` or a bench redirecting logs
to stderr after a library import had already configured stdout).  Handlers
installed by embedding applications are left untouched.

``LMRS_LOG_JSON=1`` switches the managed handler to one-JSON-object-per-
line output (ts/level/logger/msg) for log scraping; the env var is re-read
on every ``setup_logging`` call so tests and long-lived processes can
toggle it.
"""

from __future__ import annotations

import json
import logging
import sys

from lmrs_tpu.utils.env import env_bool

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


class JsonFormatter(logging.Formatter):
    """One JSON object per line — machine-scrapable structured logs."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, ensure_ascii=False)


def _managed_handler(root: logging.Logger) -> logging.StreamHandler | None:
    for h in root.handlers:
        if getattr(h, "_lmrs_managed", False):
            return h
    return None


def setup_logging(quiet: bool = False, level: int | None = None,
                  stream=None) -> None:
    """Configure the ``lmrs`` logger tree.  quiet → WARNING (main.py
    --quiet).  ``stream`` defaults to stdout (the reference logs to
    stdout, main.py:32-40); artifact-emitting callers whose stdout is a
    machine-read contract (bench.py's one-JSON-line) pass stderr.
    Safe to call repeatedly — later calls update level/stream/format."""
    root = logging.getLogger("lmrs")
    formatter: logging.Formatter = (
        JsonFormatter() if env_bool("LMRS_LOG_JSON", False)
        else logging.Formatter(_FORMAT))
    handler = _managed_handler(root)
    if handler is None:
        # legacy compat: a pre-existing FOREIGN handler (an embedding app's)
        # is respected — we only manage handlers we created
        if not root.handlers:
            handler = logging.StreamHandler(stream if stream is not None
                                            else sys.stdout)
            handler._lmrs_managed = True
            root.addHandler(handler)
    elif stream is not None:
        handler.setStream(stream)
    if handler is not None:
        handler.setFormatter(formatter)
    root.setLevel(level if level is not None
                  else (logging.WARNING if quiet else logging.INFO))
