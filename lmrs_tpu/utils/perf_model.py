"""Roofline accounting: FLOP / byte counts + chip peaks for MFU and
HBM-bandwidth utilization reporting (bench.py, docs/PERF.md).

The reference publishes no perf model at all (its compute is a vendor API);
these counts are the standard decoder-transformer roofline: dense-matmul
FLOPs dominate prefill (MFU vs the MXU peak), weight+KV bytes dominate
decode (utilization vs the HBM peak).
"""

from __future__ import annotations

from dataclasses import dataclass

from lmrs_tpu.config import ModelConfig

# Public peak numbers per chip generation (bf16 TFLOP/s, HBM GB/s).
# device_kind strings as reported by jax.devices()[0].device_kind.
_CHIP_PEAKS = {
    "v5 lite": (197e12, 819e9),   # v5e
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v5": (459e12, 2765e9),       # bare "TPU v5" -> assume v5p
    "v4": (275e12, 1228e9),
    "v6 lite": (918e12, 1640e9),  # Trillium
    "v6e": (918e12, 1640e9),
}


def pow2_bucket(n: int, lo: int) -> int:
    """Smallest power of two >= max(n, lo) — THE compile-key bucketing of
    the ragged-span family (query-token buckets, page windows).  One
    shared definition: the scheduler (via ops/paged_attention), the mock
    engine, and the bucket-economics accounting (obs/anatomy.py) must
    agree on bucket edges or the per-bucket padding-waste numbers
    attribute to the wrong key.  Lives here (jax-free) so the mock's
    import closure stays deviceless."""
    b = lo
    while b < n:
        b *= 2
    return b


@dataclass
class ChipSpec:
    kind: str
    peak_flops: float  # bf16 FLOP/s
    peak_hbm_bw: float  # bytes/s
    known: bool


def chip_spec() -> ChipSpec:
    """Peak specs of the default device (v5e fallback when unrecognized)."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    low = kind.lower()
    for key, (fl, bw) in _CHIP_PEAKS.items():
        if key in low:
            return ChipSpec(kind, fl, bw, True)
    return ChipSpec(kind, 197e12, 819e9, False)


def matmul_params(cfg: ModelConfig) -> int:
    """Parameters that participate in per-token matmuls (embedding lookup
    excluded; the LM head included — tied or not, it is a [D, V] matmul)."""
    d, hd = cfg.dim, cfg.hd
    per_layer = (
        d * cfg.n_heads * hd          # wq
        + 2 * d * cfg.n_kv_heads * hd  # wk, wv
        + cfg.n_heads * hd * d        # wo
    )
    if cfg.n_experts:
        # only the activated experts' FFN weights do per-token work
        per_layer += 3 * d * cfg.hidden_dim * cfg.n_experts_per_token
    else:
        per_layer += 3 * d * cfg.hidden_dim
    return cfg.n_layers * per_layer + d * cfg.vocab_size


def prefill_flops(cfg: ModelConfig, n_tokens: int,
                  head_tokens: int | None = None,
                  kv_start: int = 0) -> float:
    """Forward FLOPs for a causal prefill of ``n_tokens``.

    Dense matmuls: 2 FLOPs per param per token.  Causal attention:
    2 * S^2 * hd * H per layer (QK^T + PV, averaged S/2 keys per query,
    2 FLOPs per MAC).  ``head_tokens`` restricts the LM-head matmul to the
    sampled rows (the packed-prefill gather, forward_paged).  ``kv_start``
    models a WINDOWED continuation chunk (chunked prefill): the chunk's
    tokens additionally attend ``kv_start`` earlier cached KV tokens —
    kv_start=0 reduces exactly to the fresh causal count."""
    d = cfg.dim
    body = matmul_params(cfg) - d * cfg.vocab_size
    fl = 2.0 * body * n_tokens
    fl += 2.0 * (head_tokens if head_tokens is not None else n_tokens) \
        * d * cfg.vocab_size
    fl += 2.0 * cfg.n_layers * (float(n_tokens) ** 2
                                + 2.0 * kv_start * n_tokens) \
        * cfg.hd * cfg.n_heads
    return fl


def weight_bytes(cfg: ModelConfig, quantized: bool = False) -> float:
    """Bytes of MATMUL weights a decode step streams from HBM (all of
    them, once — one read serves the whole batch).  The embedding lookup
    gathers only B rows per step and is excluded (negligible; counting
    the full table would overstate untied models' bandwidth)."""
    import jax.numpy as jnp

    itemsize = 1 if quantized else jnp.dtype(cfg.dtype).itemsize
    return matmul_params(cfg) * itemsize


def kv_bytes_per_token(cfg: ModelConfig) -> float:
    """KV-cache bytes per cached token (K + V, all layers, all kv heads)."""
    import jax.numpy as jnp

    return (2 * cfg.n_layers * cfg.n_kv_heads * cfg.hd
            * jnp.dtype(cfg.dtype).itemsize)


def decode_step_bytes(cfg: ModelConfig, total_live_tokens: int,
                      quantized: bool = False,
                      kv_quantized: bool = False) -> float:
    """HBM bytes one batched decode step moves: every weight once (batch
    amortized — one read serves all rows) + every live KV token's K and V
    (halved when the pages are int8)."""
    kv = kv_bytes_per_token(cfg) * total_live_tokens
    if kv_quantized:
        kv /= 2
    return weight_bytes(cfg, quantized) + kv


def time_chain(make_chain, lo: int, hi: int, reps: int = 3) -> float:
    """Per-iteration wall time of a chained on-device computation, by the
    LONG-minus-SHORT difference.  ``make_chain(iters)`` must return a
    zero-arg callable that runs ``iters`` chained steps in ONE dispatch
    (e.g. a jitted ``fori_loop`` whose carry threads the output) and
    returns a device value to fetch.  Timing the difference between the
    hi- and lo-length chains and dividing by the iteration delta cancels
    the dispatch cost and the tunnel's fetch RTT exactly — naive per-call
    timing on tunneled chips is ~97% RTT and produced garbage fits,
    including negative slopes (docs/PERF.md round 5).  Each chain length
    compiles + settles once, then takes best-of-``reps``.

    THE one implementation of the chained-probe method: the standalone
    probes (scripts/decode_rowcost.py) and the in-engine attribution
    (scheduler.rowcost_microbench) both call it, so the methodology —
    warmup discipline, best-of timing, the slope arithmetic — cannot
    drift between them and their us/row numbers stay comparable."""
    import time

    import jax
    import numpy as np

    walls = {}
    for iters in (lo, hi):
        fn = make_chain(iters)
        np.asarray(jax.device_get(fn()))  # compile + settle
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            np.asarray(jax.device_get(fn()))
            best = min(best, time.time() - t0)
        walls[iters] = best
    return (walls[hi] - walls[lo]) / (hi - lo)
