"""Shared utilities: logging, stage timing, metrics, profiling hooks."""

from lmrs_tpu.utils.timing import StageTimer, format_duration
from lmrs_tpu.utils.logging import setup_logging

__all__ = ["StageTimer", "format_duration", "setup_logging"]
