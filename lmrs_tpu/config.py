"""Typed configuration tree for the whole framework.

Replaces the reference's three ad-hoc config layers (env via ``LLMConfig``
at llm_executor.py:31-52, argparse flags at main.py:412-472, ctor kwargs on
every component) with one dataclass tree and the same precedence:
explicit kwargs > CLI flags > environment > defaults  (SURVEY.md §5.6).

Reference-compatible environment variables (MAX_CONCURRENT_REQUESTS,
TEMPERATURE, MAX_TOKENS, REQUEST_TIMEOUT, RETRY_ATTEMPTS, RETRY_DELAY,
DEFAULT_PROVIDER; .env.template:1-22) are honored so a reference user's
``.env`` keeps working.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


def _env(name: str, default: Any, cast: type = str) -> Any:
    """Config-field env override, routed through the shared validated
    parser (utils/env.py): empty string means default, non-finite numbers
    are rejected, bad values warn once and keep the default."""
    from lmrs_tpu.utils import env as _envmod

    if cast is bool:
        return _envmod.env_bool(name, bool(default))
    if cast is int:
        return _envmod.env_int(name, default)
    if cast is float:
        return _envmod.env_float(name, default)
    raw = _envmod.env_str(name, "" if default is None else str(default))
    return raw if default is not None or raw else default


@dataclass
class DataConfig:
    """Preprocessing stage knobs (reference: preprocessor.py:15-67)."""

    merge_same_speaker: bool = True
    time_interval_seconds: float | None = None
    max_segment_duration: float = 120.0
    preserve_timestamps: bool = True
    limit_segments: int | None = None  # reference --limit-segments (main.py:450-452)


@dataclass
class ChunkConfig:
    """Chunker knobs (reference: big_chunkeroosky.py:23-44).

    Unlike the reference, ``overlap_tokens`` is actually implemented
    (reference accepts-but-ignores it; SURVEY.md §2.3 quirk 1).
    ``tokenizer`` names the token-count authority — in the TPU build this is
    the *serving model's* tokenizer, not cl100k_base (SURVEY.md §7.4 item 4).
    """

    max_tokens_per_chunk: int = 4000
    overlap_tokens: int = 200
    context_tokens: int = 150
    tokenizer: str = "approx"  # "approx" | "byte" | HF repo id / sp model path

    @property
    def effective_max_tokens(self) -> int:
        return self.max_tokens_per_chunk - self.context_tokens


@dataclass
class ModelConfig:
    """Decoder-only transformer hyperparameters (lmrs_tpu.models)."""

    name: str = "tiny"
    vocab_size: int = 512
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    hidden_dim: int = 688
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = True
    dtype: str = "bfloat16"
    # Gemma-style differences
    logit_softcap: float | None = None
    embed_scale: bool = False  # Gemma multiplies embeddings by sqrt(dim)
    head_dim: int | None = None  # explicit per-head dim (Gemma-7B: 256 != dim/heads)
    activation: str = "silu"  # FFN gate activation: "silu" (Llama) | "gelu" (Gemma)
    # Mixture-of-experts (0 experts = dense FFN; ops/moe.py)
    n_experts: int = 0
    n_experts_per_token: int = 2
    expert_capacity_factor: float = 1.25
    router_aux_coef: float = 0.01  # load-balance loss weight in training

    @property
    def hd(self) -> int:
        """Per-head dimension; ``head_dim`` overrides the dim/n_heads default."""
        return self.head_dim or self.dim // self.n_heads


@dataclass
class MeshConfig:
    """Device mesh axes: data, tensor (ICI), sequence/context, pipeline.

    The reference has no device parallelism at all (SURVEY.md §2.2); these
    axes are the TPU-native replacement for its asyncio request fan-out.
    """

    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1  # expert parallel (MoE expert axis; ops/moe.py)
    pp: int = 1
    axis_names: tuple[str, ...] = ("dp", "tp", "sp", "ep", "pp")

    @property
    def n_devices(self) -> int:
        return self.dp * self.tp * self.sp * self.ep * self.pp


@dataclass
class EngineConfig:
    """Generation engine knobs.

    Mirrors the reference's ``LLMConfig`` env surface (llm_executor.py:31-52)
    but the "provider" is an in-tree backend, not an HTTP vendor:
    ``backend`` ∈ {"mock", "jax", "http"} — "http" fans over remote
    lmrs-serve hosts (multi-host serving, serving/router.py).
    ``max_concurrent_requests`` maps to the continuous-batching decode slot
    count (admission control; SURVEY.md §2.2).
    """

    backend: str = field(default_factory=lambda: _env("LMRS_BACKEND", _env("DEFAULT_PROVIDER", "mock")))
    model: str = field(default_factory=lambda: _env("LMRS_MODEL", "tiny"))
    # backend="http": remote lmrs-serve hosts the RouterEngine fans over
    # (multi-host DP serving, serving/router.py); comma-separated in env
    hosts: tuple[str, ...] = field(
        default_factory=lambda: tuple(
            h.strip() for h in _env("LMRS_HOSTS", "").split(",") if h.strip()))
    # Disaggregated serving pools (serving/router.py + docs/SERVING.md):
    # prefill-role and decode-role lmrs-serve hosts.  When BOTH are
    # non-empty the router runs the two-tier handoff (admission to the
    # prefill pool, KV-page ticket to the decode pool); either pool empty
    # or fully degraded falls back to colocated operation over
    # ``hosts``/the surviving pool.  Comma-separated in env.
    prefill_hosts: tuple[str, ...] = field(
        default_factory=lambda: tuple(
            h.strip() for h in _env("LMRS_PREFILL_HOSTS", "").split(",")
            if h.strip()))
    decode_hosts: tuple[str, ...] = field(
        default_factory=lambda: tuple(
            h.strip() for h in _env("LMRS_DECODE_HOSTS", "").split(",")
            if h.strip()))
    temperature: float = field(default_factory=lambda: _env("TEMPERATURE", 0.3, float))
    max_tokens: int = field(default_factory=lambda: _env("MAX_TOKENS", 1000, int))
    max_concurrent_requests: int = field(
        default_factory=lambda: _env("MAX_CONCURRENT_REQUESTS", 5, int)
    )
    request_timeout: float = field(default_factory=lambda: _env("REQUEST_TIMEOUT", 60.0, float))
    retry_attempts: int = field(default_factory=lambda: _env("RETRY_ATTEMPTS", 3, int))
    retry_delay: float = field(default_factory=lambda: _env("RETRY_DELAY", 5.0, float))
    seed: int = 0
    # serving-side knobs (no reference counterpart — SURVEY.md §7.4 item 1)
    scheduler: str = "continuous"  # "continuous" (slot-based) | "static" (lockstep waves)
    max_batch_slots: int = 8
    page_size: int = 128
    num_pages: int = 512
    # Prompt tokens prefilled per scheduler turn.  Default = one-dispatch
    # prefill: chunking (e.g. 512) was ABBA-measured a throughput LOSS and,
    # per the decode-latency histogram (docs/PERF.md round 2), DOUBLES p50
    # decode latency for active slots (272-302 vs 140-144 ms/block) while
    # only trimming p90 (330 vs 443-485 ms).  Set a small value only when
    # worst-case tail fairness under very long prompts outweighs both.
    prefill_chunk: int = 4096
    decode_block: int = 16  # decode steps per host sync (see scheduler)
    # Multi-row decode page walk (ops/paged_attention.py): each ragged
    # decode program walks `decode_row_group` batch rows' live pages
    # through one shared double-buffered DMA pipeline, amortizing the
    # per-program fixed cost that one-row-per-program dispatch pays per
    # row (~2.8 ms of the 8B decode step; docs/PERF.md r5 intercept
    # decomposition).  The scheduler length-balances the row→group
    # assignment per dispatch and clamps to the slot count.
    # LMRS_MULTIROW=0 is the kill switch (per-row grid, exact previous
    # behavior — same A/B convention as LMRS_PACK_PREFILL);
    # LMRS_DECODE_ROW_GROUP overrides the group size.
    decode_row_group: int = field(
        default_factory=lambda: _env("LMRS_DECODE_ROW_GROUP", 4, int))
    # SARATHI-style mixed batches (PAPERS.md): while a prompt is mid-
    # prefill, each scheduler step dispatches ONE fused batch carrying all
    # live decode rows (one token each) plus a chunked-prefill slice
    # clipped to `mixed_token_budget - decode_tokens`, through the ragged
    # multi-token row-group path — decode cadence never pauses for an
    # admission and prefill rides the decode step's spare FLOPs (the
    # block-gap / TTFT coupling ROADMAP item 1 measured).  LMRS_MIXED=0 is
    # the kill switch (exact alternating prefill/decode dispatch — same
    # A/B convention as LMRS_PACK_PREFILL / LMRS_MULTIROW).  Auto-disabled
    # with kv_quantize (a mixed chunk cannot own its slot's frozen
    # prefill scales) and under sp>1 meshes (ring prefill replaces
    # chunking there, so there is no slice to piggyback).
    mixed_batch: bool = True
    # Token budget of one mixed step: live decode tokens first, the
    # remainder is the prefill slice (clipped; a budget the decode rows
    # already exhaust falls back to alternating dispatch for that step).
    mixed_token_budget: int = field(
        default_factory=lambda: _env("LMRS_MIXED_TOKEN_BUDGET", 256, int))
    # prompt-lookup speculative decoding: draft length per step (0 = off).
    # Exact-distribution verify (ops/speculative.py) — output quality is
    # unchanged; latency drops when summaries quote their source.
    speculate_k: int = 0
    # n-gram length for prompt-lookup drafting (ops/speculative.draft_lookup):
    # 3 collides far less than 2 on byte-level vocabularies (measured r4)
    speculate_ngram: int = 3
    checkpoint_path: str | None = None
    quantize: str | None = None  # None | "int8" (weight-only; ops/quant.py)
    # int8 KV-cache pages (ops/quant.py KV section): halves decode's KV
    # bytes and doubles tokens per HBM GiB; per-slot/head/channel scales
    # fixed at prefill.  Gates packed + ring prefill off (per-slot scales
    # can't cover a packed row's many prompts / sp-sharded writes).
    kv_quantize: str | None = None  # None | "int8"
    # Shared-prefix KV cache (engine/prefix_cache.py): completed prompts
    # donate their full-page KV prefix to a radix tree; a new request whose
    # prompt shares that prefix clones the pages (ref-counted, read-only)
    # and starts prefill at the first uncached token.  Default ON — the
    # map/reduce stages repeat the same preamble per chunk; LMRS_PREFIX_CACHE=0
    # or prefix_cache=False is the kill switch.  Auto-disabled with
    # kv_quantize (per-slot scales cannot cover donor-quantized pages) and
    # under sp>1 meshes (cache hits enter the windowed-continuation prefill,
    # which does not ride the ring).
    prefix_cache: bool = True
    # cap on pages the prefix cache retains (0 = no explicit cap: retained
    # pages stay bounded by the pool, drained on demand by the OutOfPages
    # back-pressure eviction)
    prefix_cache_max_pages: int = 0
    # Host-RAM KV spill tier (engine/host_kv.py, ROADMAP item 3): evicted
    # refcount-zero prefix-cache pages capture their content into a
    # bounded host-memory pool and prefetch back on a later radix match
    # instead of re-prefilling — the fleet's HBM + host RAM become one
    # cache hierarchy.  LMRS_HOST_KV=0 (or host_kv=False) is the kill
    # switch: eviction means gone, byte-for-byte today's behavior.  Only
    # meaningful with prefix_cache on (and therefore never with int8 KV,
    # which disables the prefix cache).
    host_kv: bool = field(
        default_factory=lambda: _env("LMRS_HOST_KV", True, bool))
    # host pool budget in GiB (LRU over spilled subtrees past it); an
    # entry bigger than the whole budget skips the spill entirely
    host_kv_gb: float = field(
        default_factory=lambda: _env("LMRS_HOST_KV_GB", 1.0, float))
    # Disk spill tier (engine/host_kv.DiskKVPool, ROADMAP item 4): host
    # pool budget pressure demotes LRU entries to mmap'd spill files
    # instead of dropping them; promotion reads disk→host→device on the
    # prefetch path.  OPT-IN (writing GBs of KV to disk is a deployment
    # decision); LMRS_KV_DISK=0 restores host-pressure-means-gone
    # byte-for-byte.  Only meaningful with the host tier armed.
    kv_disk: bool = field(
        default_factory=lambda: _env("LMRS_KV_DISK", False, bool))
    # disk pool budget in GiB (LRU subtree drops past it)
    kv_disk_gb: float = field(
        default_factory=lambda: _env("LMRS_KV_DISK_GB", 4.0, float))
    # spill-file root directory ("" = system temp); each pool makes its
    # own fresh subdirectory, so engines sharing the root never collide
    kv_disk_dir: str = field(
        default_factory=lambda: _env("LMRS_KV_DISK_DIR", ""))
    # engine-side tokenizer spec ("" = model default: byte for random-init
    # vocabs, the checkpoint's tokenizer for real ones).  Accepts the same
    # forms as data.tokenizer.get_tokenizer: "byte", a *.model SentencePiece
    # path, or an HF tokenizer directory/repo id (local_files_only).
    tokenizer: str = ""
    # Fault-injection plane (lmrs_tpu/testing/faults.py): a JSON FaultPlan
    # (or "@/path/to/plan.json") installed process-globally by make_engine.
    # Empty = disabled — every injection site is a module-level no-op and
    # the hot path pays nothing (the tier-1 A/B gate asserts the greedy
    # output is token-identical with the plane disarmed).
    fault_plan: str = field(
        default_factory=lambda: _env("LMRS_FAULT_PLAN", ""))
    # Deadline budget (seconds) the MAP EXECUTOR stamps onto every request
    # it runs that doesn't already carry one (0 = no deadline).  A
    # deadline-carrying request is shed at admission when the remaining
    # budget can't cover the TTFT estimate (finish_reason="shed") and
    # expired in flight at the next block boundary ("deadline"); executor
    # and router retries clip to the remaining budget.
    request_deadline_s: float = field(
        default_factory=lambda: _env("LMRS_REQUEST_DEADLINE", 0.0, float))
    # Disaggregated handoff pin TTL (seconds): pages exported for a
    # prefill→decode handoff stay pinned (ref-counted) until the decode
    # side acks the import; a ticket never acked is orphan-swept after
    # this long and its pages reclaimed (the crash-safety backstop for a
    # dead decode pod or a lost ack — docs/SERVING.md ticket lifecycle).
    # A request deadline tighter than the TTL clips it.
    handoff_ttl_s: float = field(
        default_factory=lambda: _env("LMRS_HANDOFF_TTL", 60.0, float))

    def __post_init__(self) -> None:
        # Reference DEFAULT_PROVIDER values name HTTP vendors; both map to
        # the local engine choice "mock" when no backend is explicitly set.
        if self.backend in ("openai", "anthropic"):
            self.backend = "mock"
        if self.quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {self.quantize!r}; "
                             "supported: int8")
        if self.kv_quantize not in (None, "int8"):
            raise ValueError(f"unknown kv_quantize mode {self.kv_quantize!r}; "
                             "supported: int8")
        if self.decode_row_group < 1:
            raise ValueError(f"decode_row_group must be >= 1 "
                             f"(got {self.decode_row_group}); use "
                             "LMRS_MULTIROW=0 to disable row grouping")
        if self.mixed_token_budget < 32:
            raise ValueError(f"mixed_token_budget must be >= 32 "
                             f"(got {self.mixed_token_budget}); use "
                             "mixed_batch=False / LMRS_MIXED=0 to disable "
                             "mixed dispatch")
        if self.host_kv_gb < 0:
            raise ValueError(f"host_kv_gb must be >= 0 "
                             f"(got {self.host_kv_gb}); use host_kv=False / "
                             "LMRS_HOST_KV=0 to disable the spill tier")
        if self.kv_disk_gb < 0:
            raise ValueError(f"kv_disk_gb must be >= 0 "
                             f"(got {self.kv_disk_gb}); use kv_disk=False / "
                             "LMRS_KV_DISK=0 to disable the disk tier")
        if self.request_deadline_s < 0:
            raise ValueError(f"request_deadline_s must be >= 0 "
                             f"(got {self.request_deadline_s}); 0 disables "
                             "deadlines")
        if self.handoff_ttl_s <= 0:
            raise ValueError(f"handoff_ttl_s must be > 0 "
                             f"(got {self.handoff_ttl_s}): un-acked "
                             "handoff pins need a finite orphan-sweep "
                             "deadline or a dead decode pod leaks pages")


@dataclass
class ReduceConfig:
    """Reduce-tree knobs (reference: result_aggregator.py:32-53,357-380).

    The reference tree is capped at exactly two levels (quirk 11); here
    ``max_levels`` allows true recursion until the batch fits.
    """

    max_tokens_per_batch: int = 6000
    hierarchical: bool = True
    reserve_tokens: int = 1000
    max_summaries_per_batch: int = 10
    # stream reduce batches into the map stage's engine stream as their
    # member summaries complete (reduce/streaming.py) instead of the
    # reference's hard map→reduce barrier (main.py:169-236).  Default OFF:
    # measured a ~2% LOSS on the bench workload (in-process ABBA,
    # docs/PERF.md) — with short decodes the reduce share is too small to
    # hide and the mixed-shape admissions cost more than the overlap wins.
    # Worth enabling for long-decode workloads (max_tokens ~1000) or deep
    # reduce trees, where the tail is a real fraction of the run.
    streaming: bool = False
    max_levels: int = 4
    temperature: float = 0.2  # reference hardcodes 0.2 (result_aggregator.py:238)
    # Stable reduce-tree shape for APPEND-ONLY workloads (lmrs_tpu/live/):
    # fixed-arity (`max_summaries_per_batch`) leaf-aligned batching with
    # position-free batch metadata, so appending leaves changes only the
    # last (partial) batch per level and the root path — every sibling
    # subtree keeps a byte-identical prompt and answers from the node
    # cache.  The default token-budget shape re-batches the WHOLE level
    # when sizes drift and bakes "batch i/n" positions into each prompt,
    # which poisons every cached node on any append.  Off by default: the
    # batch pipeline keeps its historical tree.
    stable_tree: bool = False


@dataclass
class JobsConfig:
    """Durable-job knobs (lmrs_tpu/jobs/: write-ahead journal + async job
    API — docs/ROBUSTNESS.md job-durability section).

    ``jobs_dir`` empty = the job API is disabled (lmrs-serve answers 501;
    batch pipeline runs are unaffected).  ``max_failed_chunk_fraction``
    is the degraded-completion policy: a job whose failed-chunk fraction
    stays at or under it finishes ``status="degraded"`` with the
    per-chunk ``degraded_reason``s attached instead of all-or-nothing
    failure; above it the job is ``status="failed"`` (the summary —
    degrade-and-continue output — is still attached either way).
    """

    jobs_dir: str = field(default_factory=lambda: _env("LMRS_JOBS_DIR", ""))
    max_failed_chunk_fraction: float = field(
        default_factory=lambda: _env("LMRS_JOBS_DEGRADED_FRACTION", 0.2,
                                     float))

    def __post_init__(self) -> None:
        if not 0.0 <= self.max_failed_chunk_fraction <= 1.0:
            raise ValueError(
                f"max_failed_chunk_fraction must be in [0, 1] "
                f"(got {self.max_failed_chunk_fraction}); 0 = any failed "
                "chunk fails the job, 1 = always finish degraded")


@dataclass
class LiveConfig:
    """Live-session knobs (lmrs_tpu/live/: incremental summarization of
    growing transcripts — docs/SERVING.md § Live sessions).

    ``sessions_dir`` empty = the session API is disabled (lmrs-serve
    answers 501; batch pipeline and jobs are unaffected).
    ``refresh_tokens`` > 0 auto-triggers a refresh when a session has
    accumulated that many appended-but-unsummarized tokens (0 = refresh
    only on request).  ``class_default`` is the deadline class a refresh
    runs under when the request names none: ``interactive`` refreshes
    carry a per-request deadline (``interactive_deadline_s``) and ride
    PR 5's shed/expiry lifecycle ahead of ``bulk`` backfill, which runs
    unbounded.
    """

    sessions_dir: str = field(default_factory=lambda: _env("LMRS_LIVE_DIR", ""))
    refresh_tokens: int = field(
        default_factory=lambda: _env("LMRS_LIVE_REFRESH_TOKENS", 0, int))
    class_default: str = field(
        default_factory=lambda: _env("LMRS_LIVE_CLASS_DEFAULT", "interactive"))
    interactive_deadline_s: float = 120.0

    def __post_init__(self) -> None:
        if self.class_default not in ("interactive", "bulk"):
            raise ValueError(
                f"unknown live deadline class {self.class_default!r}; "
                "want interactive|bulk")
        if self.refresh_tokens < 0:
            raise ValueError(
                f"refresh_tokens must be >= 0 (got {self.refresh_tokens}); "
                "0 disables auto-refresh")
        if self.interactive_deadline_s <= 0:
            raise ValueError(
                f"interactive_deadline_s must be > 0 "
                f"(got {self.interactive_deadline_s}); use class 'bulk' "
                "for unbounded refreshes")


@dataclass
class PipelineConfig:
    """Top-level config: one object wires the whole pipeline."""

    data: DataConfig = field(default_factory=DataConfig)
    chunk: ChunkConfig = field(default_factory=ChunkConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    reduce: ReduceConfig = field(default_factory=ReduceConfig)
    jobs: JobsConfig = field(default_factory=JobsConfig)
    live: LiveConfig = field(default_factory=LiveConfig)

    def replace(self, **kw: Any) -> "PipelineConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_mesh(spec: str) -> "MeshConfig":
    """``"dp,tp[,sp[,pp]]"`` → MeshConfig (shared by the lmrs/lmrs-train
    CLIs so the axis order can't drift between them)."""
    dims = [int(x) for x in spec.split(",")]
    if not 1 <= len(dims) <= 4:
        raise ValueError(f"mesh spec {spec!r}: expected 1-4 axes dp,tp[,sp[,pp]]")
    dims += [1] * (4 - len(dims))
    return MeshConfig(dp=dims[0], tp=dims[1], sp=dims[2], pp=dims[3])


def model_preset(name: str) -> ModelConfig:
    """Named model configurations (L3 model zoo presets)."""
    presets: dict[str, dict] = {
        "tiny": {},
        "tiny-gemma": dict(
            logit_softcap=30.0, embed_scale=True, rope_theta=10000.0,
            tie_embeddings=True, activation="gelu", norm_eps=1e-6,
        ),
        "llama3-8b": dict(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            hidden_dim=14336, max_seq_len=8192, rope_theta=500000.0,
            tie_embeddings=False,
        ),
        "llama3-70b": dict(
            vocab_size=128256, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
            hidden_dim=28672, max_seq_len=8192, rope_theta=500000.0,
            tie_embeddings=False,
        ),
        "gemma-2b": dict(
            vocab_size=256128, dim=2048, n_layers=18, n_heads=8, n_kv_heads=1,
            hidden_dim=16384, max_seq_len=8192, rope_theta=10000.0,
            tie_embeddings=True, embed_scale=True, head_dim=256,
            activation="gelu", norm_eps=1e-6,
        ),
        "gemma-7b": dict(
            vocab_size=256128, dim=3072, n_layers=28, n_heads=16, n_kv_heads=16,
            hidden_dim=24576, max_seq_len=8192, rope_theta=10000.0,
            tie_embeddings=True, embed_scale=True, head_dim=256,  # != dim/heads
            activation="gelu", norm_eps=1e-6,
        ),
        "bench-1b": dict(
            # ~1.03B params, Llama-3 proportions at 1B scale (GQA 16q/8kv,
            # head_dim 128 engages the ragged decode kernel), byte vocab so
            # the bench needs no downloaded tokenizer.  The scale exists so
            # bench.py measures the MXU/HBM, not the host link (a 45M model
            # under-utilizes the chip ~20x; VERDICT r1).
            vocab_size=512, dim=2048, n_layers=18, n_heads=16, n_kv_heads=8,
            hidden_dim=7168, max_seq_len=2048, rope_theta=500000.0,
            tie_embeddings=True,
        ),
        "tiny-moe": dict(
            hidden_dim=512, n_experts=4, n_experts_per_token=2,
        ),
        "bench-8b": dict(
            # The BASELINE north-star model shape (Llama-3-8B: BASELINE.md
            # headline row), full vocabulary included so the LM head
            # streams its real 525 MB share of the decode bytes.  Window
            # 2048 = the bench's measured-optimal serving window (the 8192
            # training window is irrelevant to chunked map serving —
            # docs/PERF.md round 4 rejected 4096).  Run with int8 weights
            # + int8 KV: ~8.6 GB weights + ~3.2 GB worst-case page pool
            # fits one 16 GB v5e chip.
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336, max_seq_len=2048,
            rope_theta=500000.0, tie_embeddings=False,
        ),
        "quality-tiny": dict(
            # CLI end-to-end quality gate (tests/test_quality.py): a byte-
            # level model small enough to fine-tune inside the test suite on
            # CPU, with a context window that fits the product-formatted map
            # prompt (template + chunk context header + chunk body) without
            # middle-truncation at the CLI's default generation budget.
            # max_seq_len 1024: the product-formatted prompts are ~460
            # bytes; CPU XLA compile time scales badly with the window
            # (tests run this preset through the full CLI)
            vocab_size=512, dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=256, max_seq_len=1024, dtype="float32",
        ),
        "bench-smoke": dict(
            # CPU smoke of the bench HARNESS itself (LMRS_BENCH_MODEL=
            # bench-smoke): tiny compute but bench-1b's max_seq_len, so the
            # bench's chunk budget (1400 + context + template < 1920
            # truncation line) holds and the exact same scheduler shapes
            # compile — in seconds on a CPU, not minutes ("tiny" inherits
            # max_seq_len 8192, whose packed/decode shapes thrash CPU XLA).
            vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=256, max_seq_len=2048,
        ),
        "mixtral-8x7b": dict(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            hidden_dim=14336, max_seq_len=8192, rope_theta=1e6,
            tie_embeddings=False, n_experts=8, n_experts_per_token=2,
        ),
    }
    if name not in presets:
        raise ValueError(f"unknown model preset {name!r}; have {sorted(presets)}")
    return ModelConfig(name=name, **presets[name])
