"""lmrs_tpu — TPU-native long-transcript map-reduce summarization framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of
``consilience-dev/llm-map-reduce-summarizer`` (see /root/repo/SURVEY.md): the
reference fans transcript chunks out to a remote LLM HTTP API; this framework
collapses that API boundary and runs the model on-pod — a sharded decoder-only
LLM lives in HBM, prefill/decode run as Pallas flash-attention kernels, and the
chunk list becomes a continuously-batched data-parallel workload.

Layer map (SURVEY.md §7.1):

    L1  data plane       lmrs_tpu.data       preprocess / chunk / tokenize
    L2  engine API       lmrs_tpu.engine     Engine protocol, Mock + JAX engines
    L3  model zoo        lmrs_tpu.models     Llama-3 / Gemma decoders (pytrees)
    L4  kernels          lmrs_tpu.ops        Pallas flash attn, paged decode
    L5  sharding/comms   lmrs_tpu.parallel   mesh, pjit specs, ring attention
    L6  serving          lmrs_tpu.engine     continuous batching, paged KV
    L7  reduce tree      lmrs_tpu.reduce     single-pass + hierarchical reduce
    L8  CLI/API          lmrs_tpu.pipeline   TranscriptSummarizer, CLI, stats
"""

__version__ = "0.1.0"

from lmrs_tpu.config import (
    ChunkConfig,
    DataConfig,
    EngineConfig,
    MeshConfig,
    ModelConfig,
    PipelineConfig,
    ReduceConfig,
    model_preset,
)
from lmrs_tpu.pipeline import TranscriptSummarizer

__all__ = [
    "ChunkConfig",
    "DataConfig",
    "EngineConfig",
    "MeshConfig",
    "ModelConfig",
    "PipelineConfig",
    "ReduceConfig",
    "TranscriptSummarizer",
    "model_preset",
    "__version__",
]
