"""Append-only, fsync'd, CRC-framed JSONL write-ahead journal.

The durability substrate for :mod:`lmrs_tpu.jobs.manager`: every unit of
completed work (a chunk summary, a reduce-tree node, the terminal job
record) is appended as ONE framed line and fsync'd before the in-memory
state advances, so a SIGKILL at any instant loses at most the record
being written — never a record already acknowledged.

Frame format (one record per line)::

    crc32-hex SP canonical-json LF

The CRC covers the canonical-JSON bytes.  Replay semantics:

* **torn tail tolerated** — a crash mid-append leaves at most one
  partial final line; replay drops it silently (``meta["torn"]``) and
  the resumed run simply redoes that one unit of work;
* **mid-file corruption stops replay** — a record that fails its CRC
  *before* the tail means the file was damaged after the fact (bad
  disk, hand edit); everything after it is untrusted and dropped
  (``meta["corrupt"]``), everything before it is kept;
* **duplicate records are idempotent** — state rebuilding keys chunk
  records by chunk identity and reduce records by content key, so a
  journal replayed twice (or a record appended twice across a crash
  window) yields byte-identical state (``rebuild_state``).

Fault-injection sites (docs/ROBUSTNESS.md): ``journal.append`` fires
before the write, ``journal.fsync`` before the fsync — both DEGRADE
(the journal marks itself non-durable and the job continues) rather
than fail the job: journaling is a durability guarantee, not a
correctness dependency of the in-flight run.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import zlib
from pathlib import Path
from typing import Any

from lmrs_tpu.testing import faults

logger = logging.getLogger("lmrs.jobs.journal")

# record types the manager writes (unknown types are ignored on replay —
# forward compatibility for journals written by a newer build).
# REC_HEADER fields: job_id, fingerprint, transcript_sha, created_t,
# trace_id (the job's distributed trace — recovery restores it so a
# resumed job continues the trace it started under; pre-trace journals
# simply lack the key), and a superseding header adds num_chunks.
REC_HEADER = "job_header"
REC_CHUNK = "chunk_done"
REC_NODE = "reduce_node_done"
REC_DONE = "job_done"


def canonical_json(obj: Any) -> str:
    """Stable serialization — the one form every hash in this module (job
    ids, fingerprints, node keys, CRC payloads) is computed over."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      ensure_ascii=False)


def config_fingerprint(**fields: Any) -> str:
    """Hash of the (prompt, model, sampling) surface that determines what a
    chunk summary MEANS.  Journaled at job start and stamped into
    ``--save-chunks`` dumps: rehydrating summaries produced under a
    different fingerprint would silently mix stale content into a fresh
    run (ISSUE 7 satellite 1), so consumers refuse (warn + drop) on
    mismatch."""
    return hashlib.sha256(
        canonical_json(fields).encode("utf-8")).hexdigest()[:16]


def job_id_for(transcript_data: dict, fingerprint: str) -> str:
    """Content-addressed job id: the same transcript under the same
    config fingerprint IS the same job — resubmitting after a crash (or a
    duplicate POST) converges on one journal instead of forking work."""
    digest = hashlib.sha256()
    digest.update(fingerprint.encode("utf-8"))
    digest.update(b":")
    digest.update(canonical_json(transcript_data).encode("utf-8"))
    return "job-" + digest.hexdigest()[:16]


def chunk_key(chunk_index: int, start_time: float, end_time: float) -> str:
    """Chunk identity key (same (index, start, end) match rule as the
    pipeline's ``_load_resume``): chunk boundaries shift when chunking
    config changes, so a stale record can never rehydrate the wrong
    span."""
    return f"{chunk_index}:{round(start_time, 3)}:{round(end_time, 3)}"


def node_key(summaries: list[str], template: str | None,
             metadata: dict | None) -> str:
    """Content-addressed reduce-node key: a node is identified by exactly
    the inputs that determine its prompt.  Deterministic chunking + a
    deterministic tree shape mean a resumed run recomputes the same keys
    and lands on the journaled nodes without any structural bookkeeping."""
    return hashlib.sha256(canonical_json(
        [template or "", metadata or {}, list(summaries)]
    ).encode("utf-8")).hexdigest()[:16]


class Journal:
    """One job's append-only WAL.  Thread-safe (the map stream's
    ``on_final`` callbacks and the manager's control path both append).

    ``append`` returns True when the record is durably on disk; a failed
    append/fsync degrades (record dropped / not-yet-durable, ``degraded``
    set, warning logged) instead of raising — a journal I/O error must
    not kill the job whose progress it was recording.
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._fh = None  # guarded-by: _lock
        self._lock = threading.Lock()
        self.appends = 0  # guarded-by: _lock
        self.append_failures = 0  # guarded-by: _lock
        self.fsync_failures = 0  # guarded-by: _lock
        self.degraded = False  # guarded-by: _lock

    def append(self, rec: dict) -> bool:
        payload = canonical_json(rec)
        data = payload.encode("utf-8")
        line = f"{zlib.crc32(data):08x} ".encode("ascii") + data + b"\n"
        with self._lock:
            try:
                # injection site: the append itself fails (disk full,
                # volume gone) — the job degrades to non-durable progress
                faults.fire("journal.append", OSError)
                if self._fh is None:
                    # (re)opening: the file may end in a PARTIAL line — a
                    # torn tail from a crashed predecessor, or bytes a
                    # failed append left behind.  Appending onto it would
                    # merge two records into one corrupt mid-file line,
                    # and replay would then drop every record after it —
                    # records already acknowledged durable.  Truncate back
                    # to the last complete newline first.
                    self._truncate_partial_tail()
                    self._fh = open(self.path, "ab")
                self._fh.write(line)
                self._fh.flush()
            except Exception as e:  # noqa: BLE001 - degrade, never fatal
                self.append_failures += 1
                self.degraded = True
                logger.warning(
                    "journal %s: append failed (%s: %s); record dropped — "
                    "durability degraded", self.path, type(e).__name__, e)
                self._close_locked()  # the handle may be poisoned
                return False
            self.appends += 1
            try:
                # injection site: the write landed in the page cache but
                # the fsync fails — the record may not survive a crash
                faults.fire("journal.fsync", OSError)
                # the fsync runs INSIDE the critical section on purpose:
                # append durability ordering IS the journal's contract —
                # every appender serializes on the disk here
                os.fsync(self._fh.fileno())  # lint: ignore[race.blocking-under-lock]
            except Exception as e:  # noqa: BLE001 - degrade, never fatal
                self.fsync_failures += 1
                self.degraded = True
                logger.warning(
                    "journal %s: fsync failed (%s: %s); record may not "
                    "survive a crash — durability degraded",
                    self.path, type(e).__name__, e)
                return False
            return True

    def _truncate_partial_tail(self) -> None:
        """Drop trailing bytes past the last complete newline (caller
        holds the lock).  Best-effort: if the disk is too broken to
        repair, the append that follows degrades like any other."""
        try:
            with open(self.path, "rb+") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                keep = size
                while keep > 0:
                    back = min(keep, 1 << 16)
                    fh.seek(keep - back)
                    data = fh.read(back)
                    nl = data.rfind(b"\n")
                    if nl >= 0:
                        keep = keep - back + nl + 1
                        break
                    keep -= back
                if keep < size:
                    fh.truncate(keep)
                    logger.warning(
                        "journal %s: truncated %d trailing partial byte(s) "
                        "(torn tail / failed append) before appending",
                        self.path, size - keep)
        except OSError:
            pass  # no file yet, or unrepairable — append will handle it

    def _close_locked(self) -> None:  # holds-lock: _lock
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:  # noqa: BLE001
                pass
            self._fh = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def stats(self) -> dict:
        return {"appends": self.appends,
                "append_failures": self.append_failures,
                "fsync_failures": self.fsync_failures,
                "degraded": self.degraded}


def replay(path: str | Path) -> tuple[list[dict], dict]:
    """Read every intact record; returns ``(records, meta)`` where meta
    carries ``records`` / ``dropped`` counts plus the ``torn`` (partial
    final line dropped) and ``corrupt`` (mid-file damage; suffix dropped)
    flags.  Never raises on journal content — a journal exists to survive
    crashes, so its reader must survive what crashes leave behind."""
    meta = {"records": 0, "dropped": 0, "torn": False, "corrupt": False}
    p = Path(path)
    try:
        data = p.read_bytes()
    except OSError:
        return [], meta
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()  # file ended with a complete newline
    records: list[dict] = []
    for i, raw in enumerate(lines):
        rec = _parse_line(raw)
        if rec is None:
            if i == len(lines) - 1:
                # torn tail: the crash window this format exists for
                meta["torn"] = True
                meta["dropped"] += 1
                logger.warning("journal %s: dropped torn tail record", p)
            else:
                # mid-file damage: the suffix is untrusted
                meta["corrupt"] = True
                meta["dropped"] += len(lines) - i
                logger.error(
                    "journal %s: corrupt record at line %d; dropping it "
                    "and the %d record(s) after it",
                    p, i + 1, len(lines) - i - 1)
            break
        records.append(rec)
    meta["records"] = len(records)
    return records, meta


def _parse_line(raw: bytes) -> dict | None:
    """One framed line -> record dict, or None when the frame is invalid
    (short line, bad CRC, malformed JSON, non-object payload)."""
    if len(raw) < 10 or raw[8:9] != b" ":
        return None
    try:
        want = int(raw[:8], 16)
    except ValueError:
        return None
    payload = raw[9:]
    if zlib.crc32(payload) != want:
        return None
    try:
        rec = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def rebuild_state(records: list[dict]) -> dict:
    """Fold replayed records into the canonical job state:

    ``{"header": rec|None, "chunks": {chunk_key: rec}, "nodes":
    {node_key: text}, "done": rec|None}``

    Idempotent by construction — duplicates overwrite their own key with
    identical content, so the same journal replayed any number of times
    yields byte-identical state (``canonical_json(rebuild_state(...))``;
    the replay-determinism test asserts exactly this).
    """
    state: dict = {"header": None, "chunks": {}, "nodes": {}, "done": None}
    for rec in records:
        kind = rec.get("type")
        if kind == REC_HEADER:
            state["header"] = rec
        elif kind == REC_CHUNK:
            key = chunk_key(rec.get("chunk_index", -1),
                            rec.get("start_time", 0.0),
                            rec.get("end_time", 0.0))
            state["chunks"][key] = rec
        elif kind == REC_NODE:
            if rec.get("key"):
                state["nodes"][rec["key"]] = rec.get("text", "")
        elif kind == REC_DONE:
            state["done"] = rec
        # unknown types: ignored (forward compatibility)
    return state
