"""Durable summarization jobs: crash-safe map/reduce execution over a WAL.

The pipeline's only durability used to be the best-effort end-of-map
``--save-chunks`` dump (pipeline.py) — manual to wire, blind to the
reduce tree, absent from the serving tier entirely.  This module makes a
*job* the durable unit:

* ``JobManager.submit`` assigns a CONTENT-ADDRESSED job id (transcript ×
  config fingerprint — journal.job_id_for), persists the request
  (``<id>.req.json``) and a journal header, and queues the job; a
  duplicate submit converges on the existing job instead of forking
  work;
* each chunk summary is journaled AS IT COMPLETES through the
  executor's streaming result path (``run_requests_streaming``), not at
  end-of-map — a crash loses at most the summaries in flight;
* the reduce tree runs through ``ResultAggregator`` with a
  content-addressed node cache: every finished node is journaled
  (``reduce_node_done``), so a crash mid-reduce resumes at the exact
  tree node instead of redoing the whole stage;
* ``recover()`` (called by the serving tier at startup) re-queues every
  journal without a terminal record and re-registers terminal jobs so
  their results survive a restart;
* degraded completion: a job whose failed-chunk fraction stays at or
  under ``JobsConfig.max_failed_chunk_fraction`` finishes
  ``status="degraded"`` with per-chunk ``degraded_reason``s attached,
  instead of all-or-nothing failure.

Determinism contract (chaos-gated): chunking, prompt assembly, and the
reduce-tree shape are deterministic in (transcript, config), and the
journal stores exact summary text — so a killed-and-resumed greedy job
produces a final summary token-identical to an uninterrupted run.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from lmrs_tpu.config import JobsConfig, PipelineConfig
from lmrs_tpu.data.chunker import Chunk
from lmrs_tpu.data.preprocessor import (
    extract_speakers,
    get_transcript_duration,
)
from lmrs_tpu.engine.api import degraded_reason
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.jobs import journal as jl
from lmrs_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS_S,
    MetricsRegistry,
    PID_PIPELINE,
    get_tracer,
)
from lmrs_tpu.pipeline import build_chunker, prepare_segments
from lmrs_tpu.prompts import (
    resolve_map_prompt,
    resolve_reduce_prompt,
    resolve_system_prompt,
)
from lmrs_tpu.reduce.aggregator import ResultAggregator
from lmrs_tpu.testing import faults
from lmrs_tpu.utils.timing import format_duration

logger = logging.getLogger("lmrs.jobs")

TERMINAL_STATES = ("done", "degraded", "failed", "cancelled")
# params a job request may carry (everything else is rejected at submit
# so a typo'd knob fails loudly instead of silently running defaults)
_ALLOWED_PARAMS = ("prompt_template", "system_prompt", "aggregator_prompt",
                   "summary_type", "max_tokens_per_chunk")


@dataclass
class Job:
    """In-memory record of one durable job (the journal is the truth)."""

    job_id: str
    params: dict
    fingerprint: str
    req_path: Path
    wal_path: Path
    status: str = "queued"
    created_t: float = field(default_factory=time.time)
    recovered: bool = False
    # distributed trace id (docs/OBSERVABILITY.md § Trace propagation):
    # minted (or taken from the submit's X-LMRS-Trace header) at submit,
    # persisted in the journal header, restored by recover() — a resumed
    # job CONTINUES its trace instead of starting an anonymous one
    trace_id: str | None = None
    # cost-attribution tenant (docs/OBSERVABILITY.md § Request-cost
    # ledger): the submit's X-LMRS-Tenant, defaulting to the job's own
    # id — persisted in the journal header like the trace id, stamped on
    # every chunk/reduce request the job runs, so GET /v1/usage rolls up
    # per job with no extra machinery
    tenant: str | None = None
    # ledger usage rolled up from this process-life's results
    # (obs.merge_usage shape; resumed work re-billed on recompute only —
    # journal-answered chunks cost nothing, which is the point)
    usage: dict = field(default_factory=dict)
    # progress (GET /v1/jobs/<id> partial-progress contract)
    n_chunks: int = 0
    chunks_done: int = 0
    chunks_failed: int = 0
    resumed_chunks: int = 0
    reduce_nodes_done: int = 0
    reduce_nodes_reused: int = 0
    result: dict | None = None
    degraded_reasons: list = field(default_factory=list)
    error: str | None = None
    # control plane
    cancel_ev: threading.Event = field(default_factory=threading.Event)
    done_ev: threading.Event = field(default_factory=threading.Event)
    # a resubmit arrived while a cancel was unwinding the RUNNING run:
    # re-queue when the cancelled finish lands (set/cleared under the
    # manager lock)
    resubmit_pending: bool = False
    journal: jl.Journal | None = None
    _executor: MapExecutor | None = None
    _live_rids: set = field(default_factory=set)

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL_STATES


class _JournalNodeCache:
    """ResultAggregator ``node_cache``: looks reduce nodes up by content
    key in the replayed journal state and journals each newly completed
    node — the exact-tree-node resume substrate."""

    def __init__(self, manager: "JobManager", job: Job, nodes: dict[str, str]):
        self._manager = manager
        self._job = job
        self._nodes = dict(nodes)
        self.reused = 0

    def lookup(self, node_id: str, summaries: list[str],
               template: str | None, metadata: dict | None) -> str | None:
        text = self._nodes.get(jl.node_key(summaries, template, metadata))
        if text is not None:
            self.reused += 1
            logger.info("job %s: reduce node %s resumed from journal",
                        self._job.job_id, node_id)
        return text

    def record(self, node_id: str, summaries: list[str],
               template: str | None, metadata: dict | None,
               text: str) -> None:
        key = jl.node_key(summaries, template, metadata)
        self._nodes[key] = text
        self._job.reduce_nodes_done += 1
        self._manager._append(self._job, {
            "type": jl.REC_NODE, "node_id": node_id, "key": key,
            "text": text})


class JobManager:
    """Owns the jobs directory, the journals, and the worker that runs
    queued jobs through a MapExecutor + ResultAggregator over ``engine``.

    One worker thread by default: raw engines (mock, jax) do not accept
    concurrent ``generate_batch`` calls; inside the serving tier the
    engine is the micro-batcher facade (serving/server.py), which
    serializes jobs with interactive traffic in the same dispatch queue.
    """

    def __init__(self, engine, jobs_dir: str | Path,
                 config: PipelineConfig | None = None,
                 jobs_config: JobsConfig | None = None,
                 start_worker: bool = True):
        self.engine = engine
        self.config = config or PipelineConfig()
        self.jobs_cfg = jobs_config or self.config.jobs
        self.dir = Path(jobs_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._jobs: dict[str, Job] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        self._queue: queue.Queue[str | None] = queue.Queue()
        self._stopped = False
        # ---- lmrs_jobs_* metrics (merged into the server's /metrics)
        self.registry = MetricsRegistry()
        c = self.registry.counter
        self._c_submitted = c("lmrs_jobs_submitted_total",
                              "jobs accepted by POST /v1/jobs or submit()")
        self._c_completed = c("lmrs_jobs_completed_total",
                              "jobs finished status=done")
        self._c_degraded = c("lmrs_jobs_degraded_total",
                             "jobs finished status=degraded (failed-chunk "
                             "fraction within policy)")
        self._c_failed = c("lmrs_jobs_failed_total",
                           "jobs finished status=failed")
        self._c_cancelled = c("lmrs_jobs_cancelled_total",
                              "jobs cancelled via DELETE /v1/jobs/<id>")
        self._c_recovered = c("lmrs_jobs_recovered_total",
                              "interrupted jobs re-queued by startup "
                              "recovery")
        self._c_chunks_resumed = c("lmrs_jobs_chunks_resumed_total",
                                   "chunk summaries rehydrated from the "
                                   "journal instead of recomputed")
        self._c_nodes_reused = c("lmrs_jobs_reduce_nodes_reused_total",
                                 "reduce-tree nodes resumed from the "
                                 "journal instead of recomputed")
        self._c_appends = c("lmrs_jobs_journal_appends_total",
                            "journal records durably written")
        self._c_append_failures = c("lmrs_jobs_journal_append_failures_total",
                                    "journal appends/fsyncs that degraded "
                                    "(record dropped or not durable)")
        self._g_active = self.registry.gauge(
            "lmrs_jobs_active", "jobs currently queued or running")
        self._h_duration = self.registry.histogram(
            "lmrs_jobs_duration_seconds", DEFAULT_LATENCY_BUCKETS_S,
            help="wall-clock of one job run (resumed runs count their "
                 "own wall only)", unit="seconds")
        self._worker: threading.Thread | None = None
        if start_worker:
            self._worker = threading.Thread(target=self._worker_loop,
                                            daemon=True, name="lmrs-jobs")
            self._worker.start()

    # ------------------------------------------------------------- public

    def submit(self, transcript_data: dict, params: dict | None = None,
               trace_id: str | None = None,
               tenant: str | None = None) -> Job:
        """Persist + queue a job; returns immediately (POST /v1/jobs).
        Content-addressed: an identical (transcript, params) submit
        returns the existing job — live jobs dedupe, terminal
        failed/cancelled jobs re-queue on the SAME journal so the retry
        resumes everything already journaled.  ``trace_id`` (the submit
        header) is persisted in the journal header so the job's trace
        survives restarts; a duplicate submit keeps the FIRST trace (the
        journal is the truth)."""
        params = self._sanitize_params(params)
        fp = self._fingerprint(params)
        jid = jl.job_id_for(transcript_data, fp)
        with self._lock:
            job = self._jobs.get(jid)
            if job is not None:
                if job.status in ("queued", "running", "done", "degraded"):
                    if job.status == "queued" and job.cancel_ev.is_set():
                        # a resubmit supersedes a still-pending cancel of a
                        # QUEUED job: answering "queued" while letting the
                        # dequeue cancel it would silently swallow the
                        # acknowledged submit
                        job.cancel_ev = threading.Event()
                    elif job.status == "running" and job.cancel_ev.is_set():
                        # same race mid-unwind: the running job WILL finish
                        # cancelled — mark it to re-queue when that finish
                        # lands, so this acknowledged submit still executes
                        job.resubmit_pending = True
                    return job
                # failed/cancelled: a resubmit is an explicit retry — the
                # journal keeps every chunk/node already done (run_job
                # supersedes the stale terminal record), the progress
                # counters start over for the new run.  params/fingerprint
                # refresh and the request re-persists (below, outside the
                # lock): a job registered by a FAILED recovery (params={},
                # fingerprint="", req file possibly unreadable) must heal
                # here, or the retry would run default params and
                # stale-side its own journal
                job.params = params
                job.fingerprint = fp
                self._reset_for_retry_locked(job)
                fresh = False
            else:
                job = self._register(jid, params, fp)
                self._c_submitted.inc()
                self._g_active.set(self._active_count())
                fresh = True
            if job.trace_id is None:
                from lmrs_tpu.obs import new_trace_id

                job.trace_id = trace_id or new_trace_id()
            if job.tenant is None:
                # the submit's tenant wins; anonymous submits bill to the
                # job's own identity (per-job usage rollups for free)
                job.tenant = tenant or f"job:{jid[:24]}"
        # Disk I/O OUTSIDE the lock: the fsync'd header append must not
        # serialize every get()/jobs()/stats() reader behind the disk.  A
        # concurrent duplicate submit finds the registered job and returns
        # it immediately; the worker only sees the jid once the artifacts
        # exist (_queue.put is last).
        try:
            # request persisted ATOMICALLY before the journal header: a
            # crash between the two leaves either nothing or a resumable
            # (req, header) pair — never a header with no way to re-chunk
            tmp = job.req_path.with_suffix(".tmp")
            tmp.write_text(jl.canonical_json({
                "job_id": jid, "fingerprint": fp, "params": params,
                "transcript": transcript_data}), encoding="utf-8")
            os.replace(tmp, job.req_path)
            if job.journal is None:
                job.journal = jl.Journal(job.wal_path)
            if fresh and not job.wal_path.exists():
                self._append(job, {
                    "type": jl.REC_HEADER, "job_id": jid, "fingerprint": fp,
                    "transcript_sha": jl.job_id_for(transcript_data, ""),
                    "trace_id": job.trace_id,
                    "tenant": job.tenant,
                    "created_t": job.created_t})
        except Exception as e:
            # the registered-but-unqueued job must not linger "queued"
            with self._lock:
                job.status = "failed"
                job.error = f"submit failed: {type(e).__name__}: {e}"
                self._g_active.set(self._active_count())
            job.done_ev.set()
            raise
        tr = get_tracer()
        if tr:
            tr.instant("job_submit", pid=PID_PIPELINE,
                       args={"job": jid, "trace": job.trace_id})
        self._queue.put(jid)
        return job

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.created_t)

    def cancel(self, job_id: str) -> Job | None:
        """Cancel a queued or running job (DELETE /v1/jobs/<id>).  Queued
        jobs terminate at dequeue; a running job's in-flight requests are
        chased through the executor's cancel/interrupt hooks and the job
        finishes ``status="cancelled"`` (journaled, so the cancellation
        itself survives a restart).  Terminal jobs are returned as-is."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.terminal:
                return job
            job.cancel_ev.set()
            ex = job._executor
            rids = list(job._live_rids)
        if ex is not None:
            ex.interrupt()
            for rid in rids:
                ex.cancel(rid)
        return job

    def recover(self) -> int:
        """Scan the jobs directory at startup: terminal jobs re-register
        (their results stay pollable across restarts), interrupted ones
        re-queue.  Returns the number re-queued.  A job whose recovery
        fails (``jobs.recover`` fault site; unreadable request file) is
        registered ``status="failed"`` so the interruption stays visible
        instead of silently vanishing — the others still recover."""
        recovered = 0
        for wal in sorted(self.dir.glob("*.wal")):
            jid = wal.stem
            with self._lock:
                if jid in self._jobs:
                    continue
            try:
                # injection site: recovery of THIS job fails (corrupt
                # request file, permission loss) — degrade per job
                faults.fire("jobs.recover", OSError)
                req = json.loads(
                    (self.dir / f"{jid}.req.json").read_text("utf-8"))
                records, _meta = jl.replay(wal)
                state = jl.rebuild_state(records)
                # fingerprint recomputed under the CURRENT config — not the
                # one stored at submit time — so run_job's gate catches a
                # prompt/model surface that changed across the restart and
                # refuses to mix the old journal's summaries into the rerun
                fp = self._fingerprint(req.get("params") or {})
            except Exception as e:  # noqa: BLE001 - degrade per job
                logger.warning("job %s: recovery failed: %s: %s",
                               jid, type(e).__name__, e)
                with self._lock:
                    job = self._register(jid, {}, "")
                    job.status = "failed"
                    job.error = f"recovery failed: {type(e).__name__}: {e}"
                    job.done_ev.set()
                continue
            with self._lock:
                job = self._register(jid, req.get("params") or {}, fp)
                job.journal = jl.Journal(job.wal_path)
                job.recovered = True
                # a resumed job CONTINUES its trace: the header's id was
                # minted at the original submit (pre-trace journals just
                # start a fresh trace here)
                header_trace = (state["header"] or {}).get("trace_id")
                if isinstance(header_trace, str) and header_trace:
                    job.trace_id = header_trace
                # a resumed job keeps billing to its original tenant
                header_tenant = (state["header"] or {}).get("tenant")
                job.tenant = (header_tenant
                              if isinstance(header_tenant, str)
                              and header_tenant else f"job:{jid[:24]}")
                if state["done"] is not None:
                    self._finish_from_record(job, state["done"])
                    continue
                job.n_chunks = (state["header"] or {}).get("num_chunks", 0)
                self._c_recovered.inc()
                self._g_active.set(self._active_count())
            tr = get_tracer()
            if tr:
                tr.instant("job_recover", pid=PID_PIPELINE,
                           args={"job": jid, "trace": job.trace_id})
            logger.info("job %s: interrupted journal found; re-queued "
                        "(%d chunk record(s), %d reduce node(s))", jid,
                        len(state["chunks"]), len(state["nodes"]))
            self._queue.put(jid)
            recovered += 1
        return recovered

    def wait(self, job_id: str, timeout: float = 60.0) -> Job | None:
        """Block until the job is terminal (test/CLI convenience)."""
        job = self.get(job_id)
        if job is not None:
            job.done_ev.wait(timeout)
        return job

    def status_doc(self, job: Job) -> dict:
        """The GET /v1/jobs/<id> response body."""
        doc = {
            "object": "job",
            "id": job.job_id,
            "status": job.status,
            "created_t": job.created_t,
            "recovered": job.recovered,
            "trace_id": job.trace_id,
            "tenant": job.tenant,
            "progress": {
                "num_chunks": job.n_chunks,
                "chunks_done": job.chunks_done,
                "chunks_failed": job.chunks_failed,
                "num_resumed_chunks": job.resumed_chunks,
                "reduce_nodes_done": job.reduce_nodes_done,
                "reduce_nodes_reused": job.reduce_nodes_reused,
            },
        }
        if job.result is not None:
            doc["result"] = job.result
        if job.usage:
            # ledger rollup over THIS process life's engine work (journal-
            # answered chunks cost nothing — the savings ARE the feature)
            doc["usage"] = job.usage
        if job.degraded_reasons:
            doc["degraded_reasons"] = job.degraded_reasons
        if job.error is not None:
            doc["error"] = job.error
        if job.journal is not None:
            doc["journal"] = job.journal.stats()
        return doc

    def stats(self) -> dict:
        with self._lock:
            by_status: dict[str, int] = {}
            for j in self._jobs.values():
                by_status[j.status] = by_status.get(j.status, 0) + 1
        return {"jobs": sum(by_status.values()), "by_status": by_status,
                "jobs_dir": str(self.dir)}

    def shutdown(self) -> None:
        self._stopped = True
        self._queue.put(None)
        if self._worker is not None:
            self._worker.join(timeout=5)
        with self._lock:
            for job in self._jobs.values():
                if job.journal is not None:
                    job.journal.close()

    # ---------------------------------------------------------- internals

    def _register(self, jid: str, params: dict,
                  fingerprint: str) -> Job:  # holds-lock: _lock
        job = Job(job_id=jid, params=params, fingerprint=fingerprint,
                  req_path=self.dir / f"{jid}.req.json",
                  wal_path=self.dir / f"{jid}.wal")
        self._jobs[jid] = job
        return job

    def _active_count(self) -> int:
        return sum(1 for j in self._jobs.values() if not j.terminal)

    def _reset_for_retry_locked(self, job: Job) -> None:
        """Back to "queued" with fresh progress/control state (caller
        holds the lock and owns the _queue.put)."""
        job.status = "queued"
        job.error = None
        job.result = None
        job.degraded_reasons = []
        job.chunks_done = job.chunks_failed = 0
        job.resumed_chunks = 0
        job.reduce_nodes_done = job.reduce_nodes_reused = 0
        job.resubmit_pending = False
        job.cancel_ev = threading.Event()
        job.done_ev = threading.Event()
        self._g_active.set(self._active_count())

    def _sanitize_params(self, params: dict | None) -> dict:
        p = dict(params or {})
        unknown = sorted(set(p) - set(_ALLOWED_PARAMS))
        if unknown:
            raise ValueError(f"unknown job param(s) {unknown}; "
                             f"supported: {sorted(_ALLOWED_PARAMS)}")
        if "max_tokens_per_chunk" in p:
            try:
                p["max_tokens_per_chunk"] = int(p["max_tokens_per_chunk"])
            except (TypeError, ValueError):
                raise ValueError(
                    "max_tokens_per_chunk must be an integer "
                    f"(got {p['max_tokens_per_chunk']!r})") from None
        return p

    def _fingerprint(self, params: dict) -> str:
        e = self.config.engine
        c = self.config.chunk
        return jl.config_fingerprint(
            map_prompt=resolve_map_prompt(params.get("prompt_template"), None),
            system_prompt=resolve_system_prompt(
                params.get("system_prompt"), None) or "",
            reduce_prompt=resolve_reduce_prompt(
                params.get("aggregator_prompt"), None) or "",
            summary_type=params.get("summary_type", "summary"),
            backend=e.backend, model=e.model, temperature=e.temperature,
            max_tokens=e.max_tokens, seed=e.seed,
            max_tokens_per_chunk=params.get("max_tokens_per_chunk",
                                            c.max_tokens_per_chunk),
            overlap_tokens=c.overlap_tokens,
            context_tokens=c.context_tokens)

    def _append(self, job: Job, rec: dict) -> None:
        ok = job.journal.append(rec) if job.journal is not None else False
        (self._c_appends if ok else self._c_append_failures).inc()

    def _worker_loop(self) -> None:
        while True:
            jid = self._queue.get()
            if jid is None:
                return
            job = self.get(jid)
            if job is None or job.terminal:
                continue
            if job.cancel_ev.is_set():
                self._finish(job, "cancelled", None, [])
                continue
            try:
                self.run_job(job)
            except Exception as e:  # noqa: BLE001 - the worker must survive
                logger.exception("job %s: run failed", jid)
                self._finish(job, "failed", None, [],
                             error=f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------- run

    def run_job(self, job: Job) -> Job:
        """Execute (or resume) one job synchronously.  Used by the worker
        thread; callable directly when the manager was built with
        ``start_worker=False`` (tests, one-shot CLI runs)."""
        t0 = time.time()
        job.status = "running"
        if job.journal is None:
            job.journal = jl.Journal(job.wal_path)
        records, meta = jl.replay(job.wal_path)
        state = jl.rebuild_state(records)
        done_rec = state["done"]
        if done_rec is not None:
            if done_rec.get("status") in ("done", "degraded"):
                # raced a completed run: its result stands
                with self._lock:
                    self._finish_from_record(job, done_rec)
                return job
            # a failed/cancelled terminal record does NOT block an explicit
            # resubmit: this run supersedes it (the _finish below appends a
            # newer job_done; rebuild_state keeps the last one), and every
            # chunk/node journaled before the failure still resumes
        # Fingerprint gate (same contract as pipeline._load_resume): a
        # journal written under a different prompt/model surface must not
        # rehydrate into this run — warn, set the stale WAL aside, start
        # a fresh journal.
        hdr = state["header"]
        if hdr is not None and hdr.get("fingerprint") != job.fingerprint:
            logger.warning(
                "job %s: journal fingerprint %s != expected %s; dropping "
                "journaled progress (stale prompt/model surface)",
                job.job_id, hdr.get("fingerprint"), job.fingerprint)
            state = {"header": None, "chunks": {}, "nodes": {}, "done": None}
            job.journal.close()
            try:
                os.replace(job.wal_path, str(job.wal_path) + ".stale")
            except OSError:
                pass
            job.journal = jl.Journal(job.wal_path)
        if state["header"] is None:
            self._append(job, {
                "type": jl.REC_HEADER, "job_id": job.job_id,
                "fingerprint": job.fingerprint, "created_t": job.created_t,
                "trace_id": job.trace_id, "tenant": job.tenant})

        transcript = json.loads(job.req_path.read_text("utf-8"))["transcript"]
        params = job.params
        map_prompt = resolve_map_prompt(params.get("prompt_template"), None)
        sys_prompt = resolve_system_prompt(params.get("system_prompt"), None)
        reduce_prompt = resolve_reduce_prompt(
            params.get("aggregator_prompt"), None)
        summary_type = params.get("summary_type", "summary")

        # one prep implementation with the batch pipeline (pipeline.py) —
        # the two durability paths must chunk identically or their
        # artifacts go stale against each other.  engine=None on purpose:
        # journal chunk-identity keys need purely (transcript, config)-
        # deterministic boundaries, never engine-instance-dependent ones
        _n, processed = prepare_segments(self.config, transcript)
        chunker = build_chunker(self.config, engine=None,
                                max_tokens_per_chunk=params.get(
                                    "max_tokens_per_chunk"))
        chunks = chunker.chunk_transcript(processed)
        job.n_chunks = len(chunks)
        # journal the chunk count (replay keeps the LAST header): a crash
        # mid-map lets recover() report a real progress denominator on the
        # re-queued job instead of num_chunks=0 until the rerun re-chunks
        hdr0 = state["header"] or {}
        if hdr0.get("num_chunks") != len(chunks):
            self._append(job, {
                **{k: v for k, v in hdr0.items() if k != "type"},
                "type": jl.REC_HEADER, "job_id": job.job_id,
                "fingerprint": job.fingerprint, "created_t": job.created_t,
                "tenant": job.tenant,
                "num_chunks": len(chunks)})

        # ---- resume: rehydrate journaled chunk summaries (errored
        # records are NOT rehydrated — a restart is a fresh retry chance;
        # an EMPTY summary is still a completed success and must resume,
        # so presence is the test, not truthiness)
        resumed = 0
        for c in chunks:
            rec = state["chunks"].get(
                jl.chunk_key(c.chunk_index, c.start_time, c.end_time))
            if rec and rec.get("summary") is not None and not rec.get("error"):
                c.summary = rec["summary"]
                c.tokens_used = rec.get("tokens_used", 0)
                resumed += 1
        job.resumed_chunks = resumed
        job.chunks_done = resumed
        if resumed:
            self._c_chunks_resumed.inc(resumed)
            tr = get_tracer()
            if tr:
                tr.instant("job_resume", pid=PID_PIPELINE,
                           args={"job": job.job_id, "resumed_chunks": resumed,
                                 "journaled_nodes": len(state["nodes"]),
                                 "trace": job.trace_id})
            logger.info("job %s: resumed %d/%d chunk summaries and %d "
                        "reduce node(s) from the journal", job.job_id,
                        resumed, len(chunks), len(state["nodes"]))

        from lmrs_tpu.engine.api import TenantStampEngine

        def _publish_usage(snap: dict) -> None:
            # atomic reference swap: status_doc serializes whatever
            # snapshot it holds — never a dict a merge is resizing
            job.usage = snap

        # batch class: a job's map fan-out is exactly the bulk work the
        # QoS preemption policy victimizes before a live session's refresh
        stamp = TenantStampEngine(self.engine, job.tenant,
                                  publish=_publish_usage, seed=job.usage,
                                  qos_class="batch")
        executor = MapExecutor(stamp, self.config.engine)
        job._executor = executor
        self._run_map(job, executor, chunks, map_prompt, summary_type,
                      sys_prompt)
        if job.cancel_ev.is_set():
            return self._finish(job, "cancelled", None, [], t0=t0)

        # ---- reduce, resuming at journaled tree nodes
        cache = _JournalNodeCache(self, job, state["nodes"])
        aggregator = ResultAggregator(executor, self.config.reduce,
                                      tokenizer=chunker.tokenizer)
        ordered = sorted(chunks, key=lambda c: c.chunk_index)
        duration = get_transcript_duration(processed)
        metadata = {
            "duration": format_duration(duration),
            "speakers": ", ".join(extract_speakers(processed)),
            "num_chunks": len(ordered),
        }
        agg = aggregator.aggregate(ordered, reduce_prompt, metadata,
                                   node_cache=cache)
        job.reduce_nodes_reused = cache.reused
        if cache.reused:
            self._c_nodes_reused.inc(cache.reused)
        if job.cancel_ev.is_set():
            return self._finish(job, "cancelled", None, [], t0=t0)

        failed = [c for c in ordered if c.error]
        frac = len(failed) / len(ordered) if ordered else 0.0
        reduce_errors = agg.get("reduce_errors", 0)
        if agg.get("final_error"):
            # the deliverable itself is an error marker — "done" with a
            # garbage summary would journal terminal and never be retried
            status = "failed"
        elif not failed and not reduce_errors:
            status = "done"
        elif frac <= self.jobs_cfg.max_failed_chunk_fraction:
            status = "degraded"
        else:
            status = "failed"
        reasons = [{"chunk_index": c.chunk_index, "degraded_reason": c.error}
                   for c in failed]
        if reduce_errors:
            reasons.append({"node": "reduce", "degraded_reason":
                            f"{reduce_errors} reduce node(s) degraded to "
                            "error markers"})
        result = {
            "summary": agg["final_summary"],
            "num_chunks": len(ordered),
            "num_resumed_chunks": resumed,
            "failed_chunks": len(failed),
            "reduce_errors": reduce_errors,
            "hierarchical": agg["hierarchical"],
            "reduce_levels": agg["levels"],
            "reduce_nodes_reused": cache.reused,
            **executor.stats(),
        }
        return self._finish(job, status, result, reasons, t0=t0)

    def _run_map(self, job: Job, executor: MapExecutor, chunks: list[Chunk],
                 map_prompt: str, summary_type: str,
                 sys_prompt: str | None) -> None:
        """Map every un-resumed chunk, journaling each summary AS IT
        COMPLETES through the streaming result path — the WAL advances
        inside the stream, not at end-of-map."""
        todo = [c for c in chunks if c.summary is None]
        if not todo:
            return
        chunk_by_rid: dict[int, Chunk] = {}
        requests = []
        for i, c in enumerate(todo):
            requests.append(executor.build_map_request(
                c, map_prompt, summary_type, sys_prompt, request_id=i))
            chunk_by_rid[i] = c
        job._live_rids = set(chunk_by_rid)

        def on_final(res, submit) -> None:
            c = chunk_by_rid[res.request_id]
            job._live_rids.discard(res.request_id)
            reason = degraded_reason(res)
            if reason is not None:
                c.summary = f"[Error processing chunk: {reason}]"
                c.error = reason
                job.chunks_failed += 1
            else:
                c.summary = res.text
            c.tokens_used = res.total_tokens
            c.device_seconds = res.device_seconds
            job.chunks_done += 1
            # a cancelled chunk is not durable progress; everything else
            # (successes AND degraded outcomes) journals — replay retries
            # errored records, so journaling them only aids triage
            if res.finish_reason != "cancelled":
                self._append(job, {
                    "type": jl.REC_CHUNK, "chunk_index": c.chunk_index,
                    "start_time": c.start_time, "end_time": c.end_time,
                    "summary": c.summary, "tokens_used": c.tokens_used,
                    "error": c.error})
            if job.cancel_ev.is_set():
                executor.interrupt()
                for rid in list(job._live_rids):
                    executor.cancel(rid)

        executor.run_requests_streaming(requests, on_final)
        job._live_rids = set()

    def _finish(self, job: Job, status: str, result: dict | None,
                reasons: list, error: str | None = None,
                t0: float | None = None) -> Job:
        with self._lock:
            job.status = status
            job.result = result
            job.degraded_reasons = reasons
            if error is not None:
                job.error = error
            self._g_active.set(self._active_count())
        # A failed/degraded finish during manager shutdown is (at least
        # partly) a shutdown artifact — the batcher fast-fails in-flight
        # requests — and journaling it terminal would make a GRACEFUL
        # restart non-resumable.  Leave the journal non-terminal so
        # recover() re-queues, same as a SIGKILL; explicit cancellations
        # still journal (user intent must survive the restart).
        skip_terminal_rec = self._stopped and status in ("failed", "degraded")
        if job.journal is not None and not skip_terminal_rec:
            self._append(job, {
                "type": jl.REC_DONE, "status": status,
                "summary": (result or {}).get("summary"),
                "result": result, "degraded_reasons": reasons,
                "error": error})
        elif skip_terminal_rec:
            logger.info("job %s: %s during shutdown — terminal record "
                        "withheld so the restart resumes it", job.job_id,
                        status)
        counter = {"done": self._c_completed, "degraded": self._c_degraded,
                   "failed": self._c_failed,
                   "cancelled": self._c_cancelled}.get(status)
        if counter is not None:
            counter.inc()
        if t0 is not None:
            self._h_duration.observe(time.time() - t0)
        tr = get_tracer()
        if tr:
            tr.instant("job_done", pid=PID_PIPELINE,
                       args={"job": job.job_id, "status": status,
                             "trace": job.trace_id})
        logger.info("job %s: %s (%d/%d chunks, %d failed, %d resumed, "
                    "%d node(s) reused)", job.job_id, status,
                    job.chunks_done, job.n_chunks, job.chunks_failed,
                    job.resumed_chunks, job.reduce_nodes_reused)
        job.done_ev.set()
        with self._lock:
            requeue = (status == "cancelled" and job.resubmit_pending
                       and not self._stopped)
            if requeue:
                self._reset_for_retry_locked(job)
        if requeue:
            logger.info("job %s: a resubmit superseded the cancel; "
                        "re-queued", job.job_id)
            self._queue.put(job.job_id)
        return job

    def _finish_from_record(self, job: Job, done: dict) -> None:
        """Register a journal's terminal record (startup recovery / raced
        completion): the result survives the restart without re-running.
        Caller holds ``self._lock``."""
        job.status = done.get("status", "done")
        self._g_active.set(self._active_count())
        job.result = done.get("result")
        job.degraded_reasons = done.get("degraded_reasons") or []
        job.error = done.get("error")
        if job.result:
            job.n_chunks = job.result.get("num_chunks", 0)
            job.chunks_done = job.n_chunks
            job.chunks_failed = job.result.get("failed_chunks", 0)
            job.resumed_chunks = job.result.get("num_resumed_chunks", 0)
        job.done_ev.set()
