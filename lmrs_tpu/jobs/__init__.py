"""Durable summarization jobs: write-ahead journal + crash-safe resume.

``journal`` — CRC-framed, fsync'd JSONL WAL (torn-tail-tolerant replay,
content-addressed job ids / reduce-node keys, config fingerprints).
``manager`` — ``JobManager``: queued execution, per-chunk + per-node
journaling, startup recovery, degraded completion, ``lmrs_jobs_*``
metrics.  Serving surface: ``POST/GET/DELETE /v1/jobs`` on lmrs-serve
(serving/server.py) with sticky router forwarding (serving/router.py).
See docs/ROBUSTNESS.md § Durable jobs.
"""

from lmrs_tpu.jobs.journal import (
    Journal,
    canonical_json,
    chunk_key,
    config_fingerprint,
    job_id_for,
    node_key,
    rebuild_state,
    replay,
)
from lmrs_tpu.jobs.manager import Job, JobManager, TERMINAL_STATES

__all__ = [
    "Journal", "canonical_json", "chunk_key", "config_fingerprint",
    "job_id_for", "node_key", "rebuild_state", "replay",
    "Job", "JobManager", "TERMINAL_STATES",
]
