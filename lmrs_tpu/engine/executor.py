"""Map-stage executor: fan chunks into the engine with the reference's
scheduling contract.

Successor of ``LLMExecutor.process_chunks`` (llm_executor.py:110-228).  The
reference's semantics are preserved exactly, re-based onto a local engine:

* concurrency cap        — ``asyncio.Semaphore(max_concurrent_requests)``
                           (llm_executor.py:133) becomes wave-sized batch
                           admission into the engine;
* per-chunk retry loop   — RETRY_ATTEMPTS × RETRY_DELAY
                           (llm_executor.py:198-228) becomes requeue waves;
* degrade-and-continue   — an exhausted chunk gets the inline
                           ``"[Error processing chunk: …]"`` summary + error
                           field, never an exception (llm_executor.py:219-225);
* order restoration      — results sorted by chunk_index
                           (llm_executor.py:157);
* accounting             — total_tokens_used / total_requests /
                           failed_requests counters (llm_executor.py:86-90);
                           dollar cost becomes device-seconds.
"""

from __future__ import annotations

import logging
import time
from dataclasses import replace
from typing import Sequence

from lmrs_tpu.config import EngineConfig
from lmrs_tpu.data.chunker import Chunk
from lmrs_tpu.engine.api import Engine, GenerationRequest, GenerationResult
from lmrs_tpu.obs import PID_PIPELINE, get_tracer
from lmrs_tpu.prompts import safe_format, shared_prefix_chars

logger = logging.getLogger("lmrs.executor")


class MapExecutor:
    """Runs the map stage (and, for the reduce tree, ad-hoc request lists)."""

    def __init__(self, engine: Engine, config: EngineConfig | None = None):
        self.engine = engine
        self.config = config or EngineConfig()
        # running totals (llm_executor.py:86-90)
        self.total_tokens_used = 0
        self.total_device_seconds = 0.0
        self.total_requests = 0
        self.failed_requests = 0

    # ------------------------------------------------------------------ map

    def process_chunks(
        self,
        chunks: Sequence[Chunk],
        prompt_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
    ) -> list[Chunk]:
        """Summarize every chunk; returns chunks ordered by chunk_index."""
        self.process_chunk_groups([chunks], prompt_template, summary_type,
                                  system_prompt)
        return sorted(chunks, key=lambda c: c.chunk_index)  # llm_executor.py:157

    def process_chunk_groups(
        self,
        groups: Sequence[Sequence[Chunk]],
        prompt_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
    ) -> None:
        """Summarize every chunk of every group through ONE pooled request
        queue (multi-transcript batching: the engine's batch slots fill from
        all transcripts at once instead of draining per transcript).
        Summaries are written onto the chunks in place.

        Groups interleave ROUND-ROBIN into the queue (VERDICT r2 item 9):
        admission is FIFO, so appending whole groups in order would make
        transcript N's first chunk wait behind every chunk of transcripts
        0..N-1 — the pooled-queue design exists to overlap transcripts, and
        per-transcript completion skew should reflect size, not submission
        order."""
        t0 = time.time()
        requests = []
        flat: list[Chunk] = []
        queues = [list(chunks) for chunks in groups]
        while any(queues):
            for g in queues:
                if not g:
                    continue
                chunk = g.pop(0)
                requests.append(self.build_map_request(
                    chunk, prompt_template, summary_type, system_prompt,
                    request_id=len(flat)))  # pool-unique, not chunk_index
                flat.append(chunk)

        results = self.run_requests(requests)
        failed = 0
        for chunk, res in zip(flat, results):
            if res.error is not None:
                chunk.summary = f"[Error processing chunk: {res.error}]"
                chunk.error = res.error
                failed += 1
            else:
                chunk.summary = res.text
            chunk.tokens_used = res.total_tokens
            chunk.device_seconds = res.device_seconds
        tr = get_tracer()
        if tr:
            tr.complete("map_stage", t0, time.time(), pid=PID_PIPELINE,
                        args={"chunks": len(flat), "groups": len(groups),
                              "failed": failed})
        logger.info(
            "map stage: %d chunks (%d groups) in %.2fs (%d failed)",
            len(flat), len(groups), time.time() - t0, failed,
        )

    def build_map_request(
        self,
        chunk: Chunk,
        prompt_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
        request_id: int = 0,
    ) -> GenerationRequest:
        """One chunk → one map request — the single source of truth for how
        map prompts and generation params are assembled (used by both the
        barrier path here and reduce/streaming.py)."""
        # safe_format, not str.format: user prompt files may contain
        # literal braces (JSON examples) that str.format would choke on
        prompt = safe_format(
            prompt_template,
            transcript=chunk.text_with_context,
            summary_type=summary_type,
        )
        return GenerationRequest(
            prompt=prompt,
            request_id=request_id,
            system_prompt=chunk.system_prompt or system_prompt,
            max_new_tokens=self.config.max_tokens,
            temperature=self.config.temperature,
            seed=self.config.seed,
            # prefix-cache hint: everything before the per-chunk transcript
            # substitution is the map preamble every chunk shares
            cache_prefix=shared_prefix_chars(
                prompt_template, "transcript", summary_type=summary_type),
        )

    # ----------------------------------------------------- request plumbing

    def run_requests(self, requests: list[GenerationRequest]) -> list[GenerationResult]:
        """Admission-controlled waves + retry/requeue + accounting.

        Engines with their own admission control (continuous batching) get
        the whole queue at once — the wave cap is the semaphore analog for
        engines that lack one (mock, static), and a barrier between waves
        would leave the continuous scheduler's slots draining idle."""
        if getattr(self.engine, "schedules_internally", False):
            wave = max(1, len(requests))
        else:
            wave = max(1, self.config.max_concurrent_requests)
        done: dict[int, GenerationResult] = {}
        pending = list(requests)
        attempt = 1
        while pending:
            failed: list[GenerationRequest] = []
            for i in range(0, len(pending), wave):
                batch = pending[i : i + wave]
                try:
                    results = self.engine.generate_batch(batch)
                except Exception as e:  # engine-level fault: fail the batch
                    logger.exception("engine batch failure")
                    results = [
                        GenerationResult(request_id=r.request_id, finish_reason="error", error=str(e))
                        for r in batch
                    ]
                for req, res in zip(batch, results):
                    self.total_requests += 1
                    if res.error is not None:
                        failed.append(req)
                    else:
                        done[res.request_id] = res
                        self.total_tokens_used += res.total_tokens
                        self.total_device_seconds += res.device_seconds
            if not failed:
                break
            if attempt >= self.config.retry_attempts:
                for req in failed:
                    self.failed_requests += 1
                    done.setdefault(
                        req.request_id,
                        GenerationResult(
                            request_id=req.request_id,
                            finish_reason="error",
                            error=f"failed after {attempt} attempts",
                        ),
                    )
                break
            logger.warning(
                "retrying %d failed requests (attempt %d/%d) after %.1fs",
                len(failed), attempt + 1, self.config.retry_attempts, self.config.retry_delay,
            )
            time.sleep(self.config.retry_delay)
            pending = failed
            attempt += 1
        return [done[r.request_id] for r in requests]

    def run_requests_streaming(self, requests: list[GenerationRequest],
                               on_final) -> None:
        """Streaming analog of ``run_requests``: one engine stream, results
        delivered through ``on_final(result, submit)`` as they complete, and
        ``submit(more)`` feeds new requests into the SAME stream (the
        map→reduce overlap hook).

        Retries: a failed request is resubmitted into the stream
        immediately — device faults don't need the HTTP-style
        ``retry_delay`` spacing — up to ``retry_attempts``, then delivered
        with its error (degrade-and-continue).  Retried copies get fresh
        NEGATIVE request_ids internally (the scheduler's stream requires
        unique ids) and are delivered under the original id; callers must
        use ids >= 0.
        """
        by_id: dict[int, GenerationRequest] = {}
        attempts: dict[int, int] = {}
        orig_of: dict[int, int] = {}  # retry clone id -> original id
        finals: set[int] = set()
        retry_seq = [0]

        def register(reqs: list[GenerationRequest]) -> None:
            for r in reqs:
                if r.request_id < 0:
                    raise ValueError("streaming request_ids must be >= 0")
                by_id[r.request_id] = r
                attempts[r.request_id] = 1

        register(requests)

        def wrapper(res: GenerationResult, submit) -> None:
            rid = orig_of.pop(res.request_id, res.request_id)
            self.total_requests += 1
            req = by_id.get(rid)
            if (res.error is not None and req is not None
                    and attempts[rid] < self.config.retry_attempts):
                attempts[rid] += 1
                retry_seq[0] -= 1
                clone = replace(req, request_id=retry_seq[0])
                orig_of[clone.request_id] = rid
                logger.warning("streaming retry %d/%d for request %d",
                               attempts[rid], self.config.retry_attempts, rid)
                submit([clone])
                return
            if res.error is not None:
                self.failed_requests += 1
            else:
                self.total_tokens_used += res.total_tokens
                self.total_device_seconds += res.device_seconds
            if res.request_id != rid:
                res = replace(res, request_id=rid)
            finals.add(rid)

            def submit_user(new_reqs: list[GenerationRequest]) -> None:
                register(new_reqs)
                submit(new_reqs)

            on_final(res, submit_user)

        try:
            self.engine.generate_batch(requests, on_result=wrapper)
        except Exception as e:
            # engine-level fault mid-stream: the same degrade-and-continue
            # contract run_requests enforces (every registered request gets
            # an error result; no exception escapes to the pipeline)
            logger.exception("engine stream failure")
            msg = str(e) or type(e).__name__
            for rid in [r for r in by_id if r not in finals]:
                self.total_requests += 1
                self.failed_requests += 1
                finals.add(rid)
                on_final(GenerationResult(request_id=rid, finish_reason="error",
                                          error=msg),
                         lambda new_reqs: None)

    # ------------------------------------------------------------ reporting

    def stats(self) -> dict:
        return {
            "total_tokens_used": self.total_tokens_used,
            "total_device_seconds": round(self.total_device_seconds, 4),
            "total_requests": self.total_requests,
            "failed_requests": self.failed_requests,
        }


if __name__ == "__main__":  # stage demo (pattern: llm_executor.py:460-509)
    from lmrs_tpu.data.chunker import TranscriptChunker
    from lmrs_tpu.data.preprocessor import preprocess_transcript
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.prompts import resolve_map_prompt
    from lmrs_tpu.utils.demo import load_demo_transcript

    segs = preprocess_transcript(load_demo_transcript(max_segments=400)["segments"])
    chunker = TranscriptChunker()
    chunks = chunker.postprocess_chunks(chunker.chunk_transcript(segs))[:3]
    executor = MapExecutor(MockEngine())
    executor.process_chunks(chunks, resolve_map_prompt())
    for c in chunks:
        print(f"chunk {c.chunk_index}: {c.summary[:160]}")
    print(f"stats: {executor.stats()}")
