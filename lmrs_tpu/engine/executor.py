"""Map-stage executor: fan chunks into the engine with the reference's
scheduling contract.

Successor of ``LLMExecutor.process_chunks`` (llm_executor.py:110-228).  The
reference's semantics are preserved exactly, re-based onto a local engine:

* concurrency cap        — ``asyncio.Semaphore(max_concurrent_requests)``
                           (llm_executor.py:133) becomes wave-sized batch
                           admission into the engine;
* per-chunk retry loop   — RETRY_ATTEMPTS × RETRY_DELAY
                           (llm_executor.py:198-228) becomes requeue waves;
* degrade-and-continue   — an exhausted chunk gets the inline
                           ``"[Error processing chunk: …]"`` summary + error
                           field, never an exception (llm_executor.py:219-225);
* order restoration      — results sorted by chunk_index
                           (llm_executor.py:157);
* accounting             — total_tokens_used / total_requests /
                           failed_requests counters (llm_executor.py:86-90);
                           dollar cost becomes device-seconds.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import replace
from typing import Sequence

from lmrs_tpu.config import EngineConfig
from lmrs_tpu.data.chunker import Chunk
from lmrs_tpu.engine.api import (Engine, GenerationRequest, GenerationResult,
                                 degraded_reason, remaining_budget)
from lmrs_tpu.obs import PID_PIPELINE, get_tracer
from lmrs_tpu.prompts import safe_format, shared_prefix_chars

logger = logging.getLogger("lmrs.executor")


class MapExecutor:
    """Runs the map stage (and, for the reduce tree, ad-hoc request lists)."""

    def __init__(self, engine: Engine, config: EngineConfig | None = None):
        self.engine = engine
        self.config = config or EngineConfig()
        # running totals (llm_executor.py:86-90)
        self.total_tokens_used = 0
        self.total_device_seconds = 0.0
        self.total_requests = 0
        self.failed_requests = 0
        # request ids aborted via cancel(): consulted before every retry so
        # a cancelled request is never resurrected by a retry clone, and
        # set from any thread (set.add is GIL-atomic) while a run is live.
        # RUN-SCOPED: request ids are reused across runs on one executor
        # (map chunks and reduce nodes both count from 0), so unlike the
        # scheduler's globally-unique-rid convention, a cancel here only
        # targets the run in flight — cancel() no-ops when none is (a
        # stale id must not poison a later run's same-numbered request),
        # and the set clears at run start.
        self._cancelled: set[int] = set()
        self._run_live = False
        # orders cancel()'s liveness check + add against the run-start
        # clear and run-end flag flip: without it a cancel racing a run
        # boundary could pass the check for run N and land its id in run
        # N+1's freshly-cleared set — the poisoning run-scoping exists to
        # prevent.
        self._cancel_lock = threading.Lock()
        # Engine-boundary rid epoch: the ENGINE sees caller ids offset by
        # a per-run base (run N uses [N<<20, (N+1)<<20); retry clones sit
        # just below their base).  Engines keep cancel state across run
        # boundaries by design (the scheduler's set clears at END of run,
        # assuming globally-unique batcher rids) — with raw reused caller
        # ids, a cancel forwarded as a run ends would alias an unrelated
        # same-numbered request in the next run.  Epoch ids make every
        # engine-visible id process-unique, so a stale forward can never
        # match anything.  Caller-facing ids are unchanged: results are
        # normalized back before any bookkeeping or delivery.
        self._epoch = 0
        self._rid_base = 0
        # original id -> live retry-clone ENGINE-SPACE id (streaming
        # retries), so cancel() can chase the clone currently in flight
        self._live_clone: dict[int, int] = {}
        # wakes the retry backoff early (cancel/interrupt): the wave loop
        # must never sit in an uninterruptible sleep while its requests'
        # deadlines burn down.  _interrupted makes interrupt() sticky for
        # the rest of the run (every later backoff is skipped too).
        self._wake = threading.Event()
        self._interrupted = False

    def cancel(self, request_id: int) -> None:
        """Abort ``request_id`` of the CURRENT run: the id is never
        retried again (a cancel must not be resurrected by a retry clone),
        any in-flight retry clone is chased through the engine's cancel
        hook, and a sleeping retry backoff wakes immediately.  Callable
        from any thread; unknown ids no-op, and so does a cancel with no
        run in flight — ids are reused across runs, so a stale cancel has
        no valid target.  The engine is told the EPOCH id (see __init__),
        so even a forward racing the run boundary can never alias a later
        run's request inside the engine's own cancel bookkeeping."""
        with self._cancel_lock:
            if not self._run_live:
                return
            self._cancelled.add(request_id)
            engine_rid = self._rid_base + request_id
            clone = self._live_clone.get(request_id)
        self._wake.set()
        eng_cancel = getattr(self.engine, "cancel", None)
        if eng_cancel is not None:
            eng_cancel(engine_rid)
            if clone is not None:
                eng_cancel(clone)

    def _new_epoch(self) -> int:
        """Advance to the next engine-rid epoch (run start, under the
        cancel lock by callers).  2**20 of headroom per run bounds caller
        ids; ``register`` enforces the bound on the streaming path."""
        self._rid_base = self._epoch
        self._epoch += 1 << 20
        return self._rid_base

    def interrupt(self) -> None:
        """Wake any in-progress retry backoff AND skip the remaining ones
        (shutdown paths): sticky for the current run — a one-shot wake
        would only skip the backoff in flight and then sleep out every
        later retry's full delay.  Cleared at the next run's start."""
        self._interrupted = True
        self._wake.set()

    @staticmethod
    def _cancelled_result(rid: int, res: GenerationResult) -> GenerationResult:
        """Terminal-cancel conversion — ONE rule shared by the wave loop
        and the streaming wrapper: the abandoned id reports cancelled;
        text and token accounting survive only from a completed attempt
        (real output, the keep-partial-output convention), never from a
        failure, and the error never surfaces (the caller cancelled)."""
        ok = res.error is None
        return GenerationResult(
            request_id=rid,
            text=res.text if ok else "",
            prompt_tokens=res.prompt_tokens if ok else 0,
            completion_tokens=res.completion_tokens if ok else 0,
            finish_reason="cancelled")

    def _stamp_deadlines(self, reqs: list[GenerationRequest]) -> None:
        """Apply the config-level deadline budget to requests that don't
        already carry one — the single point where EngineConfig
        .request_deadline_s enters the request stream (map chunks, reduce
        nodes, and streamed submissions all pass through here)."""
        budget = self.config.request_deadline_s
        if budget and budget > 0:
            now = time.time()
            for r in reqs:
                if r.deadline_s is None:
                    r.deadline_s = now + budget

    # ------------------------------------------------------------------ map

    def process_chunks(
        self,
        chunks: Sequence[Chunk],
        prompt_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
    ) -> list[Chunk]:
        """Summarize every chunk; returns chunks ordered by chunk_index."""
        self.process_chunk_groups([chunks], prompt_template, summary_type,
                                  system_prompt)
        return sorted(chunks, key=lambda c: c.chunk_index)  # llm_executor.py:157

    def process_chunk_groups(
        self,
        groups: Sequence[Sequence[Chunk]],
        prompt_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
    ) -> None:
        """Summarize every chunk of every group through ONE pooled request
        queue (multi-transcript batching: the engine's batch slots fill from
        all transcripts at once instead of draining per transcript).
        Summaries are written onto the chunks in place.

        Groups interleave ROUND-ROBIN into the queue (VERDICT r2 item 9):
        admission is FIFO, so appending whole groups in order would make
        transcript N's first chunk wait behind every chunk of transcripts
        0..N-1 — the pooled-queue design exists to overlap transcripts, and
        per-transcript completion skew should reflect size, not submission
        order."""
        t0 = time.time()
        requests = []
        flat: list[Chunk] = []
        queues = [list(chunks) for chunks in groups]
        while any(queues):
            for g in queues:
                if not g:
                    continue
                chunk = g.pop(0)
                requests.append(self.build_map_request(
                    chunk, prompt_template, summary_type, system_prompt,
                    request_id=len(flat)))  # pool-unique, not chunk_index
                flat.append(chunk)

        results = self.run_requests(requests)
        failed = 0
        for chunk, res in zip(flat, results):
            # degraded_reason, not res.error: shed/deadline terminals carry
            # no error but may carry no content either — an empty summary
            # must be marked, not silently aggregated as success
            reason = degraded_reason(res)
            if reason is not None:
                chunk.summary = f"[Error processing chunk: {reason}]"
                chunk.error = reason
                failed += 1
            else:
                chunk.summary = res.text
            chunk.tokens_used = res.total_tokens
            chunk.device_seconds = res.device_seconds
        tr = get_tracer()
        if tr:
            tr.complete("map_stage", t0, time.time(), pid=PID_PIPELINE,
                        args={"chunks": len(flat), "groups": len(groups),
                              "failed": failed})
        logger.info(
            "map stage: %d chunks (%d groups) in %.2fs (%d failed)",
            len(flat), len(groups), time.time() - t0, failed,
        )

    def build_map_request(
        self,
        chunk: Chunk,
        prompt_template: str,
        summary_type: str = "summary",
        system_prompt: str | None = None,
        request_id: int = 0,
    ) -> GenerationRequest:
        """One chunk → one map request — the single source of truth for how
        map prompts and generation params are assembled (used by both the
        barrier path here and reduce/streaming.py)."""
        # safe_format, not str.format: user prompt files may contain
        # literal braces (JSON examples) that str.format would choke on
        prompt = safe_format(
            prompt_template,
            transcript=chunk.text_with_context,
            summary_type=summary_type,
        )
        return GenerationRequest(
            prompt=prompt,
            request_id=request_id,
            system_prompt=chunk.system_prompt or system_prompt,
            max_new_tokens=self.config.max_tokens,
            temperature=self.config.temperature,
            seed=self.config.seed,
            # prefix-cache hint: everything before the per-chunk transcript
            # substitution is the map preamble every chunk shares
            cache_prefix=shared_prefix_chars(
                prompt_template, "transcript", summary_type=summary_type),
        )

    # ----------------------------------------------------- request plumbing

    def run_requests(self, requests: list[GenerationRequest]) -> list[GenerationResult]:
        """Admission-controlled waves + retry/requeue + accounting.

        Engines with their own admission control (continuous batching) get
        the whole queue at once — the wave cap is the semaphore analog for
        engines that lack one (mock, static), and a barrier between waves
        would leave the continuous scheduler's slots draining idle."""
        if getattr(self.engine, "schedules_internally", False):
            wave = max(1, len(requests))
        else:
            wave = max(1, self.config.max_concurrent_requests)
        for r in requests:
            # same bound register() enforces on the streaming path: an id
            # past the epoch stride would land in a later run's reserved
            # engine-id band, re-enabling the stale-cancel aliasing the
            # epoch scheme exists to prevent
            if not 0 <= r.request_id < 1 << 19:
                raise ValueError(f"request_ids must be in [0, {1 << 19}) "
                                 f"(got {r.request_id}); the engine-boundary "
                                 "epoch reserves the rest")
        self._stamp_deadlines(requests)
        done: dict[int, GenerationResult] = {}
        pending = list(requests)
        attempt = 1
        with self._cancel_lock:  # run-scoped cancel state (see __init__)
            self._cancelled.clear()
            self._live_clone.clear()
            self._new_epoch()
            self._interrupted = False
            self._run_live = True
        try:
            return self._run_waves(pending, done, attempt, wave, requests)
        finally:
            with self._cancel_lock:
                self._run_live = False

    def _run_waves(self, pending, done, attempt, wave,
                   requests) -> list[GenerationResult]:
        last_error: dict[int, str] = {}  # rid -> most recent failure
        while pending:
            # re-arm BEFORE dispatching the wave: a cancel()/interrupt()
            # landing any time after this (mid-wave or mid-backoff) leaves
            # the event set, so the backoff below returns immediately
            self._wake.clear()
            failed: list[GenerationRequest] = []
            for i in range(0, len(pending), wave):
                batch = pending[i : i + wave]
                # the engine sees epoch ids (__init__); results normalize
                # straight back to caller space before any bookkeeping
                base = self._rid_base
                ebatch = [replace(r, request_id=base + r.request_id)
                          for r in batch]
                try:
                    results = [replace(res, request_id=res.request_id - base)
                               for res in self.engine.generate_batch(ebatch)]
                except Exception as e:  # engine-level fault: fail the batch
                    logger.exception("engine batch failure")
                    results = [
                        GenerationResult(request_id=r.request_id, finish_reason="error", error=str(e))
                        for r in batch
                    ]
                for req, res in zip(batch, results):
                    self.total_requests += 1
                    if (req.request_id in self._cancelled
                            and res.finish_reason != "cancelled"):
                        # terminal cancel: a completed attempt must not
                        # resurrect an abandoned id as a success
                        done[req.request_id] = self._cancelled_result(
                            req.request_id, res)
                    elif res.error is not None:
                        last_error[req.request_id] = res.error
                        failed.append(req)
                    else:
                        done[res.request_id] = res
                        self.total_tokens_used += res.total_tokens
                        self.total_device_seconds += res.device_seconds
            if not failed:
                break
            if attempt >= self.config.retry_attempts:
                for req in failed:
                    self.failed_requests += 1
                    # root cause kept alongside the exhaustion marker (the
                    # same keep-the-failure-visible rule as the deadline
                    # clip below): triage must not have to go to the logs
                    cause = last_error.get(req.request_id)
                    done.setdefault(
                        req.request_id,
                        GenerationResult(
                            request_id=req.request_id,
                            finish_reason="error",
                            error=f"failed after {attempt} attempts"
                                  + (f": {cause}" if cause else ""),
                        ),
                    )
                break
            # Deadline-aware, interruptible backoff (the reference slept
            # RETRY_DELAY unconditionally): the wait clips to the soonest
            # failed request's remaining budget — sleeping past a deadline
            # would burn the budget the retry needs — and cancel()/
            # interrupt() wake it immediately instead of stalling the wave
            # loop.
            delay = self.config.retry_delay
            # positive budgets only: an ALREADY-expired request is dropped
            # from the retry set right below and never retried, so its
            # negative budget must not zero the backoff for the others
            rems = [r for r in (remaining_budget(q) for q in failed)
                    if r is not None and r > 0]
            if rems:
                delay = max(0.0, min(delay, min(rems)))
            logger.warning(
                "retrying %d failed requests (attempt %d/%d) after %.1fs",
                len(failed), attempt + 1, self.config.retry_attempts, delay,
            )
            if delay and not self._interrupted:
                self._wake.wait(delay)
            # clip the retry set: cancelled ids must not resurrect, and a
            # request whose budget is gone finishes as "deadline" now —
            # a retry could not complete in time anyway
            now = time.time()
            pending = []
            for req in failed:
                rid = req.request_id
                if rid in self._cancelled:
                    done.setdefault(rid, GenerationResult(
                        request_id=rid, finish_reason="cancelled"))
                elif req.deadline_s is not None and req.deadline_s <= now:
                    self.failed_requests += 1
                    # the root-cause failure stays visible (api.py
                    # contract; the streaming clip preserves it too) —
                    # finish_reason already says the budget ran out
                    done.setdefault(rid, GenerationResult(
                        request_id=rid, finish_reason="deadline",
                        error=last_error.get(
                            rid, "deadline exceeded before retry")))
                else:
                    pending.append(req)
            attempt += 1
        return [done[r.request_id] for r in requests]

    def run_requests_streaming(self, requests: list[GenerationRequest],
                               on_final) -> None:
        """Streaming analog of ``run_requests``: one engine stream, results
        delivered through ``on_final(result, submit)`` as they complete, and
        ``submit(more)`` feeds new requests into the SAME stream (the
        map→reduce overlap hook).

        Retries: a failed request is resubmitted into the stream
        immediately — device faults don't need the HTTP-style
        ``retry_delay`` spacing — up to ``retry_attempts``, then delivered
        with its error (degrade-and-continue).  Retried copies get fresh
        ids just below the run's engine-rid epoch base (the scheduler's
        stream requires unique ids; the epoch keeps them unique across
        runs too) and are delivered under the original id; callers must
        use ids in [0, 2**19).
        """
        by_id: dict[int, GenerationRequest] = {}  # CALLER-space throughout
        attempts: dict[int, int] = {}
        orig_of: dict[int, int] = {}  # engine-space clone id -> caller id
        finals: set[int] = set()
        retry_seq = [0]
        with self._cancel_lock:  # run-scoped cancel state (see __init__)
            self._cancelled.clear()
            self._live_clone.clear()
            base = self._new_epoch()  # engine sees base-offset ids
            self._interrupted = False
            self._run_live = True

        def register(reqs: list[GenerationRequest]) -> None:
            self._stamp_deadlines(reqs)
            for r in reqs:
                if not 0 <= r.request_id < 1 << 19:
                    raise ValueError("streaming request_ids must be in "
                                     f"[0, {1 << 19}) (got {r.request_id}); "
                                     "the engine-boundary epoch reserves "
                                     "the rest")
                by_id[r.request_id] = r
                attempts[r.request_id] = 1

        def to_engine(reqs: list[GenerationRequest]) -> list[GenerationRequest]:
            return [replace(r, request_id=base + r.request_id) for r in reqs]

        register(requests)

        def wrapper(res: GenerationResult, submit) -> None:
            rid = orig_of.pop(res.request_id, None)
            if rid is not None:  # a retry clone came home
                self._live_clone.pop(rid, None)
            else:
                rid = res.request_id - base
            self.total_requests += 1
            req = by_id.get(rid)
            # Retry gate: cancelled ids must never be resurrected by a
            # retry clone (the cancel-vs-retry race), and a request whose
            # deadline budget is gone is delivered now — the clone could
            # not finish in time.
            cancelled = rid in self._cancelled
            expired = (req is not None and req.deadline_s is not None
                       and req.deadline_s <= time.time())
            if (res.error is not None and req is not None
                    and not cancelled and not expired
                    and attempts[rid] < self.config.retry_attempts):
                attempts[rid] += 1
                retry_seq[0] -= 1
                # clone ids sit just below this run's epoch base: unique
                # within the run (scheduler stream requirement) AND across
                # runs (stale engine-side cancels can never alias them)
                clone = replace(req, request_id=base + retry_seq[0])
                orig_of[clone.request_id] = rid
                self._live_clone[rid] = clone.request_id
                logger.warning("streaming retry %d/%d for request %d",
                               attempts[rid], self.config.retry_attempts, rid)
                submit([clone])
                return
            if cancelled and res.finish_reason != "cancelled":
                # a recorded cancel is TERMINAL at this layer: even an
                # attempt (or retry clone) that completed — the engine may
                # lack a cancel hook, or the cancel raced the completion —
                # must not come back as a normal success for an id its
                # caller abandoned
                res = self._cancelled_result(rid, res)
            elif (res.error is not None and expired and req is not None
                    and attempts[rid] < self.config.retry_attempts):
                # the retry was blocked by the expired budget alone: the
                # same clip as run_requests — a deadline outcome with the
                # underlying failure preserved
                res = replace(res, request_id=rid, finish_reason="deadline")
            if res.error is not None:
                self.failed_requests += 1
            else:
                self.total_tokens_used += res.total_tokens
                self.total_device_seconds += res.device_seconds
            if res.request_id != rid:  # engine/clone space -> caller space
                res = replace(res, request_id=rid)
            finals.add(rid)

            def submit_user(new_reqs: list[GenerationRequest]) -> None:
                register(new_reqs)
                submit(to_engine(new_reqs))

            on_final(res, submit_user)

        try:
            self.engine.generate_batch(to_engine(requests), on_result=wrapper)
        except Exception as e:  # noqa: BLE001 - degrade-and-continue below
            # engine-level fault mid-stream: the same degrade-and-continue
            # contract run_requests enforces (every registered request gets
            # an error result; no exception escapes to the pipeline)
            logger.exception("engine stream failure")
            msg = str(e) or type(e).__name__
            for rid in [r for r in by_id if r not in finals]:
                self.total_requests += 1
                self.failed_requests += 1
                finals.add(rid)
                on_final(GenerationResult(request_id=rid, finish_reason="error",
                                          error=msg),
                         lambda new_reqs: None)
        else:
            # a WEDGED engine (watchdog, docs/ROBUSTNESS.md § Hang
            # survival) returns synthesized terminals whose retry clones
            # the dead run can no longer accept — wrapper's submit
            # dropped them, leaving their rids without a final.  The
            # stream must never end with a silent hole: deliver the
            # exhaustion now (degrade-and-continue, same contract as the
            # except branch).
            for rid in [r for r in by_id if r not in finals]:
                self.total_requests += 1
                self.failed_requests += 1
                finals.add(rid)
                on_final(GenerationResult(
                    request_id=rid, finish_reason="error",
                    error="engine stream ended before a retry could run "
                          "(wedged/degraded engine)"),
                    lambda new_reqs: None)
        finally:
            with self._cancel_lock:
                self._run_live = False

    # ------------------------------------------------------------ reporting

    def stats(self) -> dict:
        return {
            "total_tokens_used": self.total_tokens_used,
            "total_device_seconds": round(self.total_device_seconds, 4),
            "total_requests": self.total_requests,
            "failed_requests": self.failed_requests,
        }


if __name__ == "__main__":  # stage demo (pattern: llm_executor.py:460-509)
    from lmrs_tpu.data.chunker import TranscriptChunker
    from lmrs_tpu.data.preprocessor import preprocess_transcript
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.prompts import resolve_map_prompt
    from lmrs_tpu.utils.demo import load_demo_transcript

    segs = preprocess_transcript(load_demo_transcript(max_segments=400)["segments"])
    chunker = TranscriptChunker()
    chunks = chunker.postprocess_chunks(chunker.chunk_transcript(segs))[:3]
    executor = MapExecutor(MockEngine())
    executor.process_chunks(chunks, resolve_map_prompt())
    for c in chunks:
        print(f"chunk {c.chunk_index}: {c.summary[:160]}")
    print(f"stats: {executor.stats()}")
