"""Data-parallel serving: independent engine replicas over disjoint devices.

SURVEY.md §2.2 row 1: the TPU-native equivalent of the reference's
request-level fan-out is "continuous batching over DP replicas of the
model".  Sharding decode's batch dim over a ``dp`` mesh axis would be the
literal translation, but a paged KV cache has no meaningful batch axis to
shard — the page pool and the host-side allocator are per-engine state.
The TPU-idiomatic design is N fully independent engines, each with its own
(tp × sp) sub-mesh, pool, and scheduler, fed round-robin from one queue:

* within a replica: ICI collectives (TP) + continuous batching;
* across replicas: no communication at all — pure throughput scaling,
  exactly like the reference's concurrent HTTP requests but device-local;
* across hosts: run one process per host (`jax.distributed`,
  parallel/mesh.py:initialize_distributed) and give each host's engine its
  local devices — the same class, DCN never carries tensor traffic.

Host-side dispatch runs one thread per replica (device execution is async
and overlaps; the GIL only serializes Python-side batch assembly).
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace

import jax

from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, GenerationResult

logger = logging.getLogger("lmrs.replicated")


class ReplicatedEngine:
    """dp independent JaxEngines over disjoint device subsets."""

    schedules_internally = True  # each replica admission-controls itself

    def __init__(
        self,
        engine_cfg: EngineConfig,
        model_cfg: ModelConfig,
        mesh_cfg: MeshConfig,
        devices=None,
    ):
        from lmrs_tpu.engine.jax_engine import JaxEngine

        devices = list(devices) if devices is not None else jax.devices()
        dp = mesh_cfg.dp
        per = mesh_cfg.n_devices // dp  # tp*sp*ep*pp per replica
        if dp < 2:
            raise ValueError("ReplicatedEngine needs mesh dp >= 2")
        if dp * per > len(devices):
            raise ValueError(
                f"mesh {mesh_cfg} needs {dp * per} devices, "
                f"have {len(devices)}")
        sub_cfg = replace(mesh_cfg, dp=1)

        # Load/init (and quantize) the weights ONCE on host; every replica
        # device_puts the same tree onto its own sub-mesh — dp identical
        # checkpoint reads would serialize startup on disk I/O.
        if engine_cfg.checkpoint_path:
            from lmrs_tpu.models.loader import load_checkpoint

            shared = load_checkpoint(engine_cfg.checkpoint_path, model_cfg)
        else:
            from lmrs_tpu.models.transformer import init_params

            logger.warning("no checkpoint for %s: replicas share random-init "
                           "weights", model_cfg.name)
            shared = init_params(model_cfg, jax.random.PRNGKey(engine_cfg.seed))
        if engine_cfg.quantize:
            from lmrs_tpu.ops.quant import quantize_params

            shared = quantize_params(shared)

        self._pool = ThreadPoolExecutor(max_workers=dp,
                                        thread_name_prefix="lmrs-dp")

        def build(i: int) -> JaxEngine:
            # per-replica sampling seed: identical weights, decorrelated
            # sampling streams (same prompt on two replicas must not emit
            # identical tokens at temperature > 0)
            cfg_i = replace(engine_cfg, seed=engine_cfg.seed + i,
                            checkpoint_path=None, quantize=None)
            return JaxEngine(cfg_i, model_cfg, sub_cfg, params=shared,
                             devices=devices[i * per: (i + 1) * per])

        self.replicas = list(self._pool.map(build, range(dp)))
        logger.info("replicated engine: dp=%d replicas x %d device(s)", dp, per)

    # ------------------------------------------------------------------ API

    def generate_batch(self, requests: list[GenerationRequest]) -> list[GenerationResult]:
        dp = len(self.replicas)
        # round-robin keeps shard sizes balanced for any request count
        shards: list[list[tuple[int, GenerationRequest]]] = [[] for _ in range(dp)]
        for i, req in enumerate(requests):
            shards[i % dp].append((i, req))

        def run(replica, shard):
            return replica.generate_batch([req for _, req in shard])

        futures = [
            (shard, self._pool.submit(run, replica, shard))
            for replica, shard in zip(self.replicas, shards) if shard
        ]
        out: list[GenerationResult | None] = [None] * len(requests)
        for shard, fut in futures:
            try:
                results = fut.result()
            except Exception as e:  # degrade-and-continue per replica
                logger.exception("replica batch failure")
                results = [
                    GenerationResult(request_id=req.request_id,
                                     finish_reason="error", error=str(e))
                    for _, req in shard
                ]
            for (pos, _), res in zip(shard, results):
                out[pos] = res
        return [r for r in out if r is not None]

    def shutdown(self) -> None:
        for replica in self.replicas:
            replica.shutdown()
        self._pool.shutdown(wait=False)

    def engine_metrics(self) -> dict:
        """Fleet metrics in the same shape as one scheduler's report
        (engine/scheduler.py:metrics_report) so downstream consumers — the
        pipeline stats banner, /metrics — need no replica-awareness."""
        per = [r.engine_metrics() for r in self.replicas]
        per = [m for m in per if m]
        if not per:
            return {}
        # replicas run concurrently: aggregate rate = total work / the
        # longest replica's scheduler time
        secs = max((m.get("scheduler_seconds", 0.0) for m in per), default=0.0)
        prefill = sum(m.get("prefill_tokens", 0) for m in per)
        decode = sum(m.get("decode_tokens", 0) for m in per)
        return {
            "replicas": len(per),
            "prefill_tokens": prefill,
            "decode_tokens": decode,
            "prefill_tokens_per_sec": round(prefill / max(secs, 1e-9), 1),
            "decode_tokens_per_sec": round(decode / max(secs, 1e-9), 1),
            "mean_decode_occupancy": round(
                sum(m.get("mean_decode_occupancy", 0.0) for m in per) / len(per), 3),
            "peak_kv_page_utilization": max(
                m.get("peak_kv_page_utilization", 0.0) for m in per),
            "scheduler_seconds": round(secs, 3),
            "per_replica": per,
        }
