"""Data-parallel serving: independent engine replicas over disjoint devices.

SURVEY.md §2.2 row 1: the TPU-native equivalent of the reference's
request-level fan-out is "continuous batching over DP replicas of the
model".  Sharding decode's batch dim over a ``dp`` mesh axis would be the
literal translation, but a paged KV cache has no meaningful batch axis to
shard — the page pool and the host-side allocator are per-engine state.
The TPU-idiomatic design is N fully independent engines, each with its own
(tp × sp) sub-mesh, pool, and scheduler, fed round-robin from one queue:

* within a replica: ICI collectives (TP) + continuous batching;
* across replicas: no communication at all — pure throughput scaling,
  exactly like the reference's concurrent HTTP requests but device-local;
* across hosts: run one process per host (`jax.distributed`,
  parallel/mesh.py:initialize_distributed) and give each host's engine its
  local devices — the same class, DCN never carries tensor traffic.

Host-side dispatch runs one thread per replica (device execution is async
and overlaps; the GIL only serializes Python-side batch assembly).
"""

from __future__ import annotations

import logging
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import replace

import jax

from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
from lmrs_tpu.engine.jax_engine import needs_host_quant_init
from lmrs_tpu.engine.watchdog import DaemonExecutor
from lmrs_tpu.testing import faults
from lmrs_tpu.utils.env import env_bool, env_float

logger = logging.getLogger("lmrs.replicated")


class ReplicatedEngine:
    """dp independent JaxEngines over disjoint device subsets."""

    schedules_internally = True  # each replica admission-controls itself

    def __init__(
        self,
        engine_cfg: EngineConfig,
        model_cfg: ModelConfig,
        mesh_cfg: MeshConfig,
        devices=None,
    ):
        from lmrs_tpu.engine.jax_engine import JaxEngine

        devices = list(devices) if devices is not None else jax.devices()
        dp = mesh_cfg.dp
        per = mesh_cfg.n_devices // dp  # tp*sp*ep*pp per replica
        if dp < 2:
            raise ValueError("ReplicatedEngine needs mesh dp >= 2")
        if dp * per > len(devices):
            raise ValueError(
                f"mesh {mesh_cfg} needs {dp * per} devices, "
                f"have {len(devices)}")
        sub_cfg = replace(mesh_cfg, dp=1)

        # Load/init (and quantize) the weights ONCE on host; every replica
        # device_puts the same tree onto its own sub-mesh — dp identical
        # checkpoint reads would serialize startup on disk I/O.
        if engine_cfg.checkpoint_path:
            from lmrs_tpu.models.loader import load_checkpoint

            shared = load_checkpoint(engine_cfg.checkpoint_path, model_cfg)
        elif needs_host_quant_init(model_cfg, engine_cfg.quantize):
            # quantized random init builds the int8 tree host-side (numpy)
            # without ever materializing the full-precision tree — at 8B
            # shape that tree would OOM the default device, and under the
            # axon tunnel there is no jax CPU backend to stage it on.
            # SHARED gate with JaxEngine (needs_host_quant_init): small
            # quantized models keep the device init so the random-weight
            # workload matches the single-engine path exactly
            # (replica-vs-single comparability)
            from lmrs_tpu.ops.quant import random_quantized_init

            logger.warning("no checkpoint for %s: replicas share random-init "
                           "weights", model_cfg.name)
            shared = random_quantized_init(model_cfg, engine_cfg.seed)
        else:
            from lmrs_tpu.models.transformer import init_params

            logger.warning("no checkpoint for %s: replicas share random-init "
                           "weights", model_cfg.name)
            shared = init_params(model_cfg, jax.random.PRNGKey(engine_cfg.seed))
            if engine_cfg.quantize:
                from lmrs_tpu.ops.quant import quantize_params

                shared = quantize_params(shared)
        if engine_cfg.quantize and engine_cfg.checkpoint_path:
            from lmrs_tpu.ops.quant import quantize_params

            shared = quantize_params(shared)

        # ONE single-worker executor PER replica: a replica's scheduler is
        # not thread-safe, so everything aimed at it — construction, user
        # shards, health probes — funnels through its own queue and can
        # never run concurrently, while distinct replicas run in parallel.
        # DAEMON workers (engine/watchdog.py): a wedged shard or probe
        # future must never pin interpreter exit, and a quarantined
        # replica's stuck pool can simply be abandoned and replaced.
        self._pools = [DaemonExecutor(thread_name=f"lmrs-dp{i}")
                       for i in range(dp)]

        def build(i: int) -> JaxEngine:
            # per-replica sampling seed: identical weights, decorrelated
            # sampling streams (same prompt on two replicas must not emit
            # identical tokens at temperature > 0)
            cfg_i = replace(engine_cfg, seed=engine_cfg.seed + i,
                            checkpoint_path=None, quantize=None)
            return JaxEngine(cfg_i, model_cfg, sub_cfg, params=shared,
                             devices=devices[i * per: (i + 1) * per])

        self.replicas = [
            fut.result() for fut in
            [self._pools[i].submit(build, i) for i in range(dp)]
        ]
        # failure detection / elastic recovery (SURVEY.md §5.3): a replica
        # whose batch raises is marked unhealthy and routed around, so the
        # executor's retry of the failed requests lands on live replicas
        # instead of round-robining back onto the dead one.  Unhealthy
        # replicas get a tiny SYNTHETIC probe each wave (never user
        # traffic); a probe that completes re-admits the replica.  Probing
        # also bounds the poison-request case — a request that
        # deterministically crashes its batch marks replicas unhealthy as
        # it burns retries, but the probes (which are not the poison)
        # revive them right after.
        self._healthy = [True] * dp
        self._probes: dict[int, object] = {}  # replica idx -> Future
        logger.info("replicated engine: dp=%d replicas x %d device(s)", dp, per)

    # ------------------------------------------------------------------ API

    def _reap_probes(self) -> None:
        for ri in list(self._probes):
            fut = self._probes[ri]
            if not fut.done():
                continue
            del self._probes[ri]
            # cancelled() FIRST: a probe queued behind a quarantined
            # shard is cancelled by the pool teardown, and exception()
            # on a cancelled future RAISES CancelledError (a
            # BaseException no degrade path catches) instead of
            # returning it
            if fut.cancelled() or fut.exception() is not None:
                results = None
            else:
                results = fut.result()
            # a degraded (wedged) engine fail-fasts its probe as a RESULT
            # carrying an error, not an exception — both mean "still down"
            ok = results is not None and all(r.error is None
                                             for r in results)
            if ok:
                self._healthy[ri] = True
                logger.info("replica %d probe succeeded: re-admitted", ri)
            else:
                logger.warning("replica %d probe failed: still unhealthy", ri)

    def _launch_probes(self) -> None:
        for ri, ok in enumerate(self._healthy):
            if not ok and ri not in self._probes:
                probe = GenerationRequest(prompt="health probe",
                                          request_id=-1, max_new_tokens=1)
                self._probes[ri] = self._pools[ri].submit(
                    self.replicas[ri].generate_batch, [probe])

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None, on_tokens=None) -> list[GenerationResult]:
        # on_tokens fans in from every replica's worker thread CONCURRENTLY —
        # callers must pass a thread-safe callback (the HTTP server's
        # per-job queues are; a bare list append is not)
        if on_result is not None:
            # replicas have no cross-replica mid-run hook: deliver per wave
            # and loop on callback submissions (engine/api.py)
            from lmrs_tpu.engine.api import drain_with_callback

            return drain_with_callback(
                lambda reqs: self._generate_wave(reqs, on_tokens=on_tokens),
                requests, on_result)
        return self._generate_wave(requests, on_tokens=on_tokens)

    def _shard_timeout_s(self) -> float | None:
        """Per-shard bound on the wave wait (straggler containment).
        None (untimed — the pre-watchdog behavior) when the hang-survival
        tier is killed via ``LMRS_WATCHDOG=0``; the timeout may only be
        armed WITH the member engines' watchdogs, whose fail-fast runner
        is what makes submitting to a quarantined replica's fresh pool
        safe (the abandoned worker can still be inside generate_batch —
        the runner refuses to touch the wedged scheduler concurrently)."""
        if not env_bool("LMRS_WATCHDOG", True):
            return None
        return env_float("LMRS_SHARD_TIMEOUT_S", 600.0, lo=1.0)

    def _shard_wait_s(self, ri: int, timeout: float | None) -> float | None:
        """Effective wait bound for one replica's shard: a member engine
        that has never completed a warm step (no step-time EMA yet) is
        still COLD-compiling, and a first-dispatch XLA compile can
        legitimately outlast LMRS_SHARD_TIMEOUT_S — extend to the
        watchdog's compile grace instead of quarantining healthy
        hardware mid-compile (the member watchdog itself graces compiles
        the same way)."""
        if timeout is None:
            return None
        wd = getattr(getattr(self.replicas[ri], "_scheduler", None),
                     "watchdog", None)
        if wd is not None and wd.ema_step_s is None:
            from lmrs_tpu.engine.watchdog import COLD_COMPILE_GRACE_S

            return max(timeout, COLD_COMPILE_GRACE_S)
        return timeout

    def _quarantine(self, ri: int, why: str) -> None:
        """A shard wedged: mark the replica unhealthy and ABANDON its
        worker pool (daemon thread — it can never pin interpreter exit).
        The fresh pool keeps probes and later waves from queueing behind
        the stuck call; re-admission goes through the existing probe
        loop once the replica answers again."""
        logger.error("replica %d quarantined: %s", ri, why)
        self._healthy[ri] = False
        self._pools[ri].shutdown(wait=False, cancel_futures=True)
        self._pools[ri] = DaemonExecutor(thread_name=f"lmrs-dp{ri}r")

    def _run_shard(self, replica, shard, on_tokens):
        # injection site (hang survival): a "stall" plan here wedges this
        # shard's worker thread the way a hung replica chip would —
        # exercising the bounded wait + quarantine + re-dispatch path;
        # "raise" takes the existing replica-fault path
        faults.fire("replicated.shard")
        return replica.generate_batch([req for _, req in shard],
                                      on_tokens=on_tokens)

    def _generate_wave(self, requests: list[GenerationRequest],
                       on_tokens=None) -> list[GenerationResult]:
        # route over healthy replicas only; if every replica is marked dead,
        # optimistically try them all again (a transient fault should not
        # permanently brick the fleet)
        self._reap_probes()
        targets = [i for i, ok in enumerate(self._healthy) if ok]
        if not targets:
            logger.warning("all %d replicas marked unhealthy; retrying all",
                           len(self.replicas))
            targets = list(range(len(self.replicas)))
        # round-robin keeps shard sizes balanced for any request count
        shards: list[list[tuple[int, GenerationRequest]]] = [[] for _ in targets]
        for i, req in enumerate(requests):
            shards[i % len(targets)].append((i, req))

        futures = [
            (ri, shard, self._pools[ri].submit(self._run_shard,
                                               self.replicas[ri], shard,
                                               on_tokens))
            for ri, shard in zip(targets, shards) if shard
        ]
        self._launch_probes()  # concurrent with the wave, on unhealthy replicas
        out: list[GenerationResult | None] = [None] * len(requests)
        timeout = self._shard_timeout_s()
        # straggler containment: shard entries whose replica wedged (stuck
        # future OR watchdog-wedged results), re-dispatched below onto the
        # replicas that survived this wave — greedy outputs are
        # replica-invariant (identical weights), so the re-dispatch is
        # token-identical to a healthy first placement
        redispatch: list[tuple[int, GenerationRequest]] = []
        survivors: list[int] = []
        for ri, shard, fut in futures:
            wait_s = self._shard_wait_s(ri, timeout)
            try:
                # bounded wait (timeout=None restores the untimed
                # pre-watchdog wait; cold-compiling members get the
                # compile grace): a shard that WEDGES inside a device
                # call is abandoned with its daemon worker — quarantined,
                # its requests re-dispatched — instead of stalling the
                # whole wave forever
                results = fut.result(timeout=wait_s)
            except FutureTimeout:
                self._quarantine(
                    ri, f"shard produced no result within {wait_s:.1f}s "
                        f"({len(shard)} request(s) re-dispatched)")
                redispatch.extend(shard)
                continue
            except Exception as e:  # degrade-and-continue per replica
                logger.exception("replica %d batch failure: marked unhealthy", ri)
                self._healthy[ri] = False
                for (pos, _), res in zip(shard, [
                    GenerationResult(request_id=req.request_id,
                                     finish_reason="error",
                                     error=str(e) or type(e).__name__)
                        for _, req in shard]):
                    out[pos] = res
                continue
            # the member engine's own watchdog may have declared the wedge
            # first (fail-fast wedged results instead of a stuck future):
            # same containment — route the wedged requests elsewhere
            wedged = [ent for ent, res in zip(shard, results)
                      if res.finish_reason == "wedged"]
            if wedged:
                self._healthy[ri] = False
                logger.warning("replica %d returned %d wedged result(s): "
                               "re-dispatching to healthy replicas",
                               ri, len(wedged))
                redispatch.extend(wedged)
                for ent, res in zip(shard, results):
                    if res.finish_reason != "wedged":
                        out[ent[0]] = res
                continue
            self._healthy[ri] = True
            survivors.append(ri)
            for (pos, _), res in zip(shard, results):
                out[pos] = res
        if redispatch:
            self._redispatch(redispatch, survivors, out, on_tokens, timeout)
        return [r for r in out if r is not None]

    def _redispatch(self, entries, survivors, out, on_tokens,
                    timeout) -> None:
        """One containment retry wave: the wedged shards' requests run on
        the replicas that answered this wave (all currently-healthy ones
        when none did).  A request that wedges or fails AGAIN terminates
        wedged/error — the executor's retry budget owns anything
        further."""
        targets = survivors or [i for i, ok in enumerate(self._healthy)
                                if ok] or list(range(len(self.replicas)))
        shards: list[list[tuple[int, GenerationRequest]]] = [
            [] for _ in targets]
        for k, ent in enumerate(entries):
            shards[k % len(targets)].append(ent)
        futures = [
            (ri, shard, self._pools[ri].submit(self._run_shard,
                                               self.replicas[ri], shard,
                                               on_tokens))
            for ri, shard in zip(targets, shards) if shard
        ]
        for ri, shard, fut in futures:
            wait_s = self._shard_wait_s(ri, timeout)
            try:
                results = fut.result(timeout=wait_s)
            except FutureTimeout:
                self._quarantine(
                    ri, f"re-dispatched shard wedged again within "
                        f"{wait_s:.1f}s")
                results = [
                    GenerationResult(request_id=req.request_id,
                                     finish_reason="wedged",
                                     error="re-dispatched shard wedged "
                                           "again")
                    for _, req in shard
                ]
            except Exception as e:  # noqa: BLE001 - degrade per replica
                logger.exception("replica %d re-dispatch failure", ri)
                self._healthy[ri] = False
                results = [
                    GenerationResult(request_id=req.request_id,
                                     finish_reason="error",
                                     error=str(e) or type(e).__name__)
                    for _, req in shard
                ]
            for (pos, _), res in zip(shard, results):
                out[pos] = res

    def cancel(self, request_id: int) -> None:
        """Engine optional abort hook: forward to every replica — request
        ids are unique across the wave (shards keep the caller's ids) and
        unknown ids are a no-op per the contract, so broadcasting is
        sufficient and race-free (scheduler.cancel is thread-safe)."""
        for replica in self.replicas:
            replica.cancel(request_id)

    def shutdown(self) -> None:
        for replica in self.replicas:
            replica.shutdown()
        for pool in self._pools:
            # daemon workers (DaemonExecutor): even a wedged shard or
            # probe future can never pin interpreter exit; cancel_futures
            # just drops anything still queued
            pool.shutdown(wait=False, cancel_futures=True)

    def usage_report(self) -> dict:
        """Optional Engine hook: per-tenant ledger rollups merged across
        replicas (obs.merge_usage — the one merge rule, so fleet totals
        equal the sum of replica totals exactly)."""
        from lmrs_tpu.obs.ledger import merge_usage, totals_from_tenants

        tenants: dict[str, dict] = {}
        enabled = False
        for r in self.replicas:
            hook = getattr(r, "usage_report", None)
            doc = hook() if hook is not None else {}
            enabled = enabled or bool(doc.get("enabled"))
            for t, roll in (doc.get("tenants") or {}).items():
                merge_usage(tenants.setdefault(t, {}), roll)
        return {"object": "usage", "enabled": enabled, "tenants": tenants,
                "totals": totals_from_tenants(tenants)}

    def anatomy_report(self) -> dict:
        """Optional Engine hook: replica anatomy documents merged with the
        one merge rule (obs.merge_anatomy) — additive totals sum exactly,
        per-class percentiles are iteration-weighted estimates."""
        from lmrs_tpu.obs.anatomy import merge_anatomy

        docs = []
        for r in self.replicas:
            hook = getattr(r, "anatomy_report", None)
            if hook is not None:
                docs.append(hook())
        return merge_anatomy(docs)

    def slo_report(self) -> dict:
        """Optional Engine hook: the replicated engine's health is the
        WORST replica's SLO state (one degraded shard degrades the
        host's placement score — the router cannot address replicas
        individually)."""
        from lmrs_tpu.obs.slo import state_rank

        docs = []
        for r in self.replicas:
            hook = getattr(r, "slo_report", None)
            if hook is not None:
                docs.append(hook())
        live = [d for d in docs if d.get("enabled")]
        if not live:
            return {"enabled": False, "state": "ok", "specs": {}}
        worst = max(live, key=lambda d: state_rank(d.get("state")))
        return {**worst, "replicas": len(live)}

    def engine_metrics(self) -> dict:
        """Fleet metrics in the same shape as one scheduler's report
        (engine/scheduler.py:metrics_report) so downstream consumers — the
        pipeline stats banner, /metrics — need no replica-awareness."""
        per = [r.engine_metrics() for r in self.replicas]
        per = [m for m in per if m]
        if not per:
            return {}
        # replicas run concurrently: aggregate rate = total work / the
        # longest replica's scheduler time
        secs = max((m.get("scheduler_seconds", 0.0) for m in per), default=0.0)
        prefill = sum(m.get("prefill_tokens", 0) for m in per)
        decode = sum(m.get("decode_tokens", 0) for m in per)
        # mixed-batch fleet view: per-replica fused dispatchers compile
        # their own bucketed mixed shapes; the fleet block sums their
        # work and averages budget fill (same shape as one scheduler's
        # mixed_batch block, minus the per-replica knobs)
        mixed = [m.get("mixed_batch") for m in per]
        mixed = [b for b in mixed if b]
        mixed_block = {}
        if mixed:
            disp = sum(b.get("dispatches", 0) for b in mixed)
            mixed_block = {"mixed_batch": {
                "enabled": any(b.get("enabled") for b in mixed),
                "dispatches": disp,
                "fill_ratio": round(
                    sum(b.get("fill_ratio", 0.0) * b.get("dispatches", 0)
                        for b in mixed) / disp, 3) if disp else 0.0,
                "prefill_tokens_piggybacked": sum(
                    b.get("prefill_tokens_piggybacked", 0) for b in mixed),
            }}
        # ragged-span fleet view (ISSUE 16): same summing shape; compile
        # shapes ADD across replicas — each compiles its own span family
        rpa = [b for b in (m.get("rpa") for m in per) if b]
        rpa_block = {}
        if rpa:
            rpa_block = {"rpa": {
                "enabled": any(b.get("enabled") for b in rpa),
                "dispatches": sum(b.get("dispatches", 0) for b in rpa),
                "span_tokens": sum(b.get("span_tokens", 0) for b in rpa),
                "compile_shapes": sum(
                    b.get("compile_shapes", 0) for b in rpa),
            }}
        return {
            "replicas": len(per),
            "healthy_replicas": sum(self._healthy),
            "prefill_tokens": prefill,
            "decode_tokens": decode,
            **mixed_block,
            **rpa_block,
            "prefill_tokens_per_sec": round(prefill / max(secs, 1e-9), 1),
            "decode_tokens_per_sec": round(decode / max(secs, 1e-9), 1),
            "mean_decode_occupancy": round(
                sum(m.get("mean_decode_occupancy", 0.0) for m in per) / len(per), 3),
            "peak_kv_page_utilization": max(
                m.get("peak_kv_page_utilization", 0.0) for m in per),
            "scheduler_seconds": round(secs, 3),
            "per_replica": per,
        }
